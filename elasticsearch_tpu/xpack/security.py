"""Security: authentication (native users, API keys), RBAC authorization,
document- and field-level security.

ref: x-pack/plugin/security — AuthenticationService (realm chain),
AuthorizationService (role resolution → cluster/index privilege checks),
ApiKeyService, and the DLS/FLS reader wrappers in x-pack core
(accesscontrol/DocumentSubsetReader.java, FieldSubsetReader.java,
SecurityIndexReaderWrapper.java).

TPU orientation: DLS is enforced the way the reference's sparse-bitset
scoring path works (ContextIndexSearcher.java:219-231 intersects a role
filter bitset with the query scorer) — the role's DLS query is compiled
into the query plan as an ANDed filter clause, which on device is one more
mask tensor intersect fused into the scoring kernel. FLS filters the
fetched _source columns host-side.

Passwords hash with PBKDF2-HMAC-SHA256 (the reference defaults to bcrypt;
PBKDF2 is its FIPS-mode hasher, available in the stdlib).
"""

from __future__ import annotations

import base64
import fnmatch
import hashlib
import hmac
import json
import os
import secrets
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuException,
    IllegalArgumentException,
    ResourceNotFoundException,
)


class SecurityException(ElasticsearchTpuException):
    status = 403


class AuthenticationException(ElasticsearchTpuException):
    status = 401


# cluster privileges (subset of the reference's ClusterPrivilegeResolver)
CLUSTER_PRIVILEGES = {
    "all", "monitor", "manage", "manage_security", "manage_ilm", "manage_slm",
    "manage_index_templates", "manage_ingest_pipelines", "manage_ml",
    "manage_transform", "manage_watcher", "manage_ccr", "manage_enrich",
    "manage_rollup", "read_ccr", "transport_client", "manage_api_key",
    "manage_token", "delegate_pki",
}

# index privileges (ref: IndexPrivilege)
INDEX_PRIVILEGES = {
    "all", "read", "write", "index", "create", "delete", "create_index",
    "delete_index", "manage", "monitor", "view_index_metadata",
    "read_cross_cluster", "maintenance", "manage_ilm",
}

# privilege implication map: holding the key implies the values
_CLUSTER_IMPLIES = {"all": CLUSTER_PRIVILEGES,
                    "manage": {"monitor", "manage_index_templates",
                               "manage_ingest_pipelines", "manage_ilm",
                               "manage_slm", "manage_rollup",
                               "manage_transform", "manage_enrich",
                               "manage_watcher"}}
_INDEX_IMPLIES = {
    "all": INDEX_PRIVILEGES,
    "write": {"index", "create", "delete"},
    "manage": {"create_index", "delete_index", "view_index_metadata",
               "monitor", "maintenance", "manage_ilm"},
    "read": set(), "monitor": set(),
}


def _hash_password(password: str, salt: Optional[bytes] = None) -> str:
    salt = salt or os.urandom(16)
    dk = hashlib.pbkdf2_hmac("sha256", password.encode(), salt, 10_000)
    return f"{salt.hex()}${dk.hex()}"


def _verify_password(password: str, stored: str) -> bool:
    try:
        salt_hex, dk_hex = stored.split("$")
    except ValueError:
        return False
    dk = hashlib.pbkdf2_hmac("sha256", password.encode(),
                             bytes.fromhex(salt_hex), 10_000)
    return secrets.compare_digest(dk.hex(), dk_hex)


# ---------------------------------------------------------------------------
# X.509 subject extraction (minimal DER walker — enough to read the
# subject DN/CN out of certificates for the PKI realm; ref:
# x-pack/plugin/security/.../pki/PkiRealm.java reads the TLS peer chain)
# ---------------------------------------------------------------------------

def _der_read(data: bytes, off: int):
    """One TLV: returns (tag, content_start, content_end, next_off)."""
    tag = data[off]
    ln = data[off + 1]
    off += 2
    if ln & 0x80:
        n = ln & 0x7F
        ln = int.from_bytes(data[off:off + n], "big")
        off += n
    return tag, off, off + ln, off + ln


_OID_CN = bytes.fromhex("550403")        # 2.5.4.3 commonName
_DN_OIDS = {
    bytes.fromhex("550403"): "CN", bytes.fromhex("55040a"): "O",
    bytes.fromhex("55040b"): "OU", bytes.fromhex("550406"): "C",
    bytes.fromhex("550408"): "ST", bytes.fromhex("550407"): "L",
}


def parse_der_subject(der: bytes) -> Dict[str, str]:
    """{attr: value} of the certificate's subject DN, e.g. {"CN": ...}.

    Certificate ::= SEQ { tbsCertificate SEQ {...}, sigAlg, sig }
    tbsCertificate: [0] version?, serial INT, sigAlg SEQ, issuer Name,
    validity SEQ, subject Name, ...
    """
    try:
        _, s, e, _ = _der_read(der, 0)            # Certificate
        _, s, e, _ = _der_read(der, s)            # tbsCertificate
        fields = []
        off = s
        while off < e and len(fields) < 6:
            tag, cs, ce, off = _der_read(der, off)
            fields.append((tag, cs, ce))
        if fields and fields[0][0] == 0xA0:        # explicit version
            fields.pop(0)
            tag, cs, ce, off = _der_read(der, off)
            fields.append((tag, cs, ce))
        # fields: serial, sigAlg, issuer, validity, subject
        _, ss, se = fields[4]
        out: Dict[str, str] = {}
        off = ss
        while off < se:                            # RDNSequence
            _, rs, re_, off = _der_read(der, off)  # RDN (SET)
            inner = rs
            while inner < re_:
                _, as_, ae, inner = _der_read(der, inner)   # AttrTypeValue
                otag, os_, oe, nxt = _der_read(der, as_)    # OID
                if otag == 0x06:
                    vtag, vs, ve, _ = _der_read(der, nxt)   # value
                    name = _DN_OIDS.get(der[os_:oe])
                    if name:
                        out[name] = der[vs:ve].decode("utf-8", "replace")
        return out
    except Exception:
        raise AuthenticationException(
            "unable to parse X.509 certificate")


def subject_dn_string(subject: Dict[str, str]) -> str:
    order = ["CN", "OU", "O", "L", "ST", "C"]
    return ",".join(f"{k}={subject[k]}" for k in order if k in subject)


def _verify_cert_chain(ders: List[bytes], truststore_path: str) -> None:
    """Validate a DER chain against a PEM CA bundle: every link's
    signature over tbsCertificate must verify against its issuer's
    public key, the terminal link must chain to a trusted CA, and all
    certs must be within their validity window (ref: PkiRealm's trust
    manager — 'Certificate for <dn> is not trusted'). Raises
    AuthenticationException on any failure."""
    try:
        from cryptography import x509
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives.asymmetric import (
            ec, ed25519, ed448, padding, rsa)
    except ImportError:                              # pragma: no cover
        raise AuthenticationException(
            "PKI chain validation unavailable (no cryptography library); "
            "refusing delegated PKI")

    def _check_sig(cert, issuer):
        pub = issuer.public_key()
        data, sig = cert.tbs_certificate_bytes, cert.signature
        if isinstance(pub, rsa.RSAPublicKey):
            pub.verify(sig, data, padding.PKCS1v15(),
                       cert.signature_hash_algorithm)
        elif isinstance(pub, ec.EllipticCurvePublicKey):
            pub.verify(sig, data,
                       ec.ECDSA(cert.signature_hash_algorithm))
        elif isinstance(pub, (ed25519.Ed25519PublicKey,
                              ed448.Ed448PublicKey)):
            pub.verify(sig, data)
        else:
            raise InvalidSignature("unsupported issuer key type")

    try:
        with open(truststore_path, "rb") as fh:
            trusted = x509.load_pem_x509_certificates(fh.read())
    except Exception:
        raise AuthenticationException(
            f"unable to load PKI truststore [{truststore_path}]")
    try:
        chain = [x509.load_der_x509_certificate(d) for d in ders]
    except Exception:
        raise AuthenticationException(
            "unable to parse X.509 certificate chain")
    import datetime as _dt
    now = _dt.datetime.now(_dt.timezone.utc)
    for cert in chain:
        if not (cert.not_valid_before_utc <= now
                <= cert.not_valid_after_utc):
            raise AuthenticationException(
                f"certificate for [{cert.subject.rfc4514_string()}] is "
                "expired or not yet valid")
    # Anchoring is decided ONLY by (a) byte-identity with a trusted cert
    # or (b) a signature that VERIFIES against a trusted cert's key.
    # Subject/issuer DN strings are attacker-chosen and never grant
    # trust by themselves — a rogue in-chain "CA" carrying a trusted
    # CA's DN must not anchor the chain.
    for i, cert in enumerate(chain):
        if any(cert == t for t in trusted):          # pinned, DER-equal
            return
        issuer_dn = cert.issuer.rfc4514_string()
        for t in trusted:
            if t.subject.rfc4514_string() == issuer_dn:
                try:
                    _check_sig(cert, t)
                    return                           # anchored in trust
                except Exception:
                    pass      # DN collision with the real CA — keep going
        if i + 1 < len(chain) \
                and chain[i + 1].subject.rfc4514_string() == issuer_dn:
            try:
                _check_sig(cert, chain[i + 1])       # untrusted link
            except Exception:
                raise AuthenticationException(
                    f"certificate for [{cert.subject.rfc4514_string()}] "
                    "has an invalid signature")
            continue
        raise AuthenticationException(
            f"certificate for [{cert.subject.rfc4514_string()}] "
            "is not trusted")
    raise AuthenticationException(
        "certificate chain does not terminate at a trusted CA")


class User:
    def __init__(self, username: str, roles: List[str],
                 metadata: Optional[Dict[str, Any]] = None,
                 full_name: Optional[str] = None,
                 email: Optional[str] = None,
                 api_key_roles: Optional[List[Dict[str, Any]]] = None):
        self.username = username
        self.roles = list(roles)
        self.metadata = metadata or {}
        self.full_name = full_name
        self.email = email
        # API-key auth carries inline role descriptors that REPLACE the
        # owner's roles when non-empty (ref: ApiKeyService role limiting)
        self.api_key_roles = api_key_roles
        # which realm authenticated this user (set by the realm chain)
        self.authenticated_realm: Optional[str] = None

    def to_dict(self):
        return {"username": self.username, "roles": self.roles,
                "full_name": self.full_name, "email": self.email,
                "metadata": self.metadata, "enabled": True}


_BUILTIN_ROLES: Dict[str, Dict[str, Any]] = {
    "superuser": {"cluster": ["all"],
                  "indices": [{"names": ["*"], "privileges": ["all"]}]},
    "kibana_system": {"cluster": ["monitor"],
                      "indices": [{"names": [".kibana*"],
                                   "privileges": ["all"]}]},
    "monitoring_user": {"cluster": ["monitor"], "indices": []},
}


# ---------------------------------------------------------------------------
# Realms (ref: x-pack/plugin/security/.../authc/AuthenticationService +
# Realms.java — ordered chain; each realm extracts its own token type
# from the request and the first realm that authenticates wins)
# ---------------------------------------------------------------------------

class Realm:
    type = "base"

    def __init__(self, name: str, order: int, svc: "SecurityService"):
        self.name = name
        self.order = order
        self.svc = svc

    def token(self, headers: Dict[str, str]):
        """Extract this realm's credential from the request, or None."""
        return None

    def authenticate(self, token) -> "User":
        raise AuthenticationException("not supported")


class NativeRealm(Realm):
    """Basic-auth against the native user store (the reserved `elastic`
    user lives here too — ref: ReservedRealm ordering before native)."""

    type = "native"

    def token(self, headers):
        auth = headers.get("authorization", "")
        if auth.lower().startswith("basic "):
            return auth.partition(" ")[2]
        return None

    def authenticate(self, payload) -> "User":
        try:
            username, _, password = base64.b64decode(
                payload).decode().partition(":")
        except Exception:
            raise AuthenticationException("invalid basic credentials")
        rec = self.svc._users.get(username)
        if (rec is None or not rec.get("enabled", True)
                or not _verify_password(password, rec["password"])):
            raise AuthenticationException(
                f"unable to authenticate user [{username}] for REST "
                f"request")
        return self.svc._user_obj(username)


class TokenRealm(Realm):
    """Bearer access tokens issued by the token service (ref:
    TokenService.java — create/refresh/invalidate, 20-minute expiry)."""

    type = "token"

    def token(self, headers):
        auth = headers.get("authorization", "")
        if auth.lower().startswith("bearer "):
            return auth.partition(" ")[2]
        return None

    def authenticate(self, access_token) -> "User":
        rec = self.svc._tokens.get(_sha(access_token))
        if rec is None or rec.get("invalidated"):
            raise AuthenticationException("token has been invalidated")
        if rec["expires"] < time.time() * 1000:
            raise AuthenticationException("token expired")
        u = User(rec["username"], rec.get("roles", []))
        return u


class ApiKeyRealm(Realm):
    type = "api_key"

    def token(self, headers):
        auth = headers.get("authorization", "")
        if auth.lower().startswith("apikey "):
            return auth.partition(" ")[2]
        return None

    def authenticate(self, payload) -> "User":
        try:
            key_id, _, key_secret = base64.b64decode(
                payload).decode().partition(":")
        except Exception:
            raise AuthenticationException("invalid ApiKey credentials")
        rec = self.svc._api_keys.get(key_id)
        if rec is None or rec.get("invalidated"):
            raise AuthenticationException("api key has been invalidated")
        if rec.get("expiration") and rec["expiration"] < time.time() * 1000:
            raise AuthenticationException("api key is expired")
        if not _verify_password(key_secret, rec["hash"]):
            raise AuthenticationException("invalid api key")
        rd = rec.get("role_descriptors") or {}
        return User(rec["owner"], rec.get("roles", []),
                    api_key_roles=list(rd.values()) if rd else None)


class FileRealm(Realm):
    """File-based users (ref: x-pack file realm — `users` and
    `users_roles` files next to the node config, bcrypt there, PBKDF2
    here via the shared hasher). Reloaded lazily on mtime change."""

    type = "file"

    def __init__(self, name, order, svc):
        super().__init__(name, order, svc)
        self._mtime = None
        self._users: Dict[str, str] = {}
        self._roles: Dict[str, List[str]] = {}

    def _paths(self):
        base = os.path.dirname(self.svc._path) if self.svc._path else None
        if base is None:
            return None, None
        return os.path.join(base, "users"), os.path.join(base,
                                                         "users_roles")

    def _reload(self):
        upath, rpath = self._paths()
        if upath is None or not os.path.exists(upath):
            self._users = {}
            return
        mtime = os.path.getmtime(upath)
        if mtime == self._mtime:
            return
        self._mtime = mtime
        users: Dict[str, str] = {}
        with open(upath) as f:
            for line in f:
                line = line.strip()
                if line and not line.startswith("#") and ":" in line:
                    name, _, hashed = line.partition(":")
                    users[name] = hashed
        roles: Dict[str, List[str]] = {}
        if rpath and os.path.exists(rpath):
            with open(rpath) as f:
                for line in f:
                    line = line.strip()
                    if line and not line.startswith("#") and ":" in line:
                        role, _, names = line.partition(":")
                        for n in names.split(","):
                            roles.setdefault(n.strip(), []).append(role)
        self._users, self._roles = users, roles

    def token(self, headers):
        auth = headers.get("authorization", "")
        if auth.lower().startswith("basic "):
            return auth.partition(" ")[2]
        return None

    def authenticate(self, payload) -> "User":
        self._reload()
        try:
            username, _, password = base64.b64decode(
                payload).decode().partition(":")
        except Exception:
            raise AuthenticationException("invalid basic credentials")
        hashed = self._users.get(username)
        if hashed is None or not _verify_password(password, hashed):
            raise AuthenticationException(
                f"unable to authenticate user [{username}] in the file "
                f"realm")
        return User(username, self._roles.get(username, []))


class JwtRealm(Realm):
    """JWT bearer authentication (ref: x-pack JWT realm). HS256 only —
    the shared secret is a keystore-only secure setting
    (xpack.security.authc.jwt.hmac_key). Principal = `sub` claim; roles
    come from a `roles` claim or role mappings; `exp`/`iss`/`aud` are
    enforced when configured."""

    type = "jwt"

    def __init__(self, name, order, svc, issuer: Optional[str] = None,
                 audience: Optional[str] = None):
        super().__init__(name, order, svc)
        self.issuer = issuer
        self.audience = audience

    def _key(self) -> Optional[bytes]:
        ks = getattr(self.svc, "keystore", None)
        if ks is not None and ks.is_loaded \
                and ks.has("xpack.security.authc.jwt.hmac_key"):
            return ks.get_string(
                "xpack.security.authc.jwt.hmac_key").encode()
        return None

    def token(self, headers):
        auth = headers.get("authorization", "")
        if auth.lower().startswith("bearer ") \
                and auth.count(".") == 2 and self._key() is not None:
            return auth.partition(" ")[2]
        return None

    @staticmethod
    def _b64url(data: str) -> bytes:
        pad = "=" * (-len(data) % 4)
        return base64.urlsafe_b64decode(data + pad)

    def authenticate(self, jwt: str) -> "User":
        key = self._key()
        if key is None:
            # keystore reloaded/unloaded between token() and here —
            # a 401, not a TypeError-driven 500
            raise AuthenticationException(
                "JWT realm has no hmac key configured")
        try:
            header_b64, claims_b64, sig_b64 = jwt.split(".")
            header = json.loads(self._b64url(header_b64))
            claims = json.loads(self._b64url(claims_b64))
            sig = self._b64url(sig_b64)
        except Exception:
            raise AuthenticationException("malformed JWT")
        if header.get("alg") != "HS256":
            raise AuthenticationException(
                f"unsupported JWT alg [{header.get('alg')}]")
        want = hmac.new(key, f"{header_b64}.{claims_b64}".encode(),
                        hashlib.sha256).digest()
        if not hmac.compare_digest(want, sig):
            raise AuthenticationException("JWT signature is invalid")
        if claims.get("exp") is not None \
                and claims["exp"] < time.time():
            raise AuthenticationException("JWT is expired")
        if self.issuer and claims.get("iss") != self.issuer:
            raise AuthenticationException("JWT issuer mismatch")
        if self.audience:
            aud = claims.get("aud")
            auds = aud if isinstance(aud, list) else [aud]
            if self.audience not in auds:
                raise AuthenticationException("JWT audience mismatch")
        sub = claims.get("sub")
        if not sub:
            raise AuthenticationException("JWT has no [sub] claim")
        roles = list(claims.get("roles", []))
        roles += self.svc.mapped_roles(username=sub, dn="",
                                       realm=self.name)
        return User(sub, sorted(set(roles)),
                    metadata={"jwt_claims": {k: v for k, v in
                                             claims.items()
                                             if k != "roles"}})


class OidcRealm(Realm):
    """OpenID Connect realm (ref: x-pack/plugin/security/.../authc/oidc/
    OpenIdConnectRealm.java — the resource-server half: RS256 ID-token /
    access-token validation against the OP's JWKS, issuer + audience
    checks, principal and groups from claims feeding role mappings).

    Config (xpack.security.authc.oidc.*): ``op.issuer``,
    ``rp.client_id`` (the audience), ``op.jwks_path`` (file path or URL
    of the OP's JWKS — the reference fetches the jwks_uri from OP
    metadata; zero-egress deployments point this at a synced file),
    ``claims.principal`` (default "sub"), ``claims.groups`` (default
    "groups")."""

    type = "oidc"

    # OP signing keys rotate; cache the JWKS briefly and re-fetch when a
    # token presents an unknown kid (rate-limited by the TTL) instead of
    # pinning the first fetch for the process lifetime
    JWKS_TTL = 300.0

    def __init__(self, name, order, svc, config: Dict[str, Any]):
        super().__init__(name, order, svc)
        self.config = config or {}
        self._jwks_cache: Optional[Dict[str, Any]] = None
        self._jwks_fetched = 0.0

    def token(self, headers):
        if not self.config.get("op.jwks_path"):
            return None
        auth = headers.get("authorization", "")
        if not auth.lower().startswith("bearer "):
            return None
        tok = auth.partition(" ")[2]
        if tok.count(".") != 2:
            return None
        try:
            header = json.loads(JwtRealm._b64url(tok.split(".")[0]))
        except Exception:
            return None
        # HS256 belongs to the JWT realm; this realm takes RS256/RS384/
        # RS512 OP-signed tokens
        if not str(header.get("alg", "")).startswith("RS"):
            return None
        return tok

    def _jwks(self, force: bool = False) -> Dict[str, Any]:
        now = time.time()
        if (self._jwks_cache is not None and not force
                and now - self._jwks_fetched < self.JWKS_TTL):
            return self._jwks_cache
        path = self.config["op.jwks_path"]
        try:
            if str(path).startswith(("http://", "https://")):
                import urllib.request
                with urllib.request.urlopen(str(path), timeout=10) as r:
                    data = json.loads(r.read())
            else:
                with open(path) as fh:
                    data = json.load(fh)
        except (OSError, ValueError) as e:
            if self._jwks_cache is not None:
                # keep serving the stale set rather than failing closed
                # on a transient refresh error
                self._jwks_fetched = now
                return self._jwks_cache
            raise AuthenticationException(
                f"unable to load OP JWKS [{path}]: {e}")
        self._jwks_cache = data
        self._jwks_fetched = now
        return data

    def _key_for(self, kid: Optional[str]):
        from cryptography.hazmat.primitives.asymmetric import rsa

        def find(jwks):
            for jwk in jwks.get("keys", []):
                if jwk.get("kty") != "RSA":
                    continue
                if kid is not None and jwk.get("kid") not in (None, kid):
                    continue
                n_int = int.from_bytes(
                    JwtRealm._b64url(jwk["n"]), "big")
                e_int = int.from_bytes(
                    JwtRealm._b64url(jwk["e"]), "big")
                return rsa.RSAPublicNumbers(e_int, n_int).public_key()
            return None

        key = find(self._jwks())
        if key is None and kid is not None \
                and time.time() - self._jwks_fetched >= 1.0:
            # unknown kid: the OP may have rotated — one forced re-fetch
            # (rate-limited) before rejecting
            key = find(self._jwks(force=True))
        if key is None:
            raise AuthenticationException(
                f"no RSA key [{kid}] in the OP JWKS")
        return key

    def authenticate(self, tok: str) -> "User":
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding
        try:
            header_b64, claims_b64, sig_b64 = tok.split(".")
            header = json.loads(JwtRealm._b64url(header_b64))
            claims = json.loads(JwtRealm._b64url(claims_b64))
            sig = JwtRealm._b64url(sig_b64)
        except Exception:
            raise AuthenticationException("malformed OIDC token")
        alg = str(header.get("alg", ""))
        digest = {"RS256": hashes.SHA256, "RS384": hashes.SHA384,
                  "RS512": hashes.SHA512}.get(alg)
        if digest is None:
            raise AuthenticationException(
                f"unsupported OIDC token alg [{alg}]")
        key = self._key_for(header.get("kid"))
        try:
            key.verify(sig, f"{header_b64}.{claims_b64}".encode(),
                       padding.PKCS1v15(), digest())
        except InvalidSignature:
            raise AuthenticationException(
                "OIDC token signature is invalid")
        issuer = self.config.get("op.issuer")
        if issuer and claims.get("iss") != issuer:
            raise AuthenticationException("OIDC token issuer mismatch")
        client_id = self.config.get("rp.client_id")
        if client_id:
            aud = claims.get("aud")
            auds = aud if isinstance(aud, list) else [aud]
            if client_id not in auds:
                raise AuthenticationException(
                    "OIDC token audience mismatch")
        if claims.get("exp") is None:
            # OIDC ID tokens REQUIRE exp (OpenID Core §2); accepting a
            # token without one means accepting it forever
            raise AuthenticationException("OIDC token has no exp claim")
        if claims["exp"] < time.time():
            raise AuthenticationException("OIDC token is expired")
        principal_claim = self.config.get("claims.principal", "sub")
        principal = claims.get(principal_claim)
        if not principal:
            raise AuthenticationException(
                f"OIDC token has no [{principal_claim}] claim")
        groups_claim = self.config.get("claims.groups", "groups")
        groups = claims.get(groups_claim) or []
        if isinstance(groups, str):
            groups = [groups]
        roles = self.svc.mapped_roles(username=principal, dn="",
                                      realm=self.name, groups=groups)
        return User(principal, roles,
                    metadata={"oidc_claims": {
                        k: v for k, v in claims.items()
                        if k not in ("exp", "iat")}})


class SamlRealm(Realm):
    """SAML 2.0 SP realm (ref: x-pack/plugin/security/.../authc/saml/
    SamlRealm.java:132). SAML credentials do not arrive on request
    headers — the browser posts the IdP's SAMLResponse to the web front,
    which calls POST /_security/saml/authenticate; the service routes
    that content here (ref: TransportSamlAuthenticateAction →
    SamlRealm.authenticate(SamlToken)). ``token()`` therefore always
    returns None.

    Config (xpack.security.authc.saml.*): ``idp.entity_id``,
    ``idp.certificate`` (PEM path or inline PEM), ``idp.sso_url``,
    ``sp.entity_id``, ``sp.acs``, ``attributes.principal`` (attribute
    name or "nameid"), ``attributes.groups`` (default "groups")."""

    type = "saml"

    def __init__(self, name, order, svc, config: Dict[str, Any]):
        super().__init__(name, order, svc)
        self.config = config or {}
        self._flow = None
        # outstanding AuthnRequest ids (ref: SamlAuthenticator
        # allowedSamlRequestIds — the REST API passes stored ids back);
        # consumed on success so a captured response can't be replayed
        self._pending_ids: Dict[str, float] = {}
        # assertion IDs already accepted (IdP-initiated flows carry no
        # InResponseTo; without this an unsolicited response replays)
        self._seen_assertions: Dict[str, float] = {}

    def _get_flow(self):
        if self._flow is None:
            from elasticsearch_tpu.xpack.saml import SamlAuthnFlow, SpConfig
            cert = self.config.get("idp.certificate", "")
            if cert and "BEGIN CERTIFICATE" not in cert:
                with open(cert) as fh:
                    cert = fh.read()
            self._flow = SamlAuthnFlow(
                SpConfig(self.config.get("sp.entity_id", ""),
                         self.config.get("sp.acs", "")),
                self.config.get("idp.entity_id", ""), cert,
                clock_skew=float(self.config.get("clock_skew", 180.0)))
        return self._flow

    def prepare(self) -> Dict[str, str]:
        """AuthnRequest for the redirect binding (ref:
        TransportSamlPrepareAuthenticationAction)."""
        out = self._get_flow().build_authn_request(
            self.config.get("idp.sso_url", ""))
        now = time.time()
        self._pending_ids = {i: t for i, t in self._pending_ids.items()
                             if now - t < 600}
        if len(self._pending_ids) >= 10_000:
            # evict oldest — an unauthenticated prepare() flood must
            # never lock legitimate logins out by filling the table
            for victim, _t in sorted(self._pending_ids.items(),
                                     key=lambda kv: kv[1])[:1000]:
                del self._pending_ids[victim]
        self._pending_ids[out["id"]] = now
        return out

    def authenticate(self, content_b64: str) -> "User":
        from elasticsearch_tpu.xpack.saml import SamlException
        try:
            res = self._get_flow().authenticate(
                content_b64, allowed_request_ids=list(self._pending_ids))
        except SamlException as e:
            raise AuthenticationException(f"SAML authentication "
                                          f"failed: {e}")
        # replay defenses: a request id authenticates ONCE, and an
        # accepted assertion ID is never accepted again for as long as
        # the assertion itself remains valid (covers the IdP-initiated
        # flow, which has no InResponseTo; the flow rejects assertions
        # without an ID or expiry, so every accepted one is trackable)
        if res.get("in_response_to"):
            self._pending_ids.pop(res["in_response_to"], None)
        aid = res["assertion_id"]
        now = time.time()
        self._seen_assertions = {
            i: exp for i, exp in self._seen_assertions.items()
            if exp > now}
        if aid in self._seen_assertions:
            raise AuthenticationException(
                "SAML assertion has already been consumed (replay)")
        if len(self._seen_assertions) >= 100_000:
            # evict the soonest-expiring — the defense must not fail
            # open under table pressure
            for victim, _e in sorted(self._seen_assertions.items(),
                                     key=lambda kv: kv[1])[:1000]:
                del self._seen_assertions[victim]
        self._seen_assertions[aid] = res["not_on_or_after"]
        attrs = res["attributes"]
        p_attr = self.config.get("attributes.principal", "nameid")
        if p_attr == "nameid":
            principal = res["principal"]
        else:
            vals = attrs.get(p_attr, [])
            principal = vals[0] if vals else None
        if not principal:
            raise AuthenticationException(
                "SAML assertion carries no usable principal")
        g_attr = self.config.get("attributes.groups", "groups")
        groups = attrs.get(g_attr, [])
        roles = self.svc.mapped_roles(username=principal, dn="",
                                      realm=self.name, groups=groups)
        return User(principal, roles,
                    metadata={"saml_nameid": res["nameid"],
                              "saml_session": res["session_index"],
                              "saml_attributes": attrs})


class LdapRealm(Realm):
    """LDAP / Active Directory authentication (ref:
    x-pack/plugin/security/.../authc/ldap/LdapRealm.java:54 — session
    factories bind as the user, then group search feeds role mappings).

    Config (xpack.security.authc.ldap.*):
    - ``url``: ldap://host:port
    - ``user_dn_templates``: ["uid={0},ou=people,dc=..."] — direct bind
      (LdapSessionFactory), OR
    - ``bind_dn``/``bind_password`` + ``user_search_base`` (+
      ``user_search_attribute``, default uid) — search-then-bind
      (LdapUserSearchSessionFactory)
    - ``group_search_base``: subtree searched for groups whose
      ``member``/``uniqueMember`` holds the user DN or ``memberUid``
      holds the username (the AD/posixGroup shapes)

    Roles come from role mappings over the ``groups``/``dn``/
    ``username`` fields — LDAP groups are never roles directly unless
    mapped (ref: the unmapped_groups_as_roles=false default)."""

    type = "ldap"

    def __init__(self, name, order, svc, config: Dict[str, Any]):
        super().__init__(name, order, svc)
        self.config = config or {}

    def token(self, headers):
        if not self.config.get("url"):
            return None
        auth = headers.get("authorization", "")
        if auth.lower().startswith("basic "):
            return auth.partition(" ")[2]
        return None

    def _connect(self):
        from elasticsearch_tpu.common.ldap import (LdapClient,
                                                   parse_ldap_url)
        host, port = parse_ldap_url(self.config["url"])
        return LdapClient(host, port, timeout=float(
            self.config.get("timeout", 5.0)))

    def authenticate(self, payload) -> "User":
        from elasticsearch_tpu.common.ldap import LdapError
        try:
            username, _, password = base64.b64decode(
                payload).decode().partition(":")
        except Exception:
            raise AuthenticationException("invalid basic credentials")
        if not username or not password:
            raise AuthenticationException(
                "missing LDAP credentials")
        try:
            user_dn = self._bind_user(username, password)
        except LdapError as e:
            raise AuthenticationException(f"LDAP authentication "
                                          f"failed: {e}")
        if user_dn is None:
            raise AuthenticationException(
                f"unable to authenticate user [{username}] against "
                f"LDAP")
        groups = self._groups(user_dn, username)
        roles = self.svc.mapped_roles(username=username, dn=user_dn,
                                      realm=self.name, groups=groups)
        return User(username, roles,
                    metadata={"ldap_dn": user_dn,
                              "ldap_groups": groups})

    @staticmethod
    def _escape_dn_value(value: str) -> str:
        """RFC 4514 escaping for an attribute VALUE substituted into a
        DN template — without it a username like ``x,ou=admins``
        rewrites the bind DN (the reference escapes via UnboundID's
        DN/RDN encoder before template substitution)."""
        if "\x00" in value:
            raise AuthenticationException(
                "invalid character in LDAP username")
        out = []
        for i, ch in enumerate(value):
            if ch in ',+"\\<>;=':
                out.append("\\" + ch)
            elif ch in "# " and i == 0:
                out.append("\\" + ch)
            elif ch == " " and i == len(value) - 1:
                out.append("\\ ")
            else:
                out.append(ch)
        return "".join(out)

    def _bind_user(self, username: str, password: str):
        """The user's DN on successful bind, else None."""
        from elasticsearch_tpu.common.ldap import LdapError
        templates = self.config.get("user_dn_templates") or []
        if templates:
            safe = self._escape_dn_value(username)
            for tpl in templates:
                dn = tpl.replace("{0}", safe)
                with self._connect() as c:
                    try:
                        if c.simple_bind(dn, password):
                            return dn
                    except LdapError:
                        continue
            return None
        # search-then-bind
        base = self.config.get("user_search_base")
        if not base:
            raise LdapError("ldap realm requires user_dn_templates or "
                            "user_search_base")
        attr = self.config.get("user_search_attribute", "uid")
        with self._connect() as c:
            bind_dn = self.config.get("bind_dn")
            if bind_dn:
                if not c.simple_bind(bind_dn,
                                     self.config.get("bind_password",
                                                     "")):
                    raise LdapError("bind_dn authentication failed")
            hits = c.search(base, ("=", attr, username), ["dn"])
        if not hits:
            return None
        user_dn = hits[0][0]
        with self._connect() as c:
            return user_dn if c.simple_bind(user_dn, password) else None

    def _groups(self, user_dn: str, username: str) -> List[str]:
        base = self.config.get("group_search_base")
        if not base:
            return []
        from elasticsearch_tpu.common.ldap import LdapError
        try:
            with self._connect() as c:
                bind_dn = self.config.get("bind_dn")
                if bind_dn and not c.simple_bind(
                        bind_dn, self.config.get("bind_password", "")):
                    raise LdapError("bind_dn authentication failed "
                                    "during group lookup")
                hits = c.search(
                    base,
                    ("|", [("=", "member", user_dn),
                           ("=", "uniqueMember", user_dn),
                           ("=", "memberUid", username)]),
                    ["cn"])
        except LdapError as e:
            # FAIL CLOSED: a broken group lookup must not silently strip
            # every mapped role (ref: the realm errors out, it never
            # authenticates with an empty group set on lookup failure)
            raise AuthenticationException(
                f"LDAP group lookup failed: {e}")
        groups = []
        for dn, attrs in hits:
            groups.append(dn)
            groups.extend(attrs.get("cn", []))
        return groups


class KerberosRealm(Realm):
    """Kerberos/SPNEGO realm (ref: x-pack/plugin/security/.../authc/
    kerberos/KerberosRealm.java:60). The browser/client sends
    ``Authorization: Negotiate <base64 SPNEGO>``; the token's AP-REQ is
    validated by decrypting the service ticket with the keytab key
    (common/krb5.py — native RFC 3961/3962 aes-cts-hmac-sha1-96, where
    the reference delegates to Java GSS). On failure the reference
    responds 401 with ``WWW-Authenticate: Negotiate``; the REST layer
    surfaces that header for AuthenticationExceptions from this realm.

    Config (xpack.security.authc.kerberos.*): ``keytab_path`` — JSON
    {service_principal: hex_aes_key} (DISCLOSED divergence: the MIT
    binary keytab container format is not parsed; the keys are the same
    material), ``remove_realm_name`` — map ``user@REALM`` to ``user``
    (ref: KerberosRealmSettings.SETTING_REMOVE_REALM_NAME)."""

    type = "kerberos"

    # authenticator replay window must cover validate_spnego's max_skew
    REPLAY_WINDOW = 600.0

    def __init__(self, name, order, svc, config: Dict[str, Any]):
        super().__init__(name, order, svc)
        self.config = config or {}
        self._keytab: Optional[Dict[str, bytes]] = None
        # AP-REQ replay cache (RFC 4120 §3.2.3 requires one: a captured
        # Negotiate header must not re-authenticate within the skew
        # window) — keyed by token digest, value = expiry
        self._seen_tokens: Dict[str, float] = {}

    def token(self, headers):
        auth = headers.get("authorization", "")
        if auth.lower().startswith("negotiate "):
            return auth.partition(" ")[2]
        return None

    def _load_keytab(self) -> Dict[str, bytes]:
        if self._keytab is None:
            path = self.config["keytab_path"]
            try:
                with open(path) as fh:
                    raw = json.load(fh)
                self._keytab = {k: bytes.fromhex(v)
                                for k, v in raw.items()}
            except (OSError, ValueError) as e:
                raise AuthenticationException(
                    f"unable to load keytab [{path}]: {e}")
        return self._keytab

    def authenticate(self, token_b64: str) -> "User":
        from elasticsearch_tpu.common.krb5 import KrbError, validate_spnego
        try:
            token = base64.b64decode(token_b64, validate=True)
        except Exception:
            raise AuthenticationException(
                "malformed Negotiate token")
        now = time.time()
        digest = _sha(token_b64)
        self._seen_tokens = {d: exp for d, exp
                             in self._seen_tokens.items() if exp > now}
        if digest in self._seen_tokens:
            raise AuthenticationException(
                "kerberos token has already been used (replay)")
        try:
            res = validate_spnego(token, self._load_keytab())
        except KrbError as e:
            raise AuthenticationException(
                f"kerberos authentication failed: {e}")
        if len(self._seen_tokens) >= 100_000:
            for victim, _e in sorted(self._seen_tokens.items(),
                                     key=lambda kv: kv[1])[:1000]:
                del self._seen_tokens[victim]
        self._seen_tokens[digest] = now + self.REPLAY_WINDOW
        principal = res["principal"]
        if self.config.get("remove_realm_name"):
            principal = res["name"]
        roles = self.svc.mapped_roles(username=principal, dn="",
                                      realm=self.name)
        return User(principal, roles,
                    metadata={"kerberos_realm": res["realm"]})


class PkiRealm(Realm):
    """Client-certificate authentication (ref: pki/PkiRealm.java). The
    certificate arrives either on the `x-ssl-client-cert` header (PEM,
    TLS-terminating-proxy convention) or through the delegated-PKI API
    (POST /_security/delegate_pki with a DER chain — ref:
    TransportDelegatePkiAuthenticationAction). The principal is the
    subject CN; roles come from role mappings."""

    type = "pki"

    def token(self, headers):
        # header-based PKI is an explicit OPT-IN
        # (xpack.security.authc.pki.trust_proxy_header): the header
        # carries an UNVERIFIED certificate, acceptable only when a
        # trusted TLS-terminating proxy strips/sets it. Without the
        # opt-in, PKI authentication happens solely through the
        # delegate_pki API, which itself requires the delegate_pki
        # cluster privilege (ref: delegated PKI authorization).
        if not getattr(self.svc, "pki_header_trusted", False):
            return None
        pem = headers.get("x-ssl-client-cert")
        if pem:
            return pem
        return None

    @staticmethod
    def _pem_to_der(pem: str) -> bytes:
        body = "".join(line for line in pem.replace("\\n", "\n").splitlines()
                       if line and not line.startswith("-----"))
        return base64.b64decode(body)

    def user_from_der(self, der: bytes) -> "User":
        subject = parse_der_subject(der)
        cn = subject.get("CN")
        if not cn:
            raise AuthenticationException(
                "certificate subject has no CN to use as principal")
        dn = subject_dn_string(subject)
        roles = self.svc.mapped_roles(username=cn, dn=dn, realm=self.name)
        return User(cn, roles, metadata={"pki_dn": dn})

    def authenticate(self, pem) -> "User":
        return self.user_from_der(self._pem_to_der(pem))


def _sha(s: str) -> str:
    return hashlib.sha256(s.encode()).hexdigest()


def _dn_like(value: Optional[str], pattern: Any) -> bool:
    """Role-mapping field compare: case-insensitive with * wildcards
    (ref: the mapping rules' DN/username templates)."""
    if value is None or pattern is None:
        return value is None and pattern is None
    return fnmatch.fnmatch(str(value).lower(), str(pattern).lower())


class AuditTrail:
    """Append-only JSONL audit log (ref: audit/logfile/
    LoggingAuditTrail.java — authentication_success/failed,
    access_granted/denied events with origin + request context)."""

    def __init__(self, path: Optional[str], enabled: bool = False):
        self.path = path
        self.enabled = enabled and path is not None
        self._lock = threading.Lock()

    def _emit(self, event: str, **fields):
        if not self.enabled:
            return
        rec = {"@timestamp": int(time.time() * 1000),
               "event.type": "security", "event.action": event, **fields}
        with self._lock:
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")

    def authentication_success(self, user: "User", realm: str,
                               method: str, path: str):
        self._emit("authentication_success", **{
            "user.name": user.username, "realm": realm,
            "url.path": path, "http.request.method": method})

    def authentication_failed(self, method: str, path: str,
                              reason: str):
        self._emit("authentication_failed", **{
            "url.path": path, "http.request.method": method,
            "reason": reason})

    def access_granted(self, user: "User", privilege: str,
                       method: str, path: str):
        self._emit("access_granted", **{
            "user.name": user.username, "privilege": privilege,
            "url.path": path, "http.request.method": method})

    def access_denied(self, user: "User", privilege: str,
                      method: str, path: str):
        self._emit("access_denied", **{
            "user.name": user.username, "privilege": privilege,
            "url.path": path, "http.request.method": method})


class SecurityService:
    """User/role/API-key registry + authn/authz engine."""

    TOKEN_TTL_MS = 20 * 60 * 1000     # ref: TokenService 20-minute expiry

    def __init__(self, data_path: Optional[str] = None,
                 enabled: bool = False,
                 bootstrap_password: str = "changeme",
                 anonymous_username: Optional[str] = None,
                 anonymous_roles: Optional[List[str]] = None,
                 audit_enabled: bool = False,
                 realm_orders: Optional[Dict[str, int]] = None,
                 pki_header_trusted: bool = False,
                 pki_truststore: Optional[str] = None,
                 keystore=None,
                 jwt_issuer: Optional[str] = None,
                 jwt_audience: Optional[str] = None,
                 ldap_config: Optional[Dict[str, Any]] = None,
                 oidc_config: Optional[Dict[str, Any]] = None,
                 saml_config: Optional[Dict[str, Any]] = None,
                 kerberos_config: Optional[Dict[str, Any]] = None):
        # ref: x-pack anonymous access (xpack.security.authc.anonymous.*)
        # — requests without credentials authenticate as this principal
        self.anonymous_username = anonymous_username
        self.anonymous_roles = list(anonymous_roles or [])
        self.enabled = enabled
        self.pki_header_trusted = pki_header_trusted
        # PEM bundle of CA certs the PKI realm trusts for DELEGATED auth
        # (ref: PkiRealm truststore — delegated tokens are refused unless
        # the submitted chain validates against it)
        self.pki_truststore = pki_truststore
        self._lock = threading.Lock()
        self._users: Dict[str, Dict[str, Any]] = {}
        self._roles: Dict[str, Dict[str, Any]] = {}
        self._api_keys: Dict[str, Dict[str, Any]] = {}
        # sha256(access_token) -> token record (ref: the .security tokens)
        self._tokens: Dict[str, Dict[str, Any]] = {}
        # sha256(refresh_token) -> access-token hash
        self._refresh: Dict[str, str] = {}
        # role mapping name -> {"roles": [...], "rules": {...}, "enabled"}
        self._role_mappings: Dict[str, Dict[str, Any]] = {}
        self._path = (os.path.join(data_path, "_security.json")
                      if data_path else None)
        self.audit = AuditTrail(
            os.path.join(data_path, "_audit.log") if data_path else None,
            enabled=audit_enabled)
        self._load()
        if "elastic" not in self._users:
            # reserved superuser (ref: ReservedRealm + bootstrap.password)
            self._users["elastic"] = {
                "password": _hash_password(bootstrap_password),
                "roles": ["superuser"], "full_name": None, "email": None,
                "metadata": {"_reserved": True}, "enabled": True}
        # ordered realm chain (ref: Realms.java — order from settings,
        # xpack.security.authc.realms.<type>.<name>.order)
        self.keystore = keystore
        orders = realm_orders or {}
        self.realms: List[Realm] = sorted([
            NativeRealm("native1", orders.get("native", 0), self),
            FileRealm("file1", orders.get("file", 1), self),
            TokenRealm("token1", orders.get("token", 2), self),
            JwtRealm("jwt1", orders.get("jwt", 3), self,
                     issuer=jwt_issuer, audience=jwt_audience),
            ApiKeyRealm("api_key1", orders.get("api_key", 4), self),
            PkiRealm("pki1", orders.get("pki", 5), self),
        ] + ([LdapRealm("ldap1", orders.get("ldap", 6), self,
                        ldap_config)]
             if ldap_config and ldap_config.get("url") else [])
          + ([OidcRealm("oidc1", orders.get("oidc", 7), self,
                        oidc_config)]
             if oidc_config and oidc_config.get("op.jwks_path")
             else [])
          + ([SamlRealm("saml1", orders.get("saml", 8), self,
                        saml_config)]
             if saml_config and saml_config.get("idp.entity_id")
             and saml_config.get("idp.certificate")
             else [])
          + ([KerberosRealm("kerb1", orders.get("kerberos", 9), self,
                            kerberos_config)]
             if kerberos_config and kerberos_config.get("keytab_path")
             else []),
            key=lambda r: r.order)

    # ------------------------------------------------------------- persist
    def _load(self):
        if self._path and os.path.exists(self._path):
            with open(self._path) as fh:
                blob = json.load(fh)
            self._users = blob.get("users", {})
            self._roles = blob.get("roles", {})
            self._api_keys = blob.get("api_keys", {})
            self._tokens = blob.get("tokens", {})
            self._refresh = blob.get("refresh", {})
            self._role_mappings = blob.get("role_mappings", {})

    def _persist(self):
        if not self._path:
            return
        tmp = self._path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump({"users": self._users, "roles": self._roles,
                       "api_keys": self._api_keys,
                       "tokens": self._tokens, "refresh": self._refresh,
                       "role_mappings": self._role_mappings}, fh)
        os.replace(tmp, self._path)

    # --------------------------------------------------------------- users
    def put_user(self, username: str, body: Dict[str, Any]):
        with self._lock:
            existing = self._users.get(username, {})
            password = body.get("password")
            if password is None and not existing:
                raise IllegalArgumentException(
                    f"password must be specified unless you are updating an "
                    f"existing user")
            self._users[username] = {
                "password": (_hash_password(password) if password
                             else existing.get("password")),
                "roles": list(body.get("roles", existing.get("roles", []))),
                "full_name": body.get("full_name", existing.get("full_name")),
                "email": body.get("email", existing.get("email")),
                "metadata": body.get("metadata", existing.get("metadata", {})),
                "enabled": body.get("enabled", True),
            }
            self._persist()
        return {"created": not existing}

    def get_user(self, username: Optional[str] = None) -> Dict[str, Any]:
        if username is None:
            return {u: self._user_obj(u).to_dict() for u in self._users}
        if username not in self._users:
            raise ResourceNotFoundException(f"user [{username}] not found")
        return {username: self._user_obj(username).to_dict()}

    def delete_user(self, username: str):
        u = self._users.get(username)
        if u is None:
            raise ResourceNotFoundException(f"user [{username}] not found")
        if u.get("metadata", {}).get("_reserved"):
            raise IllegalArgumentException(
                f"user [{username}] is reserved and cannot be deleted")
        with self._lock:
            del self._users[username]
            self._persist()

    def change_password(self, username: str, password: str):
        if username not in self._users:
            raise ResourceNotFoundException(f"user [{username}] not found")
        with self._lock:
            self._users[username]["password"] = _hash_password(password)
            self._persist()

    def _user_obj(self, username: str) -> User:
        rec = self._users[username]
        return User(username, rec.get("roles", []), rec.get("metadata"),
                    rec.get("full_name"), rec.get("email"))

    # --------------------------------------------------------------- roles
    def put_role(self, name: str, body: Dict[str, Any]):
        for cp in body.get("cluster", []):
            if cp not in CLUSTER_PRIVILEGES:
                raise IllegalArgumentException(
                    f"unknown cluster privilege [{cp}]")
        for grp in body.get("indices", []):
            for ip in grp.get("privileges", []):
                if ip not in INDEX_PRIVILEGES:
                    raise IllegalArgumentException(
                        f"unknown index privilege [{ip}]")
        with self._lock:
            created = name not in self._roles
            self._roles[name] = {"cluster": list(body.get("cluster", [])),
                                 "indices": list(body.get("indices", [])),
                                 "run_as": list(body.get("run_as", [])),
                                 "metadata": body.get("metadata", {})}
            self._persist()
        return {"role": {"created": created}}

    def get_role(self, name: Optional[str] = None) -> Dict[str, Any]:
        allr = {**_BUILTIN_ROLES, **self._roles}
        if name is None:
            return dict(allr)
        if name not in allr:
            raise ResourceNotFoundException(f"role [{name}] not found")
        return {name: allr[name]}

    def delete_role(self, name: str):
        if name not in self._roles:
            raise ResourceNotFoundException(f"role [{name}] not found")
        with self._lock:
            del self._roles[name]
            self._persist()

    # ------------------------------------------------------------ API keys
    def create_api_key(self, user: User, body: Dict[str, Any]) -> Dict[str, Any]:
        key_id = secrets.token_urlsafe(16)
        key_secret = secrets.token_urlsafe(24)
        expiration = body.get("expiration")
        expires_ms = None
        if expiration:
            from elasticsearch_tpu.xpack.ilm import parse_time_ms
            expires_ms = int(time.time() * 1000 + parse_time_ms(expiration))
        with self._lock:
            self._api_keys[key_id] = {
                "name": body.get("name"),
                "hash": _hash_password(key_secret),
                "owner": user.username,
                "roles": user.roles,
                "role_descriptors": body.get("role_descriptors") or {},
                "creation": int(time.time() * 1000),
                "expiration": expires_ms,
                "invalidated": False,
            }
            self._persist()
        encoded = base64.b64encode(
            f"{key_id}:{key_secret}".encode()).decode()
        return {"id": key_id, "name": body.get("name"),
                "api_key": key_secret, "encoded": encoded,
                "expiration": expires_ms}

    def get_api_keys(self) -> List[Dict[str, Any]]:
        return [{"id": kid, "name": rec.get("name"),
                 "username": rec.get("owner"),
                 "creation": rec.get("creation"),
                 "expiration": rec.get("expiration"),
                 "invalidated": rec.get("invalidated", False)}
                for kid, rec in self._api_keys.items()]

    def invalidate_api_key(self, key_id: Optional[str] = None,
                           name: Optional[str] = None) -> List[str]:
        out = []
        with self._lock:
            for kid, rec in self._api_keys.items():
                if (key_id and kid == key_id) or (name and rec.get("name") == name):
                    if not rec["invalidated"]:
                        rec["invalidated"] = True
                        out.append(kid)
            self._persist()
        return out

    # ---------------------------------------------------------------- authn
    def authenticate(self, headers: Optional[Dict[str, str]]) -> User:
        """Run the ordered realm chain (ref: AuthenticationService
        .authenticate — each realm extracts its own token type; the
        first realm whose token authenticates wins; a consumed-but-
        failed token surfaces the realm's error)."""
        headers = {k.lower(): v for k, v in (headers or {}).items()}
        last_error: Optional[AuthenticationException] = None
        consumed = False
        for realm in self.realms:
            tok = realm.token(headers)
            if tok is None:
                continue
            consumed = True
            try:
                user = realm.authenticate(tok)
                user.authenticated_realm = realm.name
                return user
            except AuthenticationException as e:
                last_error = e
        if consumed:
            raise last_error or AuthenticationException(
                "unable to authenticate for REST request")
        if self.anonymous_username is not None:
            # no realm consumed any credential: anonymous principal
            # (ref: AuthenticationService.handleNullToken)
            return User(self.anonymous_username, self.anonymous_roles)
        if headers.get("authorization"):
            raise AuthenticationException(
                "unsupported authorization scheme "
                f"[{headers['authorization'].partition(' ')[0]}]")
        raise AuthenticationException(
            "missing authentication credentials for REST request")

    # -------------------------------------------------------- SAML APIs
    def _saml_realm(self) -> "SamlRealm":
        for r in self.realms:
            if isinstance(r, SamlRealm):
                return r
        raise IllegalArgumentException(
            "no SAML realm is configured "
            "(xpack.security.authc.saml.idp.entity_id)")

    def saml_prepare(self) -> Dict[str, Any]:
        """POST /_security/saml/prepare (ref:
        TransportSamlPrepareAuthenticationAction): the AuthnRequest
        redirect URL + the request id the caller must hand back."""
        realm = self._saml_realm()
        out = realm.prepare()
        return {"realm": realm.name, "id": out["id"],
                "redirect": out["redirect"]}

    def saml_authenticate(self, content_b64: str) -> Dict[str, Any]:
        """POST /_security/saml/authenticate (ref:
        TransportSamlAuthenticateAction): validates the IdP response and
        issues an access/refresh token pair for the mapped user."""
        realm = self._saml_realm()
        try:
            user = realm.authenticate(content_b64)
        except AuthenticationException as e:
            # the login endpoint bypasses the header-auth path, so its
            # failures must be audited here (forgery/replay attempts
            # against SSO would otherwise be invisible)
            self.audit.authentication_failed(
                "POST", "/_security/saml/authenticate", str(e))
            raise
        user.authenticated_realm = realm.name
        self.audit.authentication_success(user, realm.name, "POST",
                                          "/_security/saml/authenticate")
        tok = self._issue_token(user)
        return {"username": user.username,
                "realm": realm.name,
                "access_token": tok["access_token"],
                "refresh_token": tok["refresh_token"],
                "expires_in": tok["expires_in"]}

    def saml_logout(self, token: str) -> Dict[str, Any]:
        """POST /_security/saml/logout (ref:
        TransportSamlLogoutAction): invalidates the access token; the
        redirect would carry a LogoutRequest to the IdP's SLO endpoint
        (none is configured in-framework, so redirect is null)."""
        n = self.invalidate_tokens(token=token)
        return {"invalidated": n, "redirect": None}

    # ------------------------------------------------------ token service
    def create_token(self, grant_type: str, username: str = "",
                     password: str = "",
                     refresh_token: str = "",
                     request_user: Optional[User] = None) -> Dict[str, Any]:
        """POST /_security/oauth2/token (ref: TokenService.java +
        TransportCreateTokenAction): password / client_credentials /
        refresh_token grants."""
        if grant_type == "password":
            rec = self._users.get(username)
            if (rec is None or not rec.get("enabled", True)
                    or not _verify_password(password, rec["password"])):
                raise AuthenticationException(
                    f"unable to authenticate user [{username}]")
            user = self._user_obj(username)
        elif grant_type == "refresh_token":
            return self.refresh_token(refresh_token)
        elif grant_type == "client_credentials":
            # issues a token for the ALREADY-authenticated request user
            # (ref: client_credentials grant has no refresh token)
            if request_user is None:
                raise AuthenticationException(
                    "client_credentials grant requires authentication")
            out = self._issue_token(request_user)
            out.pop("refresh_token", None)
            return out
        else:
            raise IllegalArgumentException(
                f"unsupported grant_type [{grant_type}]")
        return self._issue_token(user)

    def _prune_tokens_locked(self) -> None:
        """Drop records a day past expiry (bounded stores — the
        reference's ExpiredTokenRemover)."""
        if len(self._tokens) < 128:
            return
        horizon = time.time() * 1000 - 24 * 3600 * 1000
        dead = {h for h, rec in self._tokens.items()
                if rec["expires"] < horizon}
        if dead:
            self._tokens = {h: r for h, r in self._tokens.items()
                            if h not in dead}
            self._refresh = {r: a for r, a in self._refresh.items()
                             if a not in dead}

    def _issue_token(self, user: User) -> Dict[str, Any]:
        access = secrets.token_urlsafe(32)
        refresh = secrets.token_urlsafe(32)
        with self._lock:
            self._prune_tokens_locked()
            self._tokens[_sha(access)] = {
                "username": user.username, "roles": user.roles,
                "expires": int(time.time() * 1000) + self.TOKEN_TTL_MS,
                "invalidated": False, "refresh": _sha(refresh),
                "refreshed": False,
            }
            self._refresh[_sha(refresh)] = _sha(access)
            self._persist()
        return {"access_token": access, "type": "Bearer",
                "expires_in": self.TOKEN_TTL_MS // 1000,
                "refresh_token": refresh}

    def refresh_token(self, refresh_token: str) -> Dict[str, Any]:
        """One-time refresh: rotates the pair, invalidating the old
        access token (ref: TokenService.refreshToken)."""
        with self._lock:
            ah = self._refresh.get(_sha(refresh_token))
            rec = self._tokens.get(ah) if ah else None
            if rec is None or rec.get("refreshed") or rec.get("invalidated"):
                raise IllegalArgumentException(
                    "token has already been refreshed or invalidated")
            rec["refreshed"] = True
            rec["invalidated"] = True
            user = User(rec["username"], rec.get("roles", []))
        return self._issue_token(user)

    def invalidate_tokens(self, token: Optional[str] = None,
                          refresh_token: Optional[str] = None,
                          username: Optional[str] = None,
                          request_user: Optional[User] = None) -> int:
        """DELETE /_security/oauth2/token (ref:
        TransportInvalidateTokenAction). Possession of a token/refresh
        token authorizes invalidating it; invalidating BY USERNAME
        requires manage_token (or self)."""
        if username is not None:
            allowed = (request_user is not None
                       and (request_user.username == username
                            or self.has_cluster_privilege(
                                request_user, "manage_token")
                            or self.has_cluster_privilege(
                                request_user, "manage_security")))
            if not allowed:
                raise SecurityException(
                    "invalidating tokens by username requires the "
                    "[manage_token] cluster privilege")
        n = 0
        with self._lock:
            if token is not None:
                rec = self._tokens.get(_sha(token))
                if rec and not rec["invalidated"]:
                    rec["invalidated"] = True
                    n += 1
            if refresh_token is not None:
                ah = self._refresh.get(_sha(refresh_token))
                rec = self._tokens.get(ah) if ah else None
                if rec and not rec["invalidated"]:
                    rec["invalidated"] = True
                    n += 1
            if username is not None:
                for rec in self._tokens.values():
                    if rec["username"] == username \
                            and not rec["invalidated"]:
                        rec["invalidated"] = True
                        n += 1
            self._persist()
        return n

    # ------------------------------------------------ delegated PKI
    def delegate_pki(self, x509_chain: List[str]) -> Dict[str, Any]:
        """POST /_security/delegate_pki: a trusted proxy submits the
        client's DER chain (base64); the PKI realm authenticates the END
        entity and a token is issued (ref:
        TransportDelegatePkiAuthenticationAction)."""
        if not x509_chain:
            raise IllegalArgumentException(
                "x509_certificate_chain must be non-empty")
        pki = next((r for r in self.realms if isinstance(r, PkiRealm)),
                   None)
        # ref: PkiRealm refuses delegated tokens unless the chain
        # validates against the realm's trust manager ("Certificate for
        # <dn> is not trusted") — without this, any holder of the
        # delegate_pki privilege could fabricate a DER blob for an
        # arbitrary CN and mint a token with that identity's roles.
        if not self.pki_truststore:
            raise AuthenticationException(
                "delegated PKI authentication requires a configured PKI "
                "truststore (pki_truststore); refusing unverified chain")
        try:
            ders = [base64.b64decode(c) for c in x509_chain]
        except Exception:
            raise AuthenticationException(
                "x509_certificate_chain entries must be base64 DER")
        _verify_cert_chain(ders, self.pki_truststore)
        der = ders[0]
        user = pki.user_from_der(der)
        user.authenticated_realm = pki.name
        out = self._issue_token(user)
        out["authentication"] = user.to_dict()
        return out

    # ------------------------------------------------ role mappings
    def put_role_mapping(self, name: str, body: Dict[str, Any]):
        with self._lock:
            created = name not in self._role_mappings
            self._role_mappings[name] = {
                "roles": list(body.get("roles", [])),
                "rules": body.get("rules", {}),
                "enabled": bool(body.get("enabled", True)),
                "metadata": body.get("metadata", {}),
            }
            self._persist()
        return {"role_mapping": {"created": created}}

    def get_role_mappings(self, name: Optional[str] = None):
        if name is not None:
            if name not in self._role_mappings:
                raise ResourceNotFoundException(
                    f"role mapping [{name}] not found")
            return {name: self._role_mappings[name]}
        return dict(self._role_mappings)

    def delete_role_mapping(self, name: str):
        with self._lock:
            found = self._role_mappings.pop(name, None) is not None
            self._persist()
        return {"found": found}

    def mapped_roles(self, username: str, dn: str,
                     realm: str,
                     groups: Optional[List[str]] = None) -> List[str]:
        """Resolve roles via role-mapping rules (ref: the field rules of
        put_role_mapping: username / dn / realm.name / groups — the
        groups field is how LDAP/AD realms grant roles, with any/all)."""
        ctx = {"username": username, "dn": dn, "realm.name": realm}
        group_list = list(groups or [])

        def match(rule: Dict[str, Any]) -> bool:
            if "field" in rule:
                for k, want in rule["field"].items():
                    wants = want if isinstance(want, list) else [want]
                    if k == "groups":
                        if not any(_dn_like(g, w) for g in group_list
                                   for w in wants):
                            return False
                        continue
                    got = ctx.get(k)
                    if not any(_dn_like(got, w) for w in wants):
                        return False
                return True
            if "any" in rule:
                return any(match(r) for r in rule["any"])
            if "all" in rule:
                return all(match(r) for r in rule["all"])
            if "except" in rule:
                return not match(rule["except"])
            return False

        roles: List[str] = []
        for m in self._role_mappings.values():
            if m.get("enabled", True) and match(m.get("rules", {})):
                roles.extend(m["roles"])
        return sorted(set(roles))

    # ---------------------------------------------------------------- authz
    def _role_defs(self, user: User) -> List[Dict[str, Any]]:
        if user.api_key_roles is not None:
            return user.api_key_roles
        out = []
        allr = {**_BUILTIN_ROLES, **self._roles}
        for r in user.roles:
            if r in allr:
                out.append(allr[r])
        return out

    def has_cluster_privilege(self, user: User, privilege: str) -> bool:
        for role in self._role_defs(user):
            for held in role.get("cluster", []):
                if held == privilege or privilege in _CLUSTER_IMPLIES.get(
                        held, ()):
                    return True
        return False

    def has_index_privilege(self, user: User, index: str,
                            privilege: str) -> bool:
        for role in self._role_defs(user):
            for grp in role.get("indices", []):
                names = grp.get("names", [])
                if not any(fnmatch.fnmatchcase(index, p) for p in names):
                    continue
                for held in grp.get("privileges", []):
                    if held == privilege or privilege in _INDEX_IMPLIES.get(
                            held, ()):
                        return True
        return False

    def authorize(self, user: User, kind: str, privilege: str,
                  index: Optional[str] = None):
        if kind == "cluster":
            if not self.has_cluster_privilege(user, privilege):
                raise SecurityException(
                    f"action [cluster:{privilege}] is unauthorized for user "
                    f"[{user.username}]")
        else:
            if not self.has_index_privilege(user, index or "*", privilege):
                raise SecurityException(
                    f"action [indices:{privilege}] is unauthorized for user "
                    f"[{user.username}], this action is granted by the "
                    f"index privileges [{privilege},all]")

    # --------------------------------------------------------------- DLS/FLS
    def dls_query(self, user: User, index: str) -> Optional[Dict[str, Any]]:
        """The role's DLS filter for `index` (None = unrestricted). Multiple
        matching role queries OR together (ref: DocumentSubsetReader — a doc
        is visible if any role's query matches)."""
        queries = []
        unrestricted = False
        for role in self._role_defs(user):
            for grp in role.get("indices", []):
                if not any(fnmatch.fnmatchcase(index, p)
                           for p in grp.get("names", [])):
                    continue
                q = grp.get("query")
                if q is None:
                    unrestricted = True
                else:
                    queries.append(json.loads(q) if isinstance(q, str) else q)
        if unrestricted or not queries:
            return None
        if len(queries) == 1:
            return queries[0]
        return {"bool": {"should": queries, "minimum_should_match": 1}}

    def fls_filter(self, user: User, index: str) -> Optional[Tuple[List[str], List[str]]]:
        """(grant, except) field patterns, or None when unrestricted."""
        grants: List[str] = []
        excepts: List[str] = []
        unrestricted = False
        for role in self._role_defs(user):
            for grp in role.get("indices", []):
                if not any(fnmatch.fnmatchcase(index, p)
                           for p in grp.get("names", [])):
                    continue
                fs = grp.get("field_security")
                if fs is None:
                    unrestricted = True
                else:
                    grants.extend(fs.get("grant", ["*"]))
                    excepts.extend(fs.get("except", []))
        if unrestricted or not grants:
            return None
        return grants, excepts

    @staticmethod
    def filter_source(source: Dict[str, Any],
                      fls: Optional[Tuple[List[str], List[str]]]) -> Dict[str, Any]:
        if fls is None:
            return source
        grant, excl = fls

        def allowed(path: str) -> bool:
            if any(fnmatch.fnmatchcase(path, e) for e in excl):
                return False
            return any(fnmatch.fnmatchcase(path, g) for g in grant)

        def walk(obj: Dict[str, Any], prefix="") -> Dict[str, Any]:
            out = {}
            for k, v in obj.items():
                p = f"{prefix}{k}"
                if isinstance(v, dict):
                    sub = walk(v, f"{p}.")
                    if sub or allowed(p):
                        out[k] = sub
                elif allowed(p):
                    out[k] = v
            return out

        return walk(source)


# ---------------------------------------------------------------------------
# route → required privilege (ref: the per-action privilege mapping the
# reference derives from action names; REST routes map onto it coarsely)
# ---------------------------------------------------------------------------

_CLUSTER_PREFIXES = {
    "_cluster": "monitor", "_nodes": "monitor", "_cat": "monitor",
    "_stats": "monitor", "_remote": "monitor",
    "_ilm": "manage_ilm", "_slm": "manage_slm", "_snapshot": "manage_slm",
    "_ingest": "manage_ingest_pipelines",
    "_template": "manage_index_templates",
    "_index_template": "manage_index_templates",
    "_component_template": "manage_index_templates",
    "_scripts": "manage", "_tasks": "monitor", "_ml": "manage_ml",
    "_transform": "manage_transform", "_watcher": "manage_watcher",
    "_ccr": "manage_ccr", "_enrich": "manage_enrich",
    "_rollup": "manage_rollup", "_migration": "monitor",
    "_features": "monitor", "_data_stream": "manage_index_templates",
    "_aliases": "manage_index_templates",
}

_READ_ENDPOINTS = {
    "_search", "_count", "_explain", "_mget", "_msearch", "_doc",
    "_source", "_termvectors", "_rank_eval", "_field_caps", "_validate",
    "_terms_enum", "_graph", "_eql", "_sql", "_async_search", "_pit",
    "_rollup_search",
    "_knn_search", "_percolate", "_scripts", "_analyze", "_mapping",
    "_settings", "_alias", "_segments", "_recovery", "_stats", "_ilm",
}

_WRITE_ENDPOINTS = {"_bulk", "_update", "_create", "_update_by_query",
                    "_delete_by_query", "_reindex", "_rollover", "_refresh",
                    "_flush", "_forcemerge", "_freeze", "_unfreeze",
                    "_open", "_close", "_shrink", "_split", "_clone"}


def required_privilege(method: str, path: str) -> Tuple[str, str, Optional[str]]:
    """(kind, privilege, index) for a REST request."""
    parts = [p for p in path.split("/") if p]
    if not parts:
        return ("cluster", "monitor", None)
    if parts[0] == "_security":
        if len(parts) >= 2 and parts[1] == "_authenticate":
            return ("cluster", "none", None)  # any authenticated user
        if len(parts) >= 2 and parts[1] == "oauth2":
            # the grant inside the body IS the authentication; the
            # request itself needs none (ref: RestGetTokenAction)
            return ("cluster", "none", None)
        if len(parts) >= 2 and parts[1] == "delegate_pki":
            return ("cluster", "delegate_pki", None)
        if len(parts) >= 2 and parts[1] == "api_key" and method == "POST":
            return ("cluster", "manage_api_key", None)
        return ("cluster", "manage_security", None)
    if parts[0].startswith("_"):
        if (parts[0] == "_cluster" and len(parts) >= 2
                and parts[1] == "settings" and method != "GET"):
            # settings writes are cluster administration, not monitoring
            return ("cluster", "manage", None)
        priv = _CLUSTER_PREFIXES.get(parts[0])
        if priv is None:
            # bare endpoints like /_search, /_bulk, /_mget run over indices
            if parts[0] in _READ_ENDPOINTS:
                return ("index", "read", "*")
            if parts[0] in _WRITE_ENDPOINTS:
                return ("index", "write", "*")
            return ("cluster", "monitor", None)
        return ("cluster", priv, None)
    index = parts[0]
    if len(parts) == 1:
        if method == "PUT":
            return ("index", "create_index", index)
        if method == "DELETE":
            return ("index", "delete_index", index)
        return ("index", "view_index_metadata", index)
    endpoint = next((p for p in parts[1:] if p.startswith("_")), None)
    if endpoint in ("_doc", "_create", "_update") and method in (
            "PUT", "POST", "DELETE"):
        return ("index", "write", index)
    if endpoint in _WRITE_ENDPOINTS:
        return ("index", "write", index)
    if endpoint in _READ_ENDPOINTS:
        if endpoint in ("_mapping", "_settings") and method in ("PUT", "POST"):
            return ("index", "manage", index)
        return ("index", "read", index)
    return ("index", "manage", index)
