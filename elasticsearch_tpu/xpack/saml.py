"""SAML 2.0 SP realm + IdP — the web-SSO half of the security stack.

Reference parity:
- SP realm: ref x-pack/plugin/security/src/main/java/org/elasticsearch/
  xpack/security/authc/saml/SamlRealm.java (realm wiring, settings),
  SamlAuthenticator.java (response/assertion validation and attribute
  extraction), SamlAuthnRequestBuilder.java + SamlRedirect.java
  (AuthnRequest via the redirect binding: deflate+base64+URL-encode),
  SamlLogoutRequestMessageBuilder.java (SP-initiated logout).
- REST surface: ref RestSamlPrepareAuthenticationAction /
  RestSamlAuthenticateAction / RestSamlInvalidateSessionAction (the
  /_security/saml/* APIs that a web front calls — ES itself is the SP
  but the browser dance happens outside, so these are JSON APIs, not
  redirect endpoints).
- IdP: ref x-pack/plugin/identity-provider/ (SamlIdentityProviderPlugin
  — a minimal IdP that issues signed assertions for registered SPs).

The XML signature core is common/xmldsig.py (enveloped RSA-SHA256; its
canonicalization divergence from exc-c14n 1.0 is disclosed there).

Validation rules carried over from SamlAuthenticator/SamlResponseHandler:
- the Response's Issuer must match the configured IdP entity id;
- a signature is REQUIRED on the Response or on the Assertion (an
  unsigned pair is rejected outright);
- Conditions/NotBefore..NotOnOrAfter bound the clock (with skew),
- AudienceRestriction must contain the SP entity id;
- InResponseTo (when present) must match an outstanding request id the
  caller supplies (ref: SamlAuthenticator checks allowedSamlRequestIds);
- Status/StatusCode must be success;
- SubjectConfirmationData Recipient must be the SP's ACS (when present).
"""

from __future__ import annotations

import base64
import datetime
import os
import secrets
import time
import zlib
from typing import Any, Dict, List, Optional
from xml.etree import ElementTree as ET

from elasticsearch_tpu.common.xmldsig import (XmlSignatureError,
                                              load_cert_public_key,
                                              sign_element,
                                              verify_enveloped)

SAML_NS = "urn:oasis:names:tc:SAML:2.0:assertion"
SAMLP_NS = "urn:oasis:names:tc:SAML:2.0:protocol"
STATUS_SUCCESS = "urn:oasis:names:tc:SAML:2.0:status:Success"
NAMEID_TRANSIENT = "urn:oasis:names:tc:SAML:2.0:nameid-format:transient"
BEARER = "urn:oasis:names:tc:SAML:2.0:cm:bearer"


class SamlException(Exception):
    pass


def _a(tag):
    return f"{{{SAML_NS}}}{tag}"


def _p(tag):
    return f"{{{SAMLP_NS}}}{tag}"


def _now():
    return datetime.datetime.now(datetime.timezone.utc)


def _ts(dt) -> str:
    return dt.strftime("%Y-%m-%dT%H:%M:%SZ")


def _parse_ts(s: str) -> float:
    """xs:dateTime → epoch seconds; honors fractional seconds and
    numeric timezone offsets; raises SamlException on garbage."""
    try:
        t = s.strip()
        if t.endswith("Z"):
            t = t[:-1] + "+00:00"
        dt = datetime.datetime.fromisoformat(t)
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=datetime.timezone.utc)
        return dt.timestamp()
    except ValueError:
        raise SamlException(f"invalid SAML timestamp [{s}]")


def _rand_id() -> str:
    return "_" + secrets.token_hex(16)


class SpConfig:
    """SP-side settings (ref: SpConfiguration.java — entity_id, ACS,
    logout endpoint)."""

    def __init__(self, entity_id: str, acs: str,
                 logout: Optional[str] = None):
        self.entity_id = entity_id
        self.acs = acs
        self.logout = logout


class SamlAuthnFlow:
    """The SP protocol engine shared by the realm and tests.

    clock_skew: tolerated seconds on NotBefore/NotOnOrAfter (ref:
    SamlRealmSettings.CLOCK_SKEW, default 3m)."""

    def __init__(self, sp: SpConfig, idp_entity_id: str,
                 idp_cert_pem: str, clock_skew: float = 180.0):
        self.sp = sp
        self.idp_entity_id = idp_entity_id
        self._idp_key = load_cert_public_key(idp_cert_pem)
        self.clock_skew = clock_skew

    # ------------------------------------------------------------ prepare
    def build_authn_request(self, idp_sso_url: str) -> Dict[str, str]:
        """(id, redirect_url) for the redirect binding: the AuthnRequest
        XML, deflated (raw), base64'd, URL-escaped onto the SSO URL
        (ref: SamlRedirect.getRedirectUrl)."""
        import urllib.parse
        rid = _rand_id()
        req = ET.Element(_p("AuthnRequest"), {
            "ID": rid, "Version": "2.0", "IssueInstant": _ts(_now()),
            "Destination": idp_sso_url,
            "AssertionConsumerServiceURL": self.sp.acs,
            "ProtocolBinding":
                "urn:oasis:names:tc:SAML:2.0:bindings:HTTP-POST"})
        iss = ET.SubElement(req, _a("Issuer"))
        iss.text = self.sp.entity_id
        xml = ET.tostring(req)
        deflated = zlib.compress(xml, 9)[2:-4]     # raw DEFLATE
        param = urllib.parse.quote_plus(base64.b64encode(deflated))
        sep = "&" if "?" in idp_sso_url else "?"
        return {"id": rid,
                "redirect": f"{idp_sso_url}{sep}SAMLRequest={param}"}

    # ------------------------------------------------------- authenticate
    def authenticate(self, content_b64: str,
                     allowed_request_ids: Optional[List[str]] = None
                     ) -> Dict[str, Any]:
        """Validate a base64 SAMLResponse; returns {principal, nameid,
        session_index, attributes{name: [values]}} or raises
        SamlException (ref: SamlAuthenticator.authenticate)."""
        try:
            xml = base64.b64decode(content_b64, validate=True)
        except Exception:
            raise SamlException("SAML content is not valid base64")
        try:
            root = ET.fromstring(xml)
        except ET.ParseError as e:
            raise SamlException(f"SAML content is not valid XML: {e}")
        if root.tag != _p("Response"):
            raise SamlException(
                f"SAML content root [{root.tag}] is not a "
                f"samlp:Response")
        status = root.find(f"{_p('Status')}/{_p('StatusCode')}")
        if status is None or status.get("Value") != STATUS_SUCCESS:
            raise SamlException("SAML response status is not success")
        irt = root.get("InResponseTo")
        if irt and allowed_request_ids is not None \
                and irt not in allowed_request_ids:
            raise SamlException(
                f"SAML response InResponseTo [{irt}] does not match any "
                f"outstanding request id")
        iss = root.find(_a("Issuer"))
        if iss is not None and (iss.text or "").strip() \
                and iss.text.strip() != self.idp_entity_id:
            raise SamlException(
                f"SAML response issuer [{iss.text.strip()}] does not "
                f"match the configured IdP [{self.idp_entity_id}]")

        response_signed = False
        if root.find(f"{{{'http://www.w3.org/2000/09/xmldsig#'}}}"
                     "Signature") is not None:
            try:
                verify_enveloped(root, self._idp_key)
                response_signed = True
            except XmlSignatureError as e:
                raise SamlException(f"SAML response signature: {e}")

        assertions = root.findall(_a("Assertion"))
        if len(assertions) != 1:
            raise SamlException(
                f"SAML response contains {len(assertions)} assertions "
                f"(expected exactly 1)")
        assertion = assertions[0]
        if not response_signed:
            try:
                verify_enveloped(assertion, self._idp_key)
            except XmlSignatureError as e:
                raise SamlException(f"SAML assertion signature: {e}")

        a_iss = assertion.find(_a("Issuer"))
        if a_iss is not None and (a_iss.text or "").strip() != \
                self.idp_entity_id:
            raise SamlException("SAML assertion issuer mismatch")
        self._check_conditions(assertion)
        self._check_subject(assertion)

        nameid_el = assertion.find(f"{_a('Subject')}/{_a('NameID')}")
        nameid = (nameid_el.text or "").strip() \
            if nameid_el is not None else None
        authn = assertion.find(_a("AuthnStatement"))
        session_index = authn.get("SessionIndex") \
            if authn is not None else None
        attrs: Dict[str, List[str]] = {}
        for att in assertion.findall(
                f"{_a('AttributeStatement')}/{_a('Attribute')}"):
            name = att.get("Name") or ""
            vals = [(v.text or "").strip()
                    for v in att.findall(_a("AttributeValue"))]
            attrs.setdefault(name, []).extend(vals)
        aid = assertion.get("ID")
        if not aid:
            # the schema requires ID; without one replay tracking is
            # impossible, so the assertion is unacceptable
            raise SamlException("SAML assertion has no ID attribute")
        # the latest instant this assertion is acceptable (drives the
        # consumer's replay-table retention)
        expiries = []
        cond = assertion.find(_a("Conditions"))
        if cond is not None and cond.get("NotOnOrAfter"):
            expiries.append(_parse_ts(cond.get("NotOnOrAfter")))
        scd = assertion.find(
            f"{_a('Subject')}/{_a('SubjectConfirmation')}"
            f"/{_a('SubjectConfirmationData')}")
        if scd is not None and scd.get("NotOnOrAfter"):
            expiries.append(_parse_ts(scd.get("NotOnOrAfter")))
        return {"principal": nameid, "nameid": nameid,
                "session_index": session_index, "attributes": attrs,
                "assertion_id": aid,
                "not_on_or_after": min(expiries) + self.clock_skew,
                "in_response_to": irt}

    def _check_conditions(self, assertion):
        """An assertion with no Conditions would be valid forever and
        for every SP — REQUIRED, with an expiry and a matching audience
        (ref: SamlAuthenticator.checkConditions rejects assertions
        whose conditions are absent/expired/mis-audienced)."""
        cond = assertion.find(_a("Conditions"))
        now = time.time()
        if cond is None:
            raise SamlException("SAML assertion has no Conditions")
        nb = cond.get("NotBefore")
        if nb and now + self.clock_skew < _parse_ts(nb):
            raise SamlException("SAML assertion is not yet valid "
                                "(NotBefore)")
        noa = cond.get("NotOnOrAfter")
        if not noa:
            raise SamlException(
                "SAML assertion Conditions carry no NotOnOrAfter")
        if now - self.clock_skew >= _parse_ts(noa):
            raise SamlException("SAML assertion has expired "
                                "(NotOnOrAfter)")
        auds = [((a.text or "").strip()) for a in cond.findall(
            f"{_a('AudienceRestriction')}/{_a('Audience')}")]
        if self.sp.entity_id not in auds:
            raise SamlException(
                f"SAML assertion audience {auds} does not include "
                f"the SP [{self.sp.entity_id}]")

    def _check_subject(self, assertion):
        """Bearer confirmation with a bounded, ACS-addressed
        SubjectConfirmationData is REQUIRED (ref:
        SamlAuthenticator.checkSubject — bearer assertions without a
        NotOnOrAfter-bearing SubjectConfirmationData are rejected)."""
        scd = assertion.find(
            f"{_a('Subject')}/{_a('SubjectConfirmation')}"
            f"/{_a('SubjectConfirmationData')}")
        if scd is None:
            raise SamlException(
                "SAML assertion has no SubjectConfirmationData")
        rec = scd.get("Recipient")
        if rec and rec != self.sp.acs:
            raise SamlException(
                f"SAML SubjectConfirmationData recipient [{rec}] is not "
                f"the SP ACS [{self.sp.acs}]")
        noa = scd.get("NotOnOrAfter")
        if not noa:
            raise SamlException(
                "SAML SubjectConfirmationData carries no NotOnOrAfter")
        if time.time() - self.clock_skew >= _parse_ts(noa):
            raise SamlException(
                "SAML subject confirmation has expired")


# ---------------------------------------------------------------------------
# Identity provider (ref: x-pack/plugin/identity-provider — the IdP that
# issues signed assertions to registered service providers)
# ---------------------------------------------------------------------------

class SamlIdentityProvider:
    """SAML IdP (ref: x-pack/plugin/identity-provider — the
    SamlIdentityProviderPlugin): registered SPs (entity id → ACS),
    signed Response+Assertion issuance for an authenticated principal
    (ref: .../saml/authn/SuccessfulAuthenticationResponseMessageBuilder
    .java), IdP metadata and AuthnRequest validation for the
    /_idp/saml/* APIs (RestSamlInitiateSingleSignOnAction,
    RestSamlMetadataAction, RestSamlValidateAuthenticationRequestAction,
    RestPutSamlServiceProviderAction paths)."""

    def __init__(self, entity_id: str, private_key_pem: bytes,
                 cert_pem: str, session_ttl: float = 300.0,
                 sso_url: str = ""):
        from cryptography.hazmat.primitives import serialization
        self.entity_id = entity_id
        self._key = serialization.load_pem_private_key(
            private_key_pem, password=None)
        self._cert_pem = cert_pem
        self.session_ttl = session_ttl
        self.sso_url = sso_url
        self._sps: Dict[str, Dict[str, Any]] = {}

    def register_sp(self, entity_id: str, acs: str,
                    attributes: Optional[Dict[str, str]] = None):
        """ref: identity-provider PutSamlServiceProviderAction."""
        self._sps[entity_id] = {"acs": acs,
                                "attributes": attributes or {}}

    def delete_sp(self, entity_id: str) -> bool:
        """ref: DeleteSamlServiceProviderAction."""
        return self._sps.pop(entity_id, None) is not None

    def sp_registered(self, entity_id: str) -> bool:
        return entity_id in self._sps

    def sp_acs(self, entity_id: str) -> Optional[str]:
        sp = self._sps.get(entity_id)
        return sp["acs"] if sp else None

    def metadata_xml(self, sp_entity_id: str) -> str:
        """IdP EntityDescriptor for a registered SP (ref:
        SamlMetadataAction → EntityDescriptor with IDPSSODescriptor +
        signing KeyDescriptor)."""
        if sp_entity_id not in self._sps:
            raise SamlException(
                f"service provider [{sp_entity_id}] is not registered")
        md = "urn:oasis:names:tc:SAML:2.0:metadata"
        ds = "http://www.w3.org/2000/09/xmldsig#"
        ed = ET.Element(f"{{{md}}}EntityDescriptor",
                        {"entityID": self.entity_id})
        idp = ET.SubElement(ed, f"{{{md}}}IDPSSODescriptor", {
            "protocolSupportEnumeration":
                "urn:oasis:names:tc:SAML:2.0:protocol"})
        kd = ET.SubElement(idp, f"{{{md}}}KeyDescriptor",
                           {"use": "signing"})
        ki = ET.SubElement(kd, f"{{{ds}}}KeyInfo")
        xd = ET.SubElement(ki, f"{{{ds}}}X509Data")
        xc = ET.SubElement(xd, f"{{{ds}}}X509Certificate")
        xc.text = "".join(
            line for line in self._cert_pem.strip().splitlines()
            if "CERTIFICATE" not in line)
        ET.SubElement(idp, f"{{{md}}}SingleSignOnService", {
            "Binding":
                "urn:oasis:names:tc:SAML:2.0:bindings:HTTP-Redirect",
            "Location": self.sso_url or ""})
        return ET.tostring(ed, encoding="unicode")

    def validate_authn_request(self, saml_request_b64: str
                               ) -> Dict[str, Any]:
        """Decode+validate a redirect-binding SAMLRequest (ref:
        SamlValidateAuthenticationRequestAction): the issuer must be a
        registered SP and the ACS must match its registration."""
        try:
            xml = zlib.decompress(base64.b64decode(saml_request_b64),
                                  -15)
            root = ET.fromstring(xml)
        except Exception:
            raise SamlException("malformed SAMLRequest")
        if root.tag != _p("AuthnRequest"):
            raise SamlException("SAMLRequest is not an AuthnRequest")
        iss = root.find(_a("Issuer"))
        sp_id = (iss.text or "").strip() if iss is not None else ""
        sp = self._sps.get(sp_id)
        if sp is None:
            raise SamlException(
                f"service provider [{sp_id}] is not registered")
        acs = root.get("AssertionConsumerServiceURL")
        if acs and acs != sp["acs"]:
            raise SamlException(
                f"AuthnRequest ACS [{acs}] does not match the "
                f"registered ACS for [{sp_id}]")
        return {"authn_state": {"entity_id": sp_id,
                                "acs": sp["acs"],
                                "authn_request_id": root.get("ID")}}

    def issue_response(self, sp_entity_id: str, principal: str,
                       groups: Optional[List[str]] = None,
                       in_response_to: Optional[str] = None,
                       sign_assertion_only: bool = False) -> str:
        """base64 samlp:Response with a signed assertion for the SP."""
        sp = self._sps.get(sp_entity_id)
        if sp is None:
            raise SamlException(
                f"service provider [{sp_entity_id}] is not registered")
        now = _now()
        later = now + datetime.timedelta(seconds=self.session_ttl)
        resp_attrs = {"ID": _rand_id(), "Version": "2.0",
                      "IssueInstant": _ts(now),
                      "Destination": sp["acs"]}
        if in_response_to:
            resp_attrs["InResponseTo"] = in_response_to
        resp = ET.Element(_p("Response"), resp_attrs)
        riss = ET.SubElement(resp, _a("Issuer"))
        riss.text = self.entity_id
        st = ET.SubElement(resp, _p("Status"))
        ET.SubElement(st, _p("StatusCode"), {"Value": STATUS_SUCCESS})

        asrt = ET.Element(_a("Assertion"), {
            "ID": _rand_id(), "Version": "2.0", "IssueInstant": _ts(now)})
        aiss = ET.SubElement(asrt, _a("Issuer"))
        aiss.text = self.entity_id
        subj = ET.SubElement(asrt, _a("Subject"))
        nid = ET.SubElement(subj, _a("NameID"),
                            {"Format": NAMEID_TRANSIENT})
        nid.text = principal
        sc = ET.SubElement(subj, _a("SubjectConfirmation"),
                           {"Method": BEARER})
        scd_attrs = {"Recipient": sp["acs"], "NotOnOrAfter": _ts(later)}
        if in_response_to:
            scd_attrs["InResponseTo"] = in_response_to
        ET.SubElement(sc, _a("SubjectConfirmationData"), scd_attrs)
        cond = ET.SubElement(asrt, _a("Conditions"), {
            "NotBefore": _ts(now - datetime.timedelta(seconds=5)),
            "NotOnOrAfter": _ts(later)})
        ar = ET.SubElement(cond, _a("AudienceRestriction"))
        aud = ET.SubElement(ar, _a("Audience"))
        aud.text = sp_entity_id
        ET.SubElement(asrt, _a("AuthnStatement"), {
            "AuthnInstant": _ts(now),
            "SessionIndex": _rand_id()})
        if groups:
            ast = ET.SubElement(asrt, _a("AttributeStatement"))
            att = ET.SubElement(ast, _a("Attribute"),
                                {"Name": "groups"})
            for g in groups:
                v = ET.SubElement(att, _a("AttributeValue"))
                v.text = g
        sign_element(asrt, self._key, self._cert_pem)
        resp.append(asrt)
        if not sign_assertion_only:
            sign_element(resp, self._key, self._cert_pem)
        return base64.b64encode(ET.tostring(resp)).decode()
