"""Index lifecycle management (ILM).

ref: x-pack/plugin/ilm — IndexLifecycleService drives a per-index step
state machine (IndexLifecycleRunner.java:41,326, PolicyStepsRegistry):
a policy defines phases (hot → warm → cold → delete), each entered after
``min_age`` and executing its actions as idempotent steps recorded in the
index's lifecycle execution state.

The reference stores execution state in IndexMetadata customs and advances
on cluster-state changes + a periodic trigger; here the state lives in the
index settings (``index.lifecycle.*`` execution keys) and `tick(now)`
advances every managed index — callable from a scheduler thread in
production and directly (with an injected clock) in tests, which keeps the
state machine deterministic the way the reference's
DeterministicTaskQueue-driven ILM tests are.

Supported actions per phase (the reference's core set minus
allocate/migrate routing, which are no-ops single-node):
  hot:    rollover, set_priority, forcemerge
  warm:   readonly, forcemerge, shrink, set_priority, allocate(no-op)
  cold:   freeze, searchable_snapshot (snapshot → drop local copy →
          LAZY cache-backed remount, xpack/searchable_snapshots.py),
          set_priority, allocate(no-op)
  delete: wait_for_snapshot, delete
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ResourceNotFoundException,
)

PHASE_ORDER = ["hot", "warm", "cold", "delete"]

_ACTION_ORDER = {
    # execution order within a phase (ref: the per-phase step order in
    # TimeseriesLifecycleType.ORDERED_VALID_*_ACTIONS)
    "hot": ["set_priority", "rollover", "forcemerge"],
    "warm": ["set_priority", "readonly", "allocate", "shrink", "forcemerge"],
    "cold": ["set_priority", "allocate", "freeze", "searchable_snapshot"],
    "delete": ["wait_for_snapshot", "delete"],
}

_VALID_ACTIONS = {a for acts in _ACTION_ORDER.values() for a in acts}


def parse_time_ms(v: Any) -> float:
    """"30d" / "1h" / "0ms" / 5000 → milliseconds."""
    if isinstance(v, (int, float)):
        return float(v)
    m = re.match(r"^\s*(\d+(?:\.\d+)?)\s*(d|h|m|s|ms|micros|nanos)\s*$", str(v))
    if not m:
        raise IllegalArgumentException(f"failed to parse time value [{v}]")
    n = float(m.group(1))
    mult = {"d": 86400_000, "h": 3600_000, "m": 60_000, "s": 1000,
            "ms": 1, "micros": 1e-3, "nanos": 1e-6}[m.group(2)]
    return n * mult


class IndexLifecycleService:
    """Policy registry + per-index state machine runner."""

    def __init__(self, indices_service, metadata_service,
                 repositories_service=None, data_path: Optional[str] = None,
                 slm_service=None):
        self.indices = indices_service
        self.metadata = metadata_service
        self.repositories = repositories_service
        self.slm = slm_service
        self.running = True
        self._policies: Dict[str, Dict[str, Any]] = {}
        self._path = (os.path.join(data_path, "_ilm_policies.json")
                      if data_path else None)
        if self._path and os.path.exists(self._path):
            with open(self._path) as fh:
                self._policies = json.load(fh)

    # ------------------------------------------------------------ registry
    def _persist(self):
        if self._path:
            tmp = self._path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(self._policies, fh)
            os.replace(tmp, self._path)

    def put_policy(self, name: str, body: Dict[str, Any]):
        policy = body.get("policy", body)
        phases = policy.get("phases")
        if not isinstance(phases, dict) or not phases:
            raise IllegalArgumentException(
                "policy must define at least one phase")
        for phase, spec in phases.items():
            if phase not in PHASE_ORDER:
                raise IllegalArgumentException(
                    f"lifecycle type [timeseries] does not support phase "
                    f"[{phase}]")
            for action in spec.get("actions", {}):
                if action not in _VALID_ACTIONS:
                    raise IllegalArgumentException(
                        f"invalid action [{action}] defined in phase "
                        f"[{phase}]")
                if action not in _ACTION_ORDER[phase]:
                    raise IllegalArgumentException(
                        f"invalid action [{action}] defined in phase "
                        f"[{phase}]")
        prev = self._policies.get(name)
        self._policies[name] = {
            "policy": {"phases": phases},
            "version": (prev["version"] + 1) if prev else 1,
            "modified_date": int(time.time() * 1000),
        }
        self._persist()

    def get_policy(self, name: Optional[str] = None) -> Dict[str, Any]:
        if name is None:
            return dict(self._policies)
        if name not in self._policies:
            raise ResourceNotFoundException(f"Lifecycle policy not found: {name}")
        return {name: self._policies[name]}

    def delete_policy(self, name: str):
        if name not in self._policies:
            raise ResourceNotFoundException(f"Lifecycle policy not found: {name}")
        using = [idx for idx in self.indices.indices.values()
                 if idx.settings.get("index.lifecycle.name") == name]
        if using:
            raise IllegalArgumentException(
                f"Cannot delete policy [{name}]. It is in use by one or "
                f"more indices: {[i.name for i in using]}")
        del self._policies[name]
        self._persist()

    # ------------------------------------------------------------ lifecycle
    def start(self):
        self.running = True

    def stop(self):
        self.running = False

    def status(self) -> str:
        return "RUNNING" if self.running else "STOPPED"

    # ---------------------------------------------------------- state rows
    def _state(self, idx) -> Dict[str, Any]:
        s = idx.settings
        return {
            "policy": s.get("index.lifecycle.name"),
            "phase": s.get("index.lifecycle.phase"),
            "action": s.get("index.lifecycle.action"),
            "step": s.get("index.lifecycle.step"),
            "phase_time": s.get("index.lifecycle.phase_time"),
            "failed_step": s.get("index.lifecycle.failed_step"),
            "step_info": s.get("index.lifecycle.step_info"),
        }

    def _set_state(self, idx, **kv):
        idx.update_settings({f"index.lifecycle.{k}": v
                             for k, v in kv.items()})

    def remove_policy(self, index_name: str) -> bool:
        idx = self.indices.get(index_name)
        had = idx.settings.get("index.lifecycle.name") is not None
        merged = {k: v for k, v in idx.settings.as_dict().items()
                  if not k.startswith("index.lifecycle.")}
        from elasticsearch_tpu.common.settings import Settings
        idx.settings = Settings(merged)
        idx._persist_meta()
        return had

    def retry(self, index_name: str):
        """Re-run a failed step (ref: TransportRetryAction)."""
        idx = self.indices.get(index_name)
        self._set_state(idx, failed_step=None, step_info=None, step="check")

    def explain(self, index_name: str, now: Optional[float] = None) -> Dict[str, Any]:
        idx = self.indices.get(index_name)
        st = self._state(idx)
        managed = st["policy"] is not None
        out: Dict[str, Any] = {"index": index_name, "managed": managed}
        if not managed:
            return out
        now_ms = (now if now is not None else time.time()) * 1000
        origin = self._age_origin_ms(idx)
        out.update({
            "policy": st["policy"],
            "phase": st["phase"],
            "action": st["action"],
            "step": st["step"] or "complete",
            "age": f"{max(0.0, (now_ms - origin) / 1000):.2f}s",
            "lifecycle_date_millis": origin,
        })
        if st["failed_step"]:
            out["failed_step"] = st["failed_step"]
            out["step_info"] = st["step_info"]
        return out

    def _age_origin_ms(self, idx) -> float:
        # age counts from rollover when the index has rolled over, else from
        # creation (ref: IndexLifecycleExplainResponse.getLifecycleDate)
        ro = idx.settings.get("index.lifecycle.indexing_complete_date")
        if ro is not None:
            return float(ro)
        return float(idx.settings.get("index.creation_date", 0))

    # ------------------------------------------------------------- runner
    def tick(self, now: Optional[float] = None):
        """Advance every managed index one scheduler pass."""
        if not self.running:
            return
        now = now if now is not None else time.time()
        for name in list(self.indices.indices):
            idx = self.indices.indices.get(name)
            if idx is None:
                continue
            policy_name = idx.settings.get("index.lifecycle.name")
            if policy_name is None or policy_name not in self._policies:
                continue
            if idx.settings.get("index.lifecycle.failed_step"):
                continue  # parked until retry
            try:
                self._advance(idx, policy_name, now)
            except Exception as e:  # park the index on its failed step
                st = self._state(idx)
                self._set_state(
                    idx, failed_step=st.get("action") or "unknown",
                    step_info=json.dumps({"type": type(e).__name__,
                                          "reason": str(e)}))

    def _advance(self, idx, policy_name: str, now: float):
        phases = self._policies[policy_name]["policy"]["phases"]
        st = self._state(idx)
        phase = st["phase"]
        now_ms = now * 1000

        if phase is None:
            # enter the first defined phase whose min_age has passed
            phase = self._next_phase(None, phases, idx, now_ms)
            if phase is None:
                return
            self._enter_phase(idx, phase, now_ms)

        while True:
            # execute remaining actions of the current phase
            actions = phases.get(phase, {}).get("actions", {})
            for action in _ACTION_ORDER[phase]:
                if action not in actions:
                    continue
                done_key = f"index.lifecycle.done.{phase}.{action}"
                if idx.settings.get(done_key):
                    continue
                finished = self._run_action(idx, phase, action,
                                            actions[action], now_ms)
                if not finished:
                    return  # waiting (e.g. rollover conditions not met)
                if not self.indices.has(idx.name):
                    return  # the delete action removed the index
                # actions may REPLACE the index object (shrink swaps,
                # searchable_snapshot remounts) — re-resolve before
                # recording completion
                idx = self.indices.get(idx.name)
                idx.update_settings({done_key: True})

            # all actions done → move to the next ripe phase this tick
            nxt = self._next_phase(phase, phases, idx, now_ms)
            if nxt is None:
                return
            self._enter_phase(idx, nxt, now_ms)
            phase = nxt

    def _enter_phase(self, idx, phase: str, now_ms: float):
        self._set_state(idx, phase=phase, phase_time=now_ms, step="check",
                        action=None)

    def _next_phase(self, current: Optional[str], phases: Dict[str, Any],
                    idx, now_ms: float) -> Optional[str]:
        start = 0 if current is None else PHASE_ORDER.index(current) + 1
        age_ms = now_ms - self._age_origin_ms(idx)
        for phase in PHASE_ORDER[start:]:
            if phase not in phases:
                continue
            min_age = parse_time_ms(phases[phase].get("min_age", 0))
            return phase if age_ms >= min_age else None
        return None

    # ------------------------------------------------------------- actions
    def _run_action(self, idx, phase: str, action: str,
                    spec: Dict[str, Any], now_ms: float) -> bool:
        """Execute one action; returns True when complete (idempotent —
        each reference action is a sequence of retryable steps)."""
        self._set_state(idx, action=action)
        if action == "rollover":
            return self._action_rollover(idx, spec, now_ms)
        if action == "set_priority":
            idx.update_settings({
                "index.priority": int(spec.get("priority", 1))})
            return True
        if action == "readonly":
            idx.update_settings({"index.blocks.write": True})
            return True
        if action == "allocate":
            # routing is a no-op without multi-node allocation filters here;
            # number_of_replicas updates apply
            if "number_of_replicas" in spec:
                idx.update_settings({"index.number_of_replicas":
                                     int(spec["number_of_replicas"])})
            return True
        if action == "forcemerge":
            idx.force_merge(int(spec.get("max_num_segments", 1)))
            return True
        if action == "shrink":
            return self._action_shrink(idx, spec)
        if action == "freeze":
            idx.update_settings({"index.frozen": True,
                                 "index.blocks.write": True})
            return True
        if action == "searchable_snapshot":
            repo = spec.get("snapshot_repository")
            if self.repositories is None or not repo:
                raise IllegalArgumentException(
                    "[searchable_snapshot] requires [snapshot_repository]")
            # the REAL mount semantics (ref: the ILM
            # SearchableSnapshotAction step sequence: snapshot → mount →
            # swap): snapshot the index, drop the local copy, and
            # re-open it as a LAZY snapshot-backed mount — local storage
            # is released and segments stream back in on first search.
            # `force_merge_index:false`-style knobs: storage defaults to
            # shared_cache for the frozen tier semantics.
            from elasticsearch_tpu.xpack import searchable_snapshots as ss
            name = idx.name
            snap = f"ilm-{name}-{int(now_ms)}"
            storage = spec.get("storage", "full_copy")
            self.repositories.get_repository(repo).snapshot(snap, [idx])
            # Mount under a TEMPORARY name before deleting the local
            # copy (ref: the SearchableSnapshotAction step sequence
            # mounts the restored copy before swapping away the
            # original) — a repository/validation failure here leaves
            # the original index untouched instead of stranding the
            # data inside the just-taken snapshot.
            tmp = f"{name}-ilm-mounting"
            if self.indices.has(tmp):
                # leftover from a crashed earlier tick — clear it so the
                # retry doesn't wedge on ResourceAlreadyExists forever
                self.indices.delete_index(tmp)
            ss.mount_services(self.repositories, self.indices, repo,
                              snap, name, tmp, storage=storage)
            self.indices.delete_index(name)
            # if this re-mount fails the temp mount survives, so the
            # data stays searchable under `tmp` while the tick errors
            ss.mount_services(self.repositories, self.indices, repo,
                              snap, name, name, storage=storage)
            self.indices.delete_index(tmp)
            return True
        if action == "wait_for_snapshot":
            policy = spec.get("policy")
            if self.slm is None or policy is None:
                return True
            stats = self.slm._stats.get(policy, {})
            return stats.get("snapshots_taken", 0) > 0
        if action == "delete":
            self.indices.delete_index(idx.name)
            return True
        raise IllegalArgumentException(f"unknown ILM action [{action}]")

    def _action_rollover(self, idx, spec: Dict[str, Any],
                         now_ms: float) -> bool:
        alias = idx.settings.get("index.lifecycle.rollover_alias")
        if alias is None:
            raise IllegalArgumentException(
                f"setting [index.lifecycle.rollover_alias] for index "
                f"[{idx.name}] is empty or not defined")
        # only the current write index rolls over
        if self.metadata.write_target(alias) != idx.name:
            return True
        conditions = {k if k.startswith("max_") else f"max_{k}": v
                      for k, v in spec.items()}
        result = self.metadata.rollover(alias, {"conditions": conditions})
        if not result.get("rolled_over"):
            return False
        idx.update_settings(
            {"index.lifecycle.indexing_complete": True,
             "index.lifecycle.indexing_complete_date": now_ms})
        return True

    def _action_shrink(self, idx, spec: Dict[str, Any]) -> bool:
        from elasticsearch_tpu.index.metadata import resize_index
        target_shards = int(spec.get("number_of_shards", 1))
        if idx.num_shards <= target_shards:
            return True  # nothing to shrink
        target_name = f"shrink-{idx.name}"
        if self.indices.has(target_name):
            return True
        idx.update_settings({"index.blocks.write": True})
        resize_index(self.indices, idx.name, target_name,
                     {"settings": {"index.number_of_shards": target_shards}},
                     mode="shrink")
        # carry the policy over to the shrunken index, minus the shrink
        # action's own phase progress (ref: ShrinkAction copies execution
        # state and swaps aliases)
        tgt = self.indices.get(target_name)
        carry = {k: v for k, v in idx.settings.as_dict().items()
                 if k.startswith("index.lifecycle.")}
        carry[f"index.lifecycle.done.warm.shrink"] = True
        tgt.update_settings(carry)
        self.indices.delete_index(idx.name)
        return True
