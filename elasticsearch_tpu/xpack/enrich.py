"""Enrich: lookup-join enrichment at ingest time.

Mirrors the reference's x-pack enrich plugin (ref: x-pack/plugin/enrich —
EnrichPolicy (match/range types), the policy executor that force-merges a
lookup copy into a `.enrich-*` system index, and the `enrich` ingest
processor doing the join; SURVEY.md §2.6). Re-design for this engine:
policy execution snapshots the source docs into a `.enrich-{policy}`
system index AND a host-side hash map (match_field value → enrich doc) —
the ingest-time join is a dict lookup, the analogue of the reference's
term query against the force-merged single-segment enrich index.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ResourceAlreadyExistsException,
    ResourceNotFoundException,
)
from elasticsearch_tpu.ingest.service import processor


class EnrichService:
    def __init__(self, node):
        self.node = node
        self.policies: Dict[str, Dict[str, Any]] = {}
        # policy -> match value -> enrich doc (the executed lookup table)
        self.lookups: Dict[str, Dict[Any, Dict[str, Any]]] = {}
        # policy -> list of (low, high, doc) for range policies
        self.range_lookups: Dict[str, List] = {}
        self._lock = threading.Lock()

    def put_policy(self, name: str, body: Dict[str, Any]):
        with self._lock:
            if name in self.policies:
                raise ResourceAlreadyExistsException(
                    f"policy [{name}] already exists")
            ptype = "match" if "match" in body else (
                "range" if "range" in body else None)
            if ptype is None:
                raise IllegalArgumentException(
                    "policy requires [match] or [range]")
            cfg = body[ptype]
            for req in ("indices", "match_field", "enrich_fields"):
                if req not in cfg:
                    raise IllegalArgumentException(f"[{req}] is required")
            self.policies[name] = {"name": name, "type": ptype,
                                   "config": cfg}
            return {"acknowledged": True}

    def get_policy(self, name: str) -> Dict[str, Any]:
        p = self.policies.get(name)
        if p is None:
            raise ResourceNotFoundException(
                f"policy [{name}] not found")
        return p

    def delete_policy(self, name: str):
        self.get_policy(name)
        with self._lock:
            del self.policies[name]
            self.lookups.pop(name, None)
            self.range_lookups.pop(name, None)
        return {"acknowledged": True}

    def list_policies(self) -> List[Dict[str, Any]]:
        out = []
        for p in self.policies.values():
            out.append({p["type"]: {
                "name": p["name"], **p["config"]}})
        return out

    def execute_policy(self, name: str):
        """Build the enrich index + lookup table from the source indices
        (ref: EnrichPolicyRunner — reindex into .enrich-* then force
        merge; here the merged artifact IS the hash map)."""
        p = self.get_policy(name)
        cfg = p["config"]
        indices = cfg["indices"]
        if isinstance(indices, str):
            indices = [indices]
        match_field = cfg["match_field"]
        keep = set(cfg["enrich_fields"]) | {match_field}
        lookup: Dict[Any, Dict[str, Any]] = {}
        ranges: List = []
        enrich_index = f".enrich-{name}"
        if enrich_index in self.node.indices_service.indices:
            self.node.indices_service.delete_index(enrich_index)
        self.node.indices_service.create_index(enrich_index, {}, None)
        eidx = self.node.indices_service.get(enrich_index)
        n = 0
        for index in indices:
            for h in self.node.search_service.scan(
                    index, {"query": {"match_all": {}}}):
                src = {k: v for k, v in h["_source"].items() if k in keep}
                mv = h["_source"].get(match_field)
                if mv is None:
                    continue
                if p["type"] == "match":
                    for v in (mv if isinstance(mv, list) else [mv]):
                        lookup.setdefault(v, src)
                elif isinstance(mv, dict):          # range policy
                    hi_exclusive = "lte" not in mv and "lt" in mv
                    lo = mv.get("gte")
                    hi = mv.get("lte", mv.get("lt"))
                    ranges.append((lo, hi, hi_exclusive, src))
                else:
                    continue                # range needs {gte,lte} objects
                eidx.index_doc(f"{n}", src)
                n += 1
        eidx.refresh()
        with self._lock:
            self.lookups[name] = lookup
            self.range_lookups[name] = ranges
        return {"status": {"phase": "COMPLETE"}}

    def enrich_lookup(self, policy_name: str, value,
                      max_matches: int = 1) -> List[Dict[str, Any]]:
        p = self.get_policy(policy_name)
        if p["type"] == "match":
            table = self.lookups.get(policy_name)
            if table is None:
                raise IllegalArgumentException(
                    f"policy [{policy_name}] has not been executed")
            # array-valued fields match on ANY element (ref: MatchProcessor)
            values = value if isinstance(value, list) else [value]
            out = []
            for v in values:
                try:
                    hit = table.get(v)
                except TypeError:
                    continue                      # unhashable element
                if hit is not None and hit not in out:
                    out.append(hit)
                if len(out) >= max_matches:
                    break
            return out
        out = []
        for lo, hi, hi_exclusive, doc in self.range_lookups.get(
                policy_name, []):
            try:
                upper_ok = (hi is None
                            or (value < hi if hi_exclusive
                                else value <= hi))
                if (lo is None or value >= lo) and upper_ok:
                    out.append(doc)
            except TypeError:
                continue
            if len(out) >= max_matches:
                break
        return out


@processor("enrich")
def _enrich_processor(cfg, svc):
    """The `enrich` ingest processor (ref: x-pack/plugin/enrich/.../
    MatchProcessor) — joins the policy's lookup table into the doc."""
    policy_name = cfg["policy_name"]
    field = cfg["field"]
    target = cfg["target_field"]
    max_matches = int(cfg.get("max_matches", 1))
    ignore_missing = bool(cfg.get("ignore_missing", False))
    override = cfg.get("override", True)

    def fn(doc):
        node = getattr(svc, "node", None)
        if node is None or not hasattr(node, "enrich_service"):
            raise IllegalArgumentException(
                "enrich processor requires the enrich service")
        value = doc.get(field)
        if value is None:
            if ignore_missing:
                return
            raise IllegalArgumentException(
                f"field [{field}] is missing")
        if not override and doc.get(target) is not None:
            return
        matches = node.enrich_service.enrich_lookup(
            policy_name, value, max_matches)
        if not matches:
            return
        doc.set(target, matches[0] if max_matches == 1 else matches)
    return fn
