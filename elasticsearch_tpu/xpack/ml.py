"""ML: anomaly detection, data frame analytics, trained-model inference.

Mirrors the reference's x-pack ML plugin (ref: x-pack/plugin/ml — job
management under `job/`, datafeeds under `datafeed/`, data frame
analytics under `dataframe/`, inference under `inference/`; the actual
math runs in external C++ processes managed via named pipes,
`process/NativeController.java`, SURVEY.md §2.2). Re-design for this
engine: **the C++ sidecar is replaced by JAX compute** —

- anomaly detection keeps per-entity Gaussian baselines (running
  mean/variance, the same normal-tail scoring family autodetect uses
  for metric functions) updated per bucket span; scores are -log tail
  probabilities normalized to 0-100 (ref: ml-cpp CAnomalyDetector's
  probability → anomaly score mapping).
- data frame analytics / outlier detection computes kNN distances as a
  tiled matmul over the feature matrix — exactly the dense-scoring
  pattern the TPU is built for (distance_kth_nn per ml-cpp COutliers).
- regression/classification train linear/logistic models with jnp
  (closed-form ridge / gradient descent) instead of boosted trees.
- trained models store coefficients and serve an infer API + ingest
  processor hook.
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ResourceAlreadyExistsException,
    ResourceNotFoundException,
)

_BUCKET_SPAN_UNITS = {"s": 1000, "m": 60_000, "h": 3_600_000,
                      "d": 86_400_000}


def _span_ms(span: str) -> float:
    import re
    m = re.fullmatch(r"(\d+)(s|m|h|d)", str(span))
    if not m:
        raise IllegalArgumentException(f"bad bucket_span [{span}]")
    return float(int(m.group(1)) * _BUCKET_SPAN_UNITS[m.group(2)])


class _Baseline:
    """Running Gaussian baseline per (detector, entity) — the normal-tail
    model family of ml-cpp's metric anomaly detection."""

    __slots__ = ("n", "mean", "m2")

    def __init__(self):
        self.n = 0
        self.mean = 0.0
        self.m2 = 0.0

    def update(self, x: float):
        self.n += 1
        d = x - self.mean
        self.mean += d / self.n
        self.m2 += d * (x - self.mean)

    @property
    def var(self) -> float:
        return self.m2 / (self.n - 1) if self.n > 1 else 0.0

    def probability(self, x: float) -> float:
        """Two-sided tail probability of x under the baseline."""
        if self.n < 3:
            return 1.0                       # warm-up: nothing is anomalous
        sd = math.sqrt(self.var)
        if sd == 0:
            return 1.0 if x == self.mean else 1e-10
        z = abs(x - self.mean) / sd
        # 2-sided normal tail via erfc
        return max(math.erfc(z / math.sqrt(2.0)), 1e-300)

    def to_dict(self):
        return {"n": self.n, "mean": self.mean, "m2": self.m2}

    @classmethod
    def from_dict(cls, d):
        b = cls()
        b.n, b.mean, b.m2 = d["n"], d["mean"], d["m2"]
        return b


class _SeasonalBaseline:
    """Seasonality-aware baseline: an overall Gaussian plus hour-of-day
    and day-of-week component Gaussians (ref: ml-cpp's periodic trend
    decomposition, CTimeSeriesDecomposition — the capability, not the
    mechanism). Once a calendar component has enough observations, the
    tail probability is taken against THAT component, so regular daily/
    weekly swings stop looking anomalous."""

    __slots__ = ("overall", "hod", "dow")

    MIN_COMPONENT_N = 4

    def __init__(self):
        self.overall = _Baseline()
        self.hod = [None] * 24     # lazily-created hour-of-day baselines
        self.dow = [None] * 7

    @staticmethod
    def _phase(ts_ms: float):
        sec = ts_ms / 1000.0
        hour = int(sec // 3600) % 24
        day = int(sec // 86400 + 4) % 7       # epoch day 0 = Thursday
        return hour, day

    def _component(self, ts_ms: float):
        hour, day = self._phase(ts_ms)
        h = self.hod[hour]
        if h is not None and h.n >= self.MIN_COMPONENT_N:
            return h
        d = self.dow[day]
        if d is not None and d.n >= self.MIN_COMPONENT_N:
            return d
        return self.overall

    def probability(self, x: float, ts_ms: float) -> float:
        return self._component(ts_ms).probability(x)

    def typical(self, ts_ms: float) -> float:
        return self._component(ts_ms).mean

    def update(self, x: float, ts_ms: float):
        hour, day = self._phase(ts_ms)
        if self.hod[hour] is None:
            self.hod[hour] = _Baseline()
        if self.dow[day] is None:
            self.dow[day] = _Baseline()
        self.overall.update(x)
        self.hod[hour].update(x)
        self.dow[day].update(x)

    def to_dict(self):
        return {
            "overall": self.overall.to_dict(),
            "hod": [b.to_dict() if b else None for b in self.hod],
            "dow": [b.to_dict() if b else None for b in self.dow],
        }

    @classmethod
    def from_dict(cls, d):
        s = cls()
        if "overall" in d:
            s.overall = _Baseline.from_dict(d["overall"])
            s.hod = [_Baseline.from_dict(b) if b else None
                     for b in d.get("hod", [None] * 24)]
            s.dow = [_Baseline.from_dict(b) if b else None
                     for b in d.get("dow", [None] * 7)]
        else:                        # round-1 snapshot: plain Gaussian
            s.overall = _Baseline.from_dict(d)
        return s


def _score_from_probability(p: float) -> float:
    """Map a tail probability to a 0-100 anomaly score (the reference's
    log-probability normalization, ml-cpp CAnomalyScore)."""
    if p >= 0.05:
        return 0.0
    s = min(100.0, -10.0 * math.log10(p) - 10.0)
    return max(0.0, s)


class MlJob:
    """One anomaly detection job (ref: x-pack/plugin/core Job config +
    x-pack/plugin/ml JobManager)."""

    def __init__(self, job_id: str, config: Dict[str, Any]):
        self.job_id = job_id
        ac = config.get("analysis_config", {})
        self.detectors: List[Dict[str, Any]] = ac.get("detectors", [])
        if not self.detectors:
            raise IllegalArgumentException(
                "analysis_config.detectors is required")
        self.bucket_span_ms = _span_ms(ac.get("bucket_span", "5m"))
        dd = config.get("data_description", {})
        self.time_field = dd.get("time_field", "timestamp")
        self.description = config.get("description", "")
        self.state = "closed"
        self.create_time = int(time.time() * 1000)
        # (detector_idx, entity key) -> _SeasonalBaseline
        self.baselines: Dict[str, _SeasonalBaseline] = {}
        # rare function: (detector_idx, by value) -> count, and totals
        self.category_counts: Dict[str, int] = {}
        self.buckets: List[Dict[str, Any]] = []       # bucket results
        self.records: List[Dict[str, Any]] = []       # record results
        self.processed_record_count = 0
        self.latest_record_ts: Optional[float] = None
        # model snapshots (ref: ModelSnapshot + JobModelSnapshotUpgrader
        # APIs): serialized baselines, revertable
        self.model_snapshots: List[Dict[str, Any]] = []
        self._snapshot_seq = 0

    # ------------------------------------------------- model snapshots
    def take_snapshot(self, description: str = "") -> Dict[str, Any]:
        """Serialize the model state (ref: autodetect persisting a
        ModelSnapshot on close/flush)."""
        self._snapshot_seq += 1
        snap = {
            "job_id": self.job_id,
            "snapshot_id": str(self._snapshot_seq),
            "timestamp": int(time.time() * 1000),
            "description": description,
            "snapshot_doc_count": len(self.baselines),
            "model": {
                "baselines": {k: b.to_dict()
                              for k, b in self.baselines.items()},
                "category_counts": dict(self.category_counts),
            },
        }
        self.model_snapshots.append(snap)
        return snap

    def revert_snapshot(self, snapshot_id: str) -> Dict[str, Any]:
        for snap in self.model_snapshots:
            if snap["snapshot_id"] == snapshot_id:
                model = snap["model"]
                self.baselines = {
                    k: _SeasonalBaseline.from_dict(d)
                    for k, d in model["baselines"].items()}
                self.category_counts = dict(model["category_counts"])
                return snap
        raise ResourceNotFoundException(
            f"No model snapshot with id [{snapshot_id}] for job "
            f"[{self.job_id}]")

    def config_dict(self) -> Dict[str, Any]:
        return {
            "job_id": self.job_id,
            "description": self.description,
            "analysis_config": {
                "bucket_span": f"{int(self.bucket_span_ms // 1000)}s",
                "detectors": self.detectors,
            },
            "data_description": {"time_field": self.time_field},
            "create_time": self.create_time,
        }

    # -- one bucket of data ---------------------------------------------
    def process_bucket(self, bucket_start: float,
                       docs: List[Dict[str, Any]]):
        """Run every detector over one bucket span of documents and emit
        record/bucket results (the autodetect per-bucket cycle)."""
        bucket_records: List[Dict[str, Any]] = []
        for di, det in enumerate(self.detectors):
            fn = det.get("function", "count")
            field = det.get("field_name")
            by = det.get("by_field_name")
            partition = det.get("partition_field_name")

            # group docs by entity (by/partition values)
            groups: Dict[tuple, List[Dict[str, Any]]] = {}
            for doc in docs:
                key = (doc.get(partition) if partition else None,
                       doc.get(by) if by else None)
                groups.setdefault(key, []).append(doc)
            if fn == "rare":
                self._rare(di, det, groups, bucket_start, bucket_records)
                continue
            for key, group in groups.items():
                value = self._detector_value(fn, field, group)
                if value is None:
                    continue
                bkey = f"{di}|{key[0]}|{key[1]}"
                base = self.baselines.get(bkey)
                if base is None:
                    base = self.baselines[bkey] = _SeasonalBaseline()
                p = base.probability(value, bucket_start)
                score = _score_from_probability(p)
                if score > 0:
                    rec = {
                        "job_id": self.job_id,
                        "result_type": "record",
                        "detector_index": di,
                        "function": fn,
                        "timestamp": int(bucket_start),
                        "record_score": score,
                        "probability": p,
                        "actual": [value],
                        "typical": [base.typical(bucket_start)],
                    }
                    if field:
                        rec["field_name"] = field
                    if partition:
                        rec["partition_field_name"] = partition
                        rec["partition_field_value"] = key[0]
                    if by:
                        rec["by_field_name"] = by
                        rec["by_field_value"] = key[1]
                    bucket_records.append(rec)
                base.update(value, bucket_start)
        self.records.extend(bucket_records)
        anomaly_score = max((r["record_score"] for r in bucket_records),
                            default=0.0)
        self.buckets.append({
            "job_id": self.job_id,
            "result_type": "bucket",
            "timestamp": int(bucket_start),
            "anomaly_score": anomaly_score,
            "event_count": len(docs),
            "bucket_span": int(self.bucket_span_ms // 1000),
        })
        self.processed_record_count += len(docs)

    def _rare(self, di, det, groups, bucket_start, bucket_records):
        """`rare` function: flag by-values seldom seen before (ml-cpp's
        individual rare model, frequency-based)."""
        total = sum(v for c, v in self.category_counts.items()
                    if c.startswith(f"{di}|"))
        for key, group in groups.items():
            ckey = f"{di}|{key[1]}"
            seen = self.category_counts.get(ckey, 0)
            n_cats = sum(1 for c in self.category_counts
                         if c.startswith(f"{di}|"))
            if seen == 0 and n_cats >= 5:
                p = 1.0 / (total + n_cats + 1)
                score = _score_from_probability(p)
                if score > 0:
                    bucket_records.append({
                        "job_id": self.job_id,
                        "result_type": "record",
                        "detector_index": di,
                        "function": "rare",
                        "timestamp": int(bucket_start),
                        "record_score": score,
                        "probability": p,
                        "by_field_name": det.get("by_field_name"),
                        "by_field_value": key[1],
                    })
            self.category_counts[ckey] = seen + len(group)

    @staticmethod
    def _detector_value(fn: str, field: Optional[str],
                        group: List[Dict[str, Any]]):
        if fn in ("count", "high_count", "low_count"):
            return float(len(group))
        if fn in ("non_zero_count", "high_non_zero_count",
                  "low_non_zero_count"):
            return float(len(group)) or None
        if fn == "distinct_count":
            return float(len({json.dumps(d.get(field), default=str)
                              for d in group if d.get(field) is not None}))
        vals = [float(d[field]) for d in group
                if isinstance(d.get(field), (int, float))]
        if not vals:
            return None
        if fn in ("mean", "avg", "high_mean", "low_mean"):
            return float(np.mean(vals))
        if fn in ("min", "low_min", "high_min"):
            return float(np.min(vals))
        if fn in ("max", "high_max", "low_max"):
            return float(np.max(vals))
        if fn in ("sum", "high_sum", "low_sum", "non_null_sum"):
            return float(np.sum(vals))
        if fn == "median":
            return float(np.median(vals))
        if fn == "varp":
            return float(np.var(vals))
        raise IllegalArgumentException(f"Unknown ML function [{fn}]")


class Datafeed:
    """Pulls bucketed data from an index into a job (ref:
    x-pack/plugin/ml/.../datafeed/DatafeedJob — the query/aggregation
    extraction loop)."""

    def __init__(self, feed_id: str, config: Dict[str, Any]):
        self.feed_id = feed_id
        self.job_id = config.get("job_id")
        self.indices = config.get("indices") or config.get("indexes", [])
        if isinstance(self.indices, str):
            self.indices = [self.indices]
        self.query = config.get("query", {"match_all": {}})
        self.state = "stopped"
        if not self.job_id or not self.indices:
            raise IllegalArgumentException(
                "datafeed requires job_id and indices")

    def config_dict(self):
        return {"datafeed_id": self.feed_id, "job_id": self.job_id,
                "indices": self.indices, "query": self.query}


class MlService:
    """Job/datafeed/analytics registry + execution (ref: the ML plugin's
    JobManager + DatafeedManager + DataFrameAnalyticsManager, with JAX
    standing in for the native processes)."""

    def __init__(self, node):
        self.node = node
        self.jobs: Dict[str, MlJob] = {}
        self.datafeeds: Dict[str, Datafeed] = {}
        self.analytics: Dict[str, Dict[str, Any]] = {}
        self.trained_models: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()

    # ---------------------------------------------------------- jobs
    def put_job(self, job_id: str, config: Dict[str, Any]) -> MlJob:
        with self._lock:
            if job_id in self.jobs:
                raise ResourceAlreadyExistsException(
                    f"job [{job_id}] already exists")
            job = MlJob(job_id, config)
            self.jobs[job_id] = job
            return job

    def get_job(self, job_id: str) -> MlJob:
        job = self.jobs.get(job_id)
        if job is None:
            raise ResourceNotFoundException(
                f"No known job with id [{job_id}]")
        return job

    def delete_job(self, job_id: str):
        self.get_job(job_id)
        with self._lock:
            del self.jobs[job_id]
            for fid in [f for f, d in self.datafeeds.items()
                        if d.job_id == job_id]:
                del self.datafeeds[fid]

    def open_job(self, job_id: str):
        self.get_job(job_id).state = "opened"

    def close_job(self, job_id: str):
        job = self.get_job(job_id)
        was_open = job.state == "opened"
        job.state = "closed"
        # autodetect persists a model snapshot at close (ref:
        # AutodetectProcessManager.closeJob → persistModelSnapshot);
        # closing an already-closed job is an idempotent no-op
        if was_open and (job.baselines or job.category_counts):
            job.take_snapshot("on close")

    def model_snapshots(self, job_id: str) -> List[Dict[str, Any]]:
        job = self.get_job(job_id)
        return [{k: v for k, v in s.items() if k != "model"}
                for s in job.model_snapshots]

    def revert_model_snapshot(self, job_id: str,
                              snapshot_id: str) -> Dict[str, Any]:
        job = self.get_job(job_id)
        snap = job.revert_snapshot(snapshot_id)
        return {k: v for k, v in snap.items() if k != "model"}

    def post_data(self, job_id: str, docs: List[Dict[str, Any]]):
        """Stream raw documents into an open job (the _data API): docs
        are bucketed by time and run through the detectors."""
        job = self.get_job(job_id)
        if job.state != "opened":
            raise IllegalArgumentException(
                f"job [{job_id}] is not open")
        self._run_buckets(job, docs)
        return {"job_id": job_id,
                "processed_record_count": job.processed_record_count}

    def _run_buckets(self, job: MlJob, docs: List[Dict[str, Any]]):
        def ts_of(doc):
            v = doc.get(job.time_field)
            if isinstance(v, (int, float)):
                return float(v)
            if isinstance(v, str):
                from datetime import datetime, timezone
                return datetime.fromisoformat(
                    v.replace("Z", "+00:00")).timestamp() * 1000
            return None

        timed = [(ts_of(d), d) for d in docs]
        timed = [(t, d) for t, d in timed if t is not None]
        timed.sort(key=lambda td: td[0])
        span = job.bucket_span_ms
        current_bucket = None
        bucket_docs: List[Dict[str, Any]] = []
        for t, d in timed:
            b = math.floor(t / span) * span
            if current_bucket is None:
                current_bucket = b
            if b != current_bucket:
                job.process_bucket(current_bucket, bucket_docs)
                # emit empty buckets in between (count detectors see 0)
                nxt = current_bucket + span
                while nxt < b:
                    job.process_bucket(nxt, [])
                    nxt += span
                current_bucket = b
                bucket_docs = []
            bucket_docs.append(d)
            job.latest_record_ts = t
        if current_bucket is not None:
            job.process_bucket(current_bucket, bucket_docs)

    # ------------------------------------------------------ datafeeds
    def put_datafeed(self, feed_id: str, config: Dict[str, Any]):
        with self._lock:
            if feed_id in self.datafeeds:
                raise ResourceAlreadyExistsException(
                    f"datafeed [{feed_id}] already exists")
            self.get_job(config.get("job_id", ""))
            feed = Datafeed(feed_id, config)
            self.datafeeds[feed_id] = feed
            return feed

    def get_datafeed(self, feed_id: str) -> Datafeed:
        feed = self.datafeeds.get(feed_id)
        if feed is None:
            raise ResourceNotFoundException(
                f"No known datafeed with id [{feed_id}]")
        return feed

    def start_datafeed(self, feed_id: str, start=None, end=None):
        """Lookback run: pull matching docs from the feed's indices
        through the search path and stream them into the job."""
        feed = self.get_datafeed(feed_id)
        job = self.get_job(feed.job_id)
        if job.state != "opened":
            raise IllegalArgumentException(
                f"cannot start datafeed [{feed_id}] while job "
                f"[{job.job_id}] is closed")
        feed.state = "started"
        query: Dict[str, Any] = {"bool": {"must": [feed.query]}}
        rng: Dict[str, Any] = {}
        if start is not None:
            rng["gte"] = start
        if end is not None:
            rng["lt"] = end
        if rng:
            query["bool"]["must"].append(
                {"range": {job.time_field: rng}})
        docs: List[Dict[str, Any]] = []
        for index in feed.indices:
            docs.extend(h["_source"] for h in self.node.search_service.scan(
                index, {"query": query,
                        "sort": [{job.time_field: {"order": "asc"}}]}))
        self._run_buckets(job, docs)
        feed.state = "stopped"
        return {"started": True}

    def stop_datafeed(self, feed_id: str):
        self.get_datafeed(feed_id).state = "stopped"
        return {"stopped": True}

    def delete_datafeed(self, feed_id: str):
        self.get_datafeed(feed_id)
        with self._lock:
            del self.datafeeds[feed_id]

    # ----------------------------------------------- data frame analytics
    def put_analytics(self, aid: str, config: Dict[str, Any]):
        with self._lock:
            if aid in self.analytics:
                raise ResourceAlreadyExistsException(
                    f"data frame analytics [{aid}] already exists")
            if "source" not in config or "dest" not in config:
                raise IllegalArgumentException(
                    "source and dest are required")
            cfg = dict(config)
            cfg["id"] = aid
            cfg["state"] = "stopped"
            self.analytics[aid] = cfg
            return cfg

    def get_analytics(self, aid: str) -> Dict[str, Any]:
        cfg = self.analytics.get(aid)
        if cfg is None:
            raise ResourceNotFoundException(
                f"No known data frame analytics with id [{aid}]")
        return cfg

    def start_analytics(self, aid: str):
        cfg = self.get_analytics(aid)
        cfg["state"] = "started"
        try:
            self._run_analytics(cfg)
            cfg["state"] = "stopped"
            cfg["progress"] = 100
        except Exception:
            cfg["state"] = "failed"
            raise
        return {"acknowledged": True}

    def _run_analytics(self, cfg: Dict[str, Any]):
        src = cfg["source"]["index"]
        if isinstance(src, list):
            src = ",".join(src)
        dest = cfg["dest"]["index"]
        analysis = cfg.get("analysis", {})
        hits = list(self.node.search_service.scan(src, {
            "query": cfg["source"].get("query", {"match_all": {}})}))
        sources = [h["_source"] for h in hits]
        if "outlier_detection" in analysis:
            results = self._outlier_detection(
                sources, analysis["outlier_detection"])
            result_field = "ml"
            rows = [{**s, result_field: {"outlier_score": sc}}
                    for s, sc in zip(sources, results)]
        elif "regression" in analysis:
            rows, model = self._regression(
                sources, analysis["regression"], classification=False)
            self._store_model_for(cfg, model)
        elif "classification" in analysis:
            rows, model = self._regression(
                sources, analysis["classification"], classification=True)
            self._store_model_for(cfg, model)
        else:
            raise IllegalArgumentException("Unknown analysis type")
        # write results to dest through the normal indexing path
        if dest not in self.node.indices_service.indices:
            self.node.indices_service.create_index(dest, {}, None)
        didx = self.node.indices_service.get(dest)
        for i, (h, row) in enumerate(zip(hits, rows)):
            didx.index_doc(h["_id"], row)
        didx.refresh()

    def _store_model_for(self, cfg, model):
        mid = cfg["id"] + "-model"
        model["model_id"] = mid
        self.trained_models[mid] = model

    @staticmethod
    def _numeric_matrix(sources: List[Dict[str, Any]],
                        exclude: Optional[str] = None):
        fields = sorted({k for s in sources
                         for k, v in s.items()
                         if isinstance(v, (int, float))
                         and not isinstance(v, bool) and k != exclude})
        mat = np.array([[float(s.get(f) or 0.0) for f in fields]
                        for s in sources], np.float32)
        return fields, mat

    def _outlier_detection(self, sources, params) -> List[float]:
        """Distance-based outlier scores: the kth-NN distance over the
        feature matrix, computed as one dense distance matrix — a tiled
        matmul on TPU (ref: ml-cpp COutliers distance_kth_nn method)."""
        import jax.numpy as jnp

        _, mat = self._numeric_matrix(sources)
        n = len(mat)
        if n < 2:
            return [0.0] * n
        k = min(int(params.get("n_neighbors", 5)), n - 1)
        x = jnp.asarray(mat)
        # standardize features so no column dominates
        std = jnp.std(x, axis=0)
        x = (x - jnp.mean(x, axis=0)) / jnp.where(std == 0, 1.0, std)
        # pairwise squared distances via the Gram matrix (MXU path)
        sq = jnp.sum(x * x, axis=1)
        d2 = sq[:, None] + sq[None, :] - 2.0 * (x @ x.T)
        d2 = jnp.maximum(d2, 0.0)
        d2 = d2.at[jnp.arange(n), jnp.arange(n)].set(jnp.inf)
        kth = jnp.sort(d2, axis=1)[:, k - 1]
        dist = np.sqrt(np.asarray(kth))
        # normalize to (0, 1]: score relative to the distribution
        med = float(np.median(dist)) or 1.0
        scores = 1.0 - np.exp(-(dist / (2.0 * med)) ** 2)
        return [float(s) for s in scores]

    def _regression(self, sources, params, classification: bool):
        """Linear (ridge) regression / logistic classification trained
        with jnp — the gradient work XLA compiles to the MXU (replaces
        ml-cpp's boosted trees for the API surface)."""
        import jax
        import jax.numpy as jnp

        dep = params.get("dependent_variable")
        if not dep:
            raise IllegalArgumentException(
                "dependent_variable is required")
        train = [s for s in sources if s.get(dep) is not None]
        fields, mat = self._numeric_matrix(train, exclude=dep)
        if classification:
            classes = sorted({str(s[dep]) for s in train})
            if len(classes) < 2:
                raise IllegalArgumentException(
                    "classification needs at least two classes")
            y = np.array([classes.index(str(s[dep])) for s in train],
                         np.float32)
        else:
            classes = None
            y = np.array([float(s[dep]) for s in train], np.float32)
        x = jnp.asarray(mat)
        mean, std = jnp.mean(x, axis=0), jnp.std(x, axis=0)
        std = jnp.where(std == 0, 1.0, std)
        xs = (x - mean) / std
        xs = jnp.concatenate([xs, jnp.ones((len(train), 1))], axis=1)
        yv = jnp.asarray(y)
        if classification:
            # multinomial softmax regression; the WHOLE optimizer runs
            # as one compiled lax.fori_loop (no per-step Python
            # dispatch — the TPU-idiomatic training loop)
            nc = len(classes)
            yi = jnp.asarray(y.astype(np.int32))

            def loss(W):
                logits = xs @ W                        # [N, nc]
                lse = jax.nn.logsumexp(logits, axis=1)
                picked = jnp.take_along_axis(
                    logits, yi[:, None], axis=1)[:, 0]
                return jnp.mean(lse - picked) + 1e-3 * jnp.sum(W * W)

            grad = jax.grad(loss)

            @jax.jit
            def fit(W0):
                def step(_, W):
                    return W - 0.5 * grad(W)
                return jax.lax.fori_loop(0, 300, step, W0)

            w = np.asarray(fit(jnp.zeros((xs.shape[1], nc))))
        else:
            # closed-form ridge: (X'X + λI)^-1 X'y
            lam = 1e-3
            xtx = xs.T @ xs + lam * jnp.eye(xs.shape[1])
            w = np.asarray(jnp.linalg.solve(xtx, xs.T @ yv))
        model = {
            "model_type": ("classification" if classification
                           else "regression"),
            "feature_names": fields,
            "mean": np.asarray(mean).tolist(),
            "std": np.asarray(std).tolist(),
            "weights": w.tolist(),
            "classes": classes,
            "dependent_variable": dep,
        }
        rows = []
        for s in sources:
            pred = self._predict(model, s)
            key = dep + "_prediction"
            rows.append({**s, "ml": {key: pred}})
        return rows, model

    @staticmethod
    def _predict(model: Dict[str, Any], doc: Dict[str, Any]):
        x = np.array([float(doc.get(f) or 0.0)
                      for f in model["feature_names"]], np.float32)
        xs = (x - np.array(model["mean"])) / np.array(model["std"])
        xs = np.concatenate([xs, [1.0]])
        w = np.array(model["weights"])
        if model["model_type"] == "classification":
            if w.ndim == 2:                   # multinomial softmax head
                return model["classes"][int(np.argmax(xs @ w))]
            v = float(xs @ w)                 # legacy binary sigmoid
            p = 1.0 / (1.0 + math.exp(-v))
            return model["classes"][1] if p >= 0.5 else model["classes"][0]
        return float(xs @ w)

    # ------------------------------------------------- trained models
    def put_trained_model(self, model_id: str, config: Dict[str, Any]):
        with self._lock:
            if model_id in self.trained_models:
                raise ResourceAlreadyExistsException(
                    f"model [{model_id}] already exists")
            # accept a raw linear definition (weights/features) — the
            # engine's native format
            model = dict(config)
            model["model_id"] = model_id
            self.trained_models[model_id] = model
            return model

    def get_trained_model(self, model_id: str) -> Dict[str, Any]:
        m = self.trained_models.get(model_id)
        if m is None:
            raise ResourceNotFoundException(
                f"No known trained model with id [{model_id}]")
        return m

    def delete_trained_model(self, model_id: str):
        self.get_trained_model(model_id)
        with self._lock:
            del self.trained_models[model_id]

    def infer(self, model_id: str,
              docs: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        model = self.get_trained_model(model_id)
        out = []
        for doc in docs:
            out.append({"predicted_value": self._predict(model, doc)})
        return out


# ---------------------------------------------------------------------------
# Inference ingest processor: trained-model predictions INSIDE ingest
# pipelines (ref: x-pack/plugin/ml/.../inference/ingest/
# InferenceProcessor.java:59). `field_map` renames document fields to
# the model's feature names before inference; the prediction lands at
# `target_field` as {predicted_value, model_id} — the reference's
# result layout.
# ---------------------------------------------------------------------------

from elasticsearch_tpu.ingest.service import processor as _ingest_processor


@_ingest_processor("inference")
def _inference_processor(cfg, svc):
    model_id = cfg["model_id"]
    target = cfg.get("target_field", "ml.inference")
    field_map: Dict[str, str] = cfg.get("field_map") or {}
    ignore_missing = bool(cfg.get("ignore_missing", False))

    def fn(doc):
        node = getattr(svc, "node", None)
        if node is None or not hasattr(node, "ml_service"):
            raise IllegalArgumentException(
                "inference processor requires the ml service")
        model = node.ml_service.get_trained_model(model_id)
        feats: Dict[str, Any] = {}
        for f in model.get("feature_names", []):
            # field_map maps DOC field -> MODEL feature name
            src_field = next(
                (k for k, v in field_map.items() if v == f), f)
            v = doc.get(src_field)
            if v is None and not ignore_missing:
                raise IllegalArgumentException(
                    f"field [{src_field}] is missing for model "
                    f"[{model_id}]")
            feats[f] = v
        result = node.ml_service.infer(model_id, [feats])[0]
        doc.set(target + ".predicted_value",
                result["predicted_value"])
        doc.set(target + ".model_id", model_id)
    return fn
