"""Rollup: periodic downsampling of time-series indices.

Mirrors the reference's x-pack rollup plugin (ref: x-pack/plugin/rollup —
RollupJob configs, the indexer that walks the source index with composite
aggs and writes flattened rollup documents, and TransportRollupSearchAction
which rewrites searches over rolled data; SURVEY.md §2.6). Re-design for
this engine: the indexer is one composite-agg pass over the TPU search
path (after-key paging), rollup docs use flattened key names
(`field.date_histogram.timestamp`, `field.terms.value`,
`field.<metric>.value`), and `_rollup_search` translates a live-style
aggregation body onto those flattened fields, merging avg from
sum/value_count pairs.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ResourceAlreadyExistsException,
    ResourceNotFoundException,
)


class RollupService:
    def __init__(self, node):
        self.node = node
        self.jobs: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------- jobs
    def put_job(self, job_id: str, config: Dict[str, Any]):
        with self._lock:
            if job_id in self.jobs:
                raise ResourceAlreadyExistsException(
                    f"Cannot create rollup job [{job_id}] because job "
                    "already exists")
            for req in ("index_pattern", "rollup_index", "groups"):
                if req not in config:
                    raise IllegalArgumentException(f"[{req}] is required")
            if "date_histogram" not in config["groups"]:
                raise IllegalArgumentException(
                    "groups.date_histogram is required")
            job = dict(config)
            job["job_id"] = job_id
            job["status"] = "stopped"
            self.jobs[job_id] = job
            return job

    def get_job(self, job_id: str) -> Dict[str, Any]:
        job = self.jobs.get(job_id)
        if job is None:
            raise ResourceNotFoundException(
                f"Task for Rollup Job [{job_id}] not found")
        return job

    def delete_job(self, job_id: str):
        self.get_job(job_id)
        with self._lock:
            del self.jobs[job_id]

    # ---------------------------------------------------------- indexer
    def start_job(self, job_id: str):
        """One indexing pass: composite over the group fields, one rollup
        doc per bucket (ref: rollup/job/RollupIndexer.buildComposite)."""
        job = self.get_job(job_id)
        job["status"] = "started"
        groups = job["groups"]
        dh = groups["date_histogram"]
        date_field = dh["field"]
        interval = (dh.get("calendar_interval")
                    or dh.get("fixed_interval") or dh.get("interval"))
        sources: List[Dict[str, Any]] = [
            {"__date": {"date_histogram": {
                "field": date_field,
                "calendar_interval": interval}}}]
        term_fields = groups.get("terms", {}).get("fields", [])
        for f in term_fields:
            sources.append({f"__t_{f}": {"terms": {"field": f}}})
        hist = groups.get("histogram", {})
        for f in hist.get("fields", []):
            sources.append({f"__h_{f}": {"histogram": {
                "field": f, "interval": hist.get("interval", 1)}}})
        metric_aggs: Dict[str, Any] = {}
        for m in job.get("metrics", []):
            f = m["field"]
            for op in m.get("metrics", []):
                if op == "avg":
                    # avg rolls up as sum + value_count (merged at search)
                    metric_aggs[f"{f}__sum"] = {"sum": {"field": f}}
                    metric_aggs[f"{f}__value_count"] = {
                        "value_count": {"field": f}}
                else:
                    metric_aggs[f"{f}__{op}"] = {op: {"field": f}}

        rollup_index = job["rollup_index"]
        if rollup_index not in self.node.indices_service.indices:
            # explicit mapping from the job config (ref: the rollup index
            # template TransportPutRollupJobAction writes)
            props: Dict[str, Any] = {
                f"{date_field}.date_histogram.timestamp": {"type": "date"},
                "_rollup.doc_count": {"type": "long"},
            }
            for f in term_fields:
                props[f"{f}.terms.value"] = {"type": "keyword"}
            for f in hist.get("fields", []):
                props[f"{f}.histogram.value"] = {"type": "double"}
            for mname in metric_aggs:
                f, _, op = mname.rpartition("__")
                props[f"{f}.{op}.value"] = {"type": "double"}
            self.node.indices_service.create_index(
                rollup_index, {}, {"properties": props})
        dest = self.node.indices_service.get(rollup_index)
        after = None
        n = 0
        while True:
            comp: Dict[str, Any] = {"size": 500, "sources": sources}
            if after is not None:
                comp["after"] = after
            node_aggs: Dict[str, Any] = {"b": {"composite": comp}}
            if metric_aggs:
                node_aggs["b"]["aggs"] = metric_aggs
            r = self.node.search_service.search(
                job["index_pattern"], {"size": 0, "aggs": node_aggs})
            g = r["aggregations"]["b"]
            for bucket in g.get("buckets", []):
                doc: Dict[str, Any] = {
                    "_rollup.id": job_id,
                    "_rollup.version": 2,
                    "_rollup.doc_count": bucket["doc_count"],
                    f"{date_field}.date_histogram.timestamp":
                        bucket["key"]["__date"],
                    f"{date_field}.date_histogram.interval": interval,
                }
                for f in term_fields:
                    doc[f"{f}.terms.value"] = bucket["key"][f"__t_{f}"]
                for f in hist.get("fields", []):
                    doc[f"{f}.histogram.value"] = bucket["key"][f"__h_{f}"]
                for mname, spec in metric_aggs.items():
                    f, _, op = mname.rpartition("__")
                    v = bucket.get(mname, {}).get("value")
                    doc[f"{f}.{op}.value"] = v
                dest.index_doc(f"{job_id}${n}", doc)
                n += 1
            after = g.get("after_key")
            if after is None or not g.get("buckets"):
                break
        dest.refresh()
        job["status"] = "stopped"
        job["stats"] = {"documents_processed": n}
        return {"started": True}

    def stop_job(self, job_id: str):
        self.get_job(job_id)["status"] = "stopped"
        return {"stopped": True}

    # ----------------------------------------------------- rollup search
    def rollup_search(self, index: str,
                      body: Dict[str, Any]) -> Dict[str, Any]:
        """Rewrite a live-style agg request onto the flattened rollup doc
        fields (ref: TransportRollupSearchAction.rewriteQuery/translate)."""
        aggs = body.get("aggs", body.get("aggregations", {}))
        if not aggs:
            raise IllegalArgumentException(
                "Rollup requires at least one aggregation")
        out_aggs = self._translate_aggs(aggs)
        query = self._translate_query(
            body.get("query", {"match_all": {}}), index)
        r = self.node.search_service.search(index, {
            "size": 0, "query": query, "aggs": out_aggs})
        translated = self._merge_avg(r.get("aggregations", {}), aggs)
        return {"took": r.get("took", 0), "timed_out": False,
                "hits": {"total": {"value": 0, "relation": "eq"},
                         "hits": []},
                "aggregations": translated}

    def _rolled_field_map(self, rollup_index: str) -> Dict[str, str]:
        """Original field name → flattened rollup field, from the jobs
        that write into this rollup index."""
        fmap: Dict[str, str] = {}
        for job in self.jobs.values():
            if job["rollup_index"] != rollup_index:
                continue
            groups = job["groups"]
            df = groups["date_histogram"]["field"]
            fmap[df] = f"{df}.date_histogram.timestamp"
            for f in groups.get("terms", {}).get("fields", []):
                fmap[f] = f"{f}.terms.value"
            for f in groups.get("histogram", {}).get("fields", []):
                fmap[f] = f"{f}.histogram.value"
        return fmap

    def _translate_query(self, query: Dict[str, Any],
                         rollup_index: str) -> Dict[str, Any]:
        """Rewrite query field names onto the flattened rollup fields
        (ref: TransportRollupSearchAction.rewriteQuery — only group-by
        fields are queryable in rolled data)."""
        fmap = self._rolled_field_map(rollup_index)

        def walk(node):
            if isinstance(node, list):
                return [walk(x) for x in node]
            if not isinstance(node, dict):
                return node
            out = {}
            for k, v in node.items():
                if k in ("term", "terms", "range", "match", "wildcard",
                         "prefix", "exists") and isinstance(v, dict):
                    nv = {}
                    for f, spec in v.items():
                        if f == "field" and k == "exists":
                            nv[f] = fmap.get(spec, spec)
                        else:
                            nv[fmap.get(f, f)] = spec
                    out[k] = nv
                else:
                    out[k] = walk(v)
            return out

        return walk(query)

    def _translate_aggs(self, aggs: Dict[str, Any]) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for name, node in aggs.items():
            sub = node.get("aggs", node.get("aggregations", {}))
            (atype, abody), = ((k, v) for k, v in node.items()
                               if k not in ("aggs", "aggregations", "meta"))
            abody = dict(abody)
            f = abody.get("field")
            if atype == "date_histogram":
                abody["field"] = f"{f}.date_histogram.timestamp"
                new = {atype: abody}
            elif atype == "terms":
                abody["field"] = f"{f}.terms.value"
                new = {atype: abody}
            elif atype == "histogram":
                abody["field"] = f"{f}.histogram.value"
                new = {atype: abody}
            elif atype in ("min", "max"):
                new = {atype: {"field": f"{f}.{atype}.value"}}
            elif atype in ("sum", "value_count"):
                # rolled partials re-aggregate by SUM
                new = {"sum": {"field": f"{f}.{atype}.value"}}
            elif atype == "avg":
                out[f"{name}__sum"] = {"sum": {"field": f"{f}.sum.value"}}
                out[f"{name}__count"] = {
                    "sum": {"field": f"{f}.value_count.value"}}
                continue
            else:
                raise IllegalArgumentException(
                    f"Unsupported aggregation [{atype}] in rollup search")
            if atype in ("date_histogram", "terms", "histogram"):
                # buckets must report ORIGINAL event counts, not rollup
                # row counts (ref: RollupResponseTranslator doc_count sums)
                sub_out = self._translate_aggs(sub) if sub else {}
                sub_out["__doc_count"] = {
                    "sum": {"field": "_rollup.doc_count"}}
                new["aggs"] = sub_out
            elif sub:
                new["aggs"] = self._translate_aggs(sub)
            out[name] = new
        return out

    def _merge_avg(self, results: Dict[str, Any],
                   orig: Dict[str, Any]) -> Dict[str, Any]:
        """Reassemble avg results from their sum/count pairs, recursing
        into buckets."""
        out: Dict[str, Any] = {}
        for name, node in orig.items():
            sub = node.get("aggs", node.get("aggregations", {}))
            (atype, _), = ((k, v) for k, v in node.items()
                           if k not in ("aggs", "aggregations", "meta"))
            if atype == "avg":
                continue        # filled by parent loop below
            res = results.get(name)
            if res is None:
                continue
            if isinstance(res, dict) and "buckets" in res:
                # helper agg names inside buckets (avg pairs, doc_count
                # carrier) must not leak to the client
                helper_names = {f"{n}__sum" for n in sub} | {
                    f"{n}__count" for n in sub} | {"__doc_count"}
                buckets = []
                for b in res["buckets"]:
                    nb = {k: v for k, v in b.items()
                          if k not in helper_names}
                    dc = b.get("__doc_count", {}).get("value")
                    if dc is not None:
                        nb["doc_count"] = int(dc)
                    if sub:
                        nb.update(self._merge_avg(
                            {k: v for k, v in b.items()
                             if isinstance(v, dict)}, sub))
                    buckets.append(nb)
                res = {**res, "buckets": buckets}
            out[name] = res
        # avg reassembly at this level
        for name, node in orig.items():
            (atype, _), = ((k, v) for k, v in node.items()
                           if k not in ("aggs", "aggregations", "meta"))
            if atype != "avg":
                continue
            s = results.get(f"{name}__sum", {}).get("value")
            c = results.get(f"{name}__count", {}).get("value")
            out[name] = {"value": (s / c) if s is not None and c else None}
        return out

    def caps(self, index_pattern: str) -> Dict[str, Any]:
        """GET _rollup/data/{pattern} — which jobs roll up which
        patterns."""
        import fnmatch
        out: Dict[str, Any] = {}
        for job in self.jobs.values():
            if (index_pattern in ("_all", "*")
                    or fnmatch.fnmatch(job["index_pattern"], index_pattern)
                    or job["index_pattern"] == index_pattern):
                out.setdefault(job["index_pattern"], {"rollup_jobs": []})[
                    "rollup_jobs"].append({
                        "job_id": job["job_id"],
                        "rollup_index": job["rollup_index"],
                        "index_pattern": job["index_pattern"],
                        "fields": {}})
        return out
