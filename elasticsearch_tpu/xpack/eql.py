"""EQL: event query language over the search engine.

Mirrors the reference's x-pack EQL plugin (ref: x-pack/plugin/eql —
ANTLR parser + planner sharing the `ql` core with SQL, sequence/join
execution under `execution/`; SURVEY.md §2.6). Re-design for this engine:

- **event queries** (`category where condition`) translate the condition
  through the shared QL core (xpack/ql.py) into the JSON query DSL and
  run on the TPU search path, ordered by the timestamp field.
- **sequences** (`sequence by key [q1] [q2] ... until [q]`) fetch each
  stage's candidate events (device-filtered), then run a host-side
  state machine over the time-ordered event stream, keyed by the join
  fields, honoring `maxspan` (ref: eql/execution/sequence/
  SequenceMatcher — the same "keyed stage windows" model).
- pipes: `| head N`, `| tail N`.

Conditions that cannot be expressed in the query DSL (arbitrary scalar
functions) fall back to device-side category filtering + host-side
row evaluation via ql.evaluate — correctness first, device filter as
the fast path.
"""

from __future__ import annotations

import re
import time
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ParsingException,
)
from elasticsearch_tpu.search.searcher import _get_path as _source_get
from elasticsearch_tpu.xpack import ql
from elasticsearch_tpu.xpack.sql import Parser as SqlParser

# host-resident hits per cursor page of an event-stream drain — the
# memory cap that replaced the old whole-index single read (fetch_size
# still bounds the TOTAL events, this bounds the per-page footprint)
EQL_FETCH_WINDOW = 1000


@dataclass
class EventQuery:
    category: Optional[str]         # None = any
    condition: ql.Expr
    join_keys: List[str] = dc_field(default_factory=list)


@dataclass
class EqlQuery:
    kind: str                       # "event" | "sequence"
    queries: List[EventQuery]
    by: List[str] = dc_field(default_factory=list)      # shared join keys
    maxspan_ms: Optional[float] = None
    until: Optional[EventQuery] = None
    head: Optional[int] = None
    tail: Optional[int] = None


_UNITS_MS = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000,
             "d": 86_400_000}


class EqlParser(SqlParser):
    """EQL grammar on top of the shared tokenizer/expression parser
    (ref: x-pack/plugin/eql/.../parser/EqlBaseParser)."""

    def parse_eql(self) -> EqlQuery:
        t = self.peek()
        if t.kind == "KEYWORD" and t.value == "sequence":
            q = self._sequence()
        elif t.kind == "KEYWORD" and t.value == "join":
            raise IllegalArgumentException("join is not supported")
        else:
            q = EqlQuery("event", [self._event_query()])
        # pipes
        while self.accept_op("|"):
            name = self.next()
            if name.value in ("head", "tail"):
                num = self.next()
                if num.kind != "NUMBER":
                    raise ParsingException(f"{name.value} requires a number")
                if name.value == "head":
                    q.head = int(num.value)
                else:
                    q.tail = int(num.value)
            else:
                raise ParsingException(f"Unsupported pipe [{name.value}]")
        if self.peek().kind != "EOF":
            raise ParsingException(
                f"Unexpected token [{self.peek().value}]")
        return q

    def _event_query(self) -> EventQuery:
        t = self.next()
        if t.kind not in ("IDENT", "KEYWORD", "STRING"):
            raise ParsingException("Expected an event category")
        category = None if t.value == "any" else str(t.value)
        self.expect_kw("where")
        cond = self._expr()
        return EventQuery(category, cond)

    def _sequence(self) -> EqlQuery:
        self.expect_kw("sequence")
        by: List[str] = []
        maxspan = None
        if self.accept_kw("by"):
            by.append(self._identifier())
            while self.accept_op(","):
                by.append(self._identifier())
        if self.accept_kw("with"):
            self.expect_kw("maxspan")
            self.expect_op("=")
            num = self.next()
            if num.kind != "NUMBER":
                raise ParsingException("maxspan requires a duration")
            unit_tok = self.peek()
            unit = "s"
            if unit_tok.kind in ("IDENT", "KEYWORD") and str(
                    unit_tok.value).lower() in _UNITS_MS:
                unit = str(self.next().value).lower()
            maxspan = float(num.value) * _UNITS_MS[unit]
        queries: List[EventQuery] = []
        until = None
        while True:
            if self.accept_op("["):
                eq = self._event_query()
                self.expect_op("]")
                if self.accept_kw("by"):
                    eq.join_keys.append(self._identifier())
                    while self.accept_op(","):
                        eq.join_keys.append(self._identifier())
                queries.append(eq)
                continue
            if self.accept_kw("until"):
                self.expect_op("[")
                until = self._event_query()
                self.expect_op("]")
                if self.accept_kw("by"):
                    until.join_keys.append(self._identifier())
                    while self.accept_op(","):
                        until.join_keys.append(self._identifier())
                continue
            break
        if len(queries) < 2:
            raise ParsingException(
                "sequence requires at least two event queries")
        n_keys = {len(q.join_keys) for q in queries}
        if len(n_keys) > 1:
            raise ParsingException(
                "all sequence queries need the same number of join keys")
        return EqlQuery("sequence", queries, by=by, maxspan_ms=maxspan,
                        until=until)


@dataclass
class _Event:
    ts: float
    tiebreak: Any
    index: str
    doc_id: str
    source: Dict[str, Any]


class EqlService:
    """Plans and executes EQL searches (ref: x-pack/plugin/eql/.../
    execution/PlanExecutor + TransportEqlSearchAction)."""

    def __init__(self, node):
        self.node = node

    def search(self, index: str, body: Dict[str, Any]) -> Dict[str, Any]:
        start = time.monotonic()
        text = body.get("query")
        if not text:
            raise IllegalArgumentException("[query] is required")
        # EQL uses "..." for strings too; normalize double quotes that
        # enclose literals after an operator into single-quoted strings
        plan = EqlParser(_normalize_strings(text)).parse_eql()
        ts_field = body.get("timestamp_field", "@timestamp")
        cat_field = body.get("event_category_field", "event.category")
        tiebreak_field = body.get("tiebreaker_field")
        size = int(body.get("size", 10))
        fetch_size = int(body.get("fetch_size", 10000))
        extra_filter = body.get("filter")
        self._truncated = False

        if plan.kind == "event":
            events = self._fetch(index, plan.queries[0], ts_field,
                                 cat_field, tiebreak_field, extra_filter,
                                 fetch_size)
            events = _apply_pipes(events, plan)
            hits = {"total": {"value": len(events), "relation": "eq"},
                    "events": [self._render(e) for e in events[:size]]}
        else:
            seqs = self._sequences(index, plan, ts_field, cat_field,
                                   tiebreak_field, extra_filter, fetch_size)
            seqs = _apply_pipes(seqs, plan)
            hits = {"total": {"value": len(seqs), "relation": "eq"},
                    "sequences": [
                        {"join_keys": list(keys),
                         "events": [self._render(e) for e in evs]}
                        for keys, evs in seqs[:size]]}
        return {
            "is_partial": self._truncated,
            "is_running": False,
            "took": int((time.monotonic() - start) * 1000),
            "timed_out": False,
            "hits": hits,
        }

    # ------------------------------------------------------------------
    def _fetch(self, index: str, eq: EventQuery, ts_field: str,
               cat_field: str, tiebreak_field: Optional[str],
               extra_filter, fetch_size: int = 10000) -> List[_Event]:
        """Fetch an event query's matching events, time-ascending.

        Device filter when the condition translates to the query DSL;
        otherwise category-only device filter + host-side evaluate."""
        musts: List[Dict[str, Any]] = [
            {"exists": {"field": ts_field}}]       # events need a timestamp
        if eq.category is not None:
            musts.append({"term": {cat_field: {"value": eq.category}}})
        if extra_filter:
            musts.append(extra_filter)
        post_eval = None
        try:
            cond_q = ql.to_filter(eq.condition)
            musts.append(cond_q)
        except ParsingException:
            post_eval = eq.condition
        query = ({"bool": {"must": musts}} if musts else {"match_all": {}})
        sort = [{ts_field: {"order": "asc"}}]
        if tiebreak_field:
            sort.append({tiebreak_field: {"order": "asc"}})
        # windowed drain instead of one whole-index host read: at most
        # EQL_FETCH_WINDOW hits are resident per page, and the explicit
        # sort makes the cursor stream resumable if a context is lost
        # mid-drain. Results match the old single-read path exactly —
        # same order, same fetch_size cap, same truncation flag.
        from elasticsearch_tpu.search.service import (
            resumable_scroll_batches)
        window = max(1, min(fetch_size, EQL_FETCH_WINDOW))
        out: List[_Event] = []
        raw_seen = 0
        for batch in resumable_scroll_batches(
                self.node.search_service, index,
                {"query": query, "sort": sort, "_source": True}, window):
            for h in batch:
                if raw_seen >= fetch_size:
                    break
                raw_seen += 1
                src = h.get("_source", {}) or {}
                if post_eval is not None:
                    try:
                        ok = ql.evaluate(
                            post_eval,
                            lambda f, _s=src: _source_get(_s, f))
                    except Exception:
                        ok = False
                    if not ok:
                        continue
                sv = h.get("sort", [])
                if not sv or sv[0] is None:
                    continue                        # no usable timestamp
                ts = float(sv[0])
                tb = sv[1] if len(sv) > 1 else h["_id"]
                out.append(_Event(ts, tb, h["_index"], h["_id"], src))
            if raw_seen >= fetch_size:
                self._truncated = True              # stream cut at the cap
                break
        return out

    def _sequences(self, index: str, plan: EqlQuery, ts_field: str,
                   cat_field: str, tiebreak_field, extra_filter,
                   fetch_size: int = 10000):
        """Keyed stage state machine (ref: eql SequenceMatcher): events
        stream in time order; a partial sequence at stage i advances when
        stage i+1's query matches the same join key within maxspan."""
        n = len(plan.queries)
        streams: List[List[_Event]] = [
            self._fetch(index, q, ts_field, cat_field, tiebreak_field,
                        extra_filter, fetch_size)
            for q in plan.queries]
        until_events = (self._fetch(index, plan.until, ts_field, cat_field,
                                    tiebreak_field, extra_filter, fetch_size)
                        if plan.until is not None else [])

        def keys_of(e: _Event, stage_q: EventQuery):
            names = list(plan.by) + list(stage_q.join_keys)
            return tuple(_source_get(e.source, k) for k in names)

        # merge all stage streams into one time-ordered list of
        # (event, stage) — an event doc may match several stages
        tagged: List[Tuple[_Event, int]] = []
        for si, evs in enumerate(streams):
            tagged.extend((e, si) for e in evs)
        for e in until_events:
            tagged.append((e, -1))                   # until marker
        def tb_key(v):
            # numbers compare numerically, strings lexicographically
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                return (0, float(v), "")
            return (1, 0.0, str(v))

        tagged.sort(key=lambda t: (t[0].ts, tb_key(t[0].tiebreak),
                                   0 if t[1] == -1 else 1, t[1]))

        # one partial per (join key, stage): slots[s] holds the events of
        # the sequence awaiting stage s; a newer stage-0 event REPLACES
        # the old frame (Elastic's KeyToSequences/SequenceMatcher
        # semantics — the freshest candidate wins each stage)
        partials: Dict[tuple, Dict[int, List[_Event]]] = {}
        completed: List[Tuple[tuple, List[_Event]]] = []
        for e, stage in tagged:
            if stage == -1:
                k = keys_of(e, plan.until)
                partials.pop(k, None)                # until kills partials
                continue
            k = keys_of(e, plan.queries[stage])
            slots = partials.setdefault(k, {})
            if stage == 0:
                slots[1] = [e]
                continue
            p = slots.get(stage)
            if p is None:
                continue
            if (plan.maxspan_ms is not None
                    and e.ts - p[0].ts > plan.maxspan_ms):
                continue
            if e.doc_id == p[-1].doc_id and e.index == p[-1].index:
                continue                              # same event doc
            del slots[stage]
            seq = p + [e]
            if len(seq) == n:
                completed.append((k, seq))
            else:
                slots[stage + 1] = seq
        return completed

    def _render(self, e: _Event) -> Dict[str, Any]:
        return {"_index": e.index, "_id": e.doc_id, "_source": e.source}


def _apply_pipes(items, plan: EqlQuery):
    if plan.head is not None:
        items = items[: plan.head]
    if plan.tail is not None:
        items = items[-plan.tail:] if plan.tail else []
    return items


def _normalize_strings(text: str) -> str:
    """EQL string literals use double quotes; the shared tokenizer treats
    double quotes as quoted identifiers. Convert "..." literals to
    '...' (escaping embedded single quotes)."""
    out = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == '"':
            j = i + 1
            buf = []
            while j < n and text[j] != '"':
                if text[j] == "\\" and j + 1 < n:
                    buf.append(text[j + 1])
                    j += 2
                    continue
                buf.append(text[j])
                j += 1
            out.append("'" + "".join(buf).replace("'", "''") + "'")
            i = j + 1
        elif c == "'":
            j = i + 1
            while j < n and text[j] != "'":
                j += 1
            out.append(text[i: j + 1])
            i = j + 1
        else:
            out.append(c)
            i += 1
    return "".join(out)
