"""Searchable snapshots: lazy blob-backed shard storage + local cache.

The analogue of the reference's SearchableSnapshotDirectory (ref:
x-pack/plugin/searchable-snapshots/.../store/
SearchableSnapshotDirectory.java — a Lucene Directory whose file reads
fetch byte ranges from the repository on demand into a bounded local
cache, so a mounted index costs no local storage until queried).

Re-homed for this engine's storage model (whole-file npz segments, not
byte-range Lucene files):

- ``_mount`` writes the shard commit + a ``snapshot_store.json``
  manifest (repository, snapshot, per-segment blob names) but copies NO
  data files.
- Engine recovery defers any committed segment whose directory is
  missing when a manifest is present; the first search (or stats that
  need real segments) pulls the segment's files through the
  :class:`BlobCache` and loads it — the lazy-materialization moment.
- ``storage=shared_cache`` keeps the fetched files inside a BOUNDED
  node-level cache directory with LRU eviction (ref: the frozen tier's
  shared snapshot cache); ``storage=full_copy`` promotes fetched files
  to the shard directory permanently.
- `/_searchable_snapshots/stats` reports hits/misses/bytes fetched and
  evictions.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional

MANIFEST = "snapshot_store.json"


class BlobCache:
    """Node-level bounded file cache (ref: the shared snapshot cache,
    xpack.searchable.snapshot.shared_cache.size)."""

    def __init__(self, cache_dir: str,
                 budget_bytes: int = 1024 * 1024 * 1024):
        self.dir = cache_dir
        self.budget = budget_bytes
        os.makedirs(cache_dir, exist_ok=True)
        self._lock = threading.Lock()
        # key -> (path, size); LRU order
        self._entries: "OrderedDict[str, tuple]" = OrderedDict()
        self._size = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.bytes_fetched = 0
        # rebuild from a previous run's files
        for name in sorted(os.listdir(cache_dir)):
            p = os.path.join(cache_dir, name)
            if os.path.isfile(p):
                sz = os.path.getsize(p)
                self._entries[name] = (p, sz)
                self._size += sz

    @staticmethod
    def _key(repo: str, index: str, shard: str, blob: str) -> str:
        return f"{repo}~{index}~{shard}~{blob}".replace("/", "_")

    def get(self, repo: str, index: str, shard: str, blob: str,
            fetch) -> str:
        """Local path of the cached blob, fetching on miss."""
        key = self._key(repo, index, shard, blob)
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return hit[0]
        data = fetch()
        path = os.path.join(self.dir, key)
        tmp = f"{path}.tmp-{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        with self._lock:
            self.misses += 1
            self.bytes_fetched += len(data)
            if key in self._entries:
                # lost a concurrent-miss race: the winner already
                # accounted the entry — don't double-count the size
                self._entries.move_to_end(key)
                return path
            self._entries[key] = (path, len(data))
            self._size += len(data)
            while self._size > self.budget and len(self._entries) > 1:
                old_key, (old_path, old_size) = \
                    self._entries.popitem(last=False)
                if old_key == key:
                    self._entries[key] = (path, len(data))
                    break
                self._size -= old_size
                self.evictions += 1
                try:
                    os.remove(old_path)
                except OSError:
                    pass
        return path

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"size_bytes": self._size,
                    "budget_bytes": self.budget,
                    "num_files": len(self._entries),
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "bytes_fetched": self.bytes_fetched}


_caches: Dict[str, BlobCache] = {}
_caches_lock = threading.Lock()


def node_cache(data_path: str,
               budget_bytes: Optional[int] = None) -> BlobCache:
    with _caches_lock:
        cache = _caches.get(data_path)
        if cache is None:
            cache = _caches[data_path] = BlobCache(
                os.path.join(data_path, "_snapshot_cache"),
                budget_bytes or 1024 * 1024 * 1024)
        return cache


def write_manifest(shard_path: str, manifest: Dict[str, Any]) -> None:
    with open(os.path.join(shard_path, MANIFEST), "w") as f:
        json.dump(manifest, f)


def read_manifest(shard_path: str) -> Optional[Dict[str, Any]]:
    p = os.path.join(shard_path, MANIFEST)
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return json.load(f)


def materialize_segment(shard_path: str, seg_name: str,
                        repositories_service, data_path: str) -> bool:
    """Fetch one deferred segment's files into its directory through the
    node cache. Returns False when no manifest covers it (a genuinely
    missing segment — caller decides how to fail)."""
    m = read_manifest(shard_path)
    if m is None:
        return False
    files = m["segments"].get(seg_name)
    if files is None:
        return False
    repo = repositories_service.get_repository(m["repository"])
    container = repo.blobstore.container(
        "indices", m["source_index"], str(m["shard"]))
    cache = node_cache(data_path)
    seg_dir = os.path.join(shard_path, seg_name)
    os.makedirs(seg_dir, exist_ok=True)
    for fname, blob in files.items():
        dest = os.path.join(seg_dir, fname)
        # a concurrent miss can LRU-evict the returned path before we
        # consume it — refetch once on a vanished file
        for attempt in (0, 1):
            local = cache.get(m["repository"], m["source_index"],
                              str(m["shard"]), blob,
                              lambda b=blob: container.read_blob(b))
            try:
                if fname == "meta.json":
                    # meta.json is REWRITTEN with the mount's segment
                    # name (device caches key on names node-wide) —
                    # always a private ATOMIC copy; a hard link would
                    # mutate the shared cache entry and
                    # cross-contaminate other mounts
                    with open(local) as fh:
                        meta = json.load(fh)
                    meta["name"] = seg_name
                    tmp = f"{dest}.tmp-{threading.get_ident()}"
                    with open(tmp, "w") as fh:
                        json.dump(meta, fh)
                    os.replace(tmp, dest)
                elif not os.path.exists(dest):
                    if m.get("storage") == "full_copy":
                        shutil.copyfile(local, dest)
                    else:
                        # shared_cache: hard-link the immutable data
                        # files so eviction of the cache entry leaves
                        # open readers intact while reclaiming space
                        # once the segment drops
                        try:
                            os.link(local, dest)
                        except OSError:
                            shutil.copyfile(local, dest)
                break
            except FileNotFoundError:
                if attempt:
                    raise
    return True


def mount(node, repo_name: str, snapshot: str, index: str,
          renamed: str, storage: str = "full_copy") -> Dict[str, Any]:
    """MountSearchableSnapshotAction (REST shape): create the index
    shell + manifests WITHOUT copying data files; segments stream in on
    first search."""
    return mount_services(node.repositories_service, node.indices_service,
                          repo_name, snapshot, index, renamed, storage)


def mount_services(repositories_service, indices_service, repo_name: str,
                   snapshot: str, index: str, renamed: str,
                   storage: str = "full_copy") -> Dict[str, Any]:
    import uuid as _uuid

    from elasticsearch_tpu.common.errors import (
        IllegalArgumentException,
        ResourceAlreadyExistsException,
    )

    repo = repositories_service.get_repository(repo_name)
    snap = repo.get_snapshot(snapshot)
    if index not in snap["indices"]:
        raise IllegalArgumentException(
            f"index [{index}] not found in snapshot [{snapshot}]")
    if indices_service.has(renamed):
        raise ResourceAlreadyExistsException(
            f"cannot mount as [{renamed}]: index already exists")
    indices_service.validate_index_name(renamed)
    idx_meta = snap["indices"][index]
    index_path = os.path.join(indices_service.data_path, renamed)
    os.makedirs(index_path, exist_ok=True)
    with open(os.path.join(index_path, "_meta.json"), "w") as fh:
        json.dump({"settings": idx_meta["settings"],
                   "mappings": idx_meta["mappings"]}, fh)
    prefix = _uuid.uuid4().hex[:12]
    for shard_id, shard_meta in enumerate(idx_meta["shards"]):
        shard_path = os.path.join(index_path, str(shard_id))
        os.makedirs(shard_path, exist_ok=True)
        name_map = {s: f"{prefix}-m{i}"
                    for i, s in enumerate(shard_meta["segments"])}
        write_manifest(shard_path, {
            "repository": repo_name,
            "snapshot": snapshot,
            "source_index": index,
            "shard": shard_id,
            "storage": storage,
            "segments": {name_map[s]: files
                         for s, files in shard_meta["segments"].items()},
        })
        if shard_meta["commit"] is not None:
            commit = dict(shard_meta["commit"])
            commit["segments"] = [name_map[s] for s in commit["segments"]]
            commit["translog_generation"] = 1
            with open(os.path.join(shard_path, "segments.json"), "w") as fh:
                json.dump(commit, fh)
    indices_service.open_index(renamed)
    idx = indices_service.get(renamed)
    idx.update_settings({
        "index.blocks.write": True,
        "index.store.type": "snapshot",
        "index.store.snapshot.repository_name": repo_name,
        "index.store.snapshot.snapshot_name": snapshot,
        "index.store.snapshot.storage": storage,
    })
    return {"snapshot": {"snapshot": snapshot, "indices": [renamed],
                         "shards": {"total": idx.num_shards, "failed": 0,
                                    "successful": idx.num_shards}}}
