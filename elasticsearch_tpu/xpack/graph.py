"""Graph: significance-guided entity graph exploration.

Mirrors the reference's x-pack graph plugin (ref: x-pack/plugin/graph —
TransportGraphExploreAction: seed a vertex set from the query's top
(significant) terms, then hop along `connections` by re-querying with the
found vertices and collecting co-occurring terms; SURVEY.md §2.6).
Re-design for this engine: each hop is one TPU-path search whose terms
aggregations provide candidate vertices; significance weight = foreground
frequency / background frequency (the same signal significant_terms
uses), and connections record co-occurrence doc counts.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import IllegalArgumentException


class GraphService:
    def __init__(self, node):
        self.node = node

    def explore(self, index: str, body: Dict[str, Any]) -> Dict[str, Any]:
        start = time.monotonic()
        query = body.get("query", {"match_all": {}})
        vertices_spec = body.get("vertices", [])
        if not vertices_spec:
            raise IllegalArgumentException("[vertices] is required")
        connections = body.get("connections")

        total_docs = self._count(index, {"match_all": {}})
        fg_docs = self._count(index, query)

        vertices: List[Dict[str, Any]] = []
        vertex_index: Dict[tuple, int] = {}
        edges: List[Dict[str, Any]] = []

        def add_vertex(field, term, fg_count, depth):
            key = (field, term)
            if key in vertex_index:
                return vertex_index[key]
            bg = self._count(index, {"term": {field: {"value": term}}})
            fg_rate = fg_count / max(fg_docs, 1)
            bg_rate = bg / max(total_docs, 1)
            weight = fg_rate / bg_rate if bg_rate > 0 else 0.0
            vertex_index[key] = len(vertices)
            vertices.append({"field": field, "term": term,
                             "weight": weight, "depth": depth})
            return vertex_index[key]

        # seed hop: top terms of the root query
        seeds: List[int] = []
        for vs in vertices_spec:
            field = vs["field"]
            size = int(vs.get("size", 5))
            min_dc = int(vs.get("min_doc_count", 1))
            buckets = self._terms(index, query, field, size)
            for b in buckets:
                if b["doc_count"] < min_dc:
                    continue
                seeds.append(add_vertex(field, b["key"], b["doc_count"], 0))

        # connection hops (ref: GraphExploreRequest.Hop chain)
        frontier = list(seeds)
        hop = connections
        depth = 1
        while hop is not None and frontier:
            next_frontier: List[int] = []
            conn_specs = hop.get("vertices", [])
            for vi in frontier:
                v = vertices[vi]
                co_query = {"bool": {"must": [
                    query, {"term": {v["field"]: {"value": v["term"]}}}]}}
                co_docs = self._count(index, co_query)
                for cs in conn_specs:
                    field = cs["field"]
                    size = int(cs.get("size", 5))
                    min_dc = int(cs.get("min_doc_count", 1))
                    for b in self._terms(index, co_query, field, size):
                        if b["doc_count"] < min_dc:
                            continue
                        if (field, b["key"]) == (v["field"], v["term"]):
                            continue
                        ti = add_vertex(field, b["key"], b["doc_count"],
                                        depth)
                        edges.append({
                            "source": vi, "target": ti,
                            "weight": b["doc_count"] / max(co_docs, 1),
                            "doc_count": b["doc_count"]})
                        if ti not in next_frontier and vertices[ti][
                                "depth"] == depth:
                            next_frontier.append(ti)
            frontier = next_frontier
            hop = hop.get("connections")
            depth += 1

        return {
            "took": int((time.monotonic() - start) * 1000),
            "timed_out": False,
            "failures": [],
            "vertices": vertices,
            "connections": edges,
        }

    # ------------------------------------------------------------ helpers
    def _count(self, index: str, query: Dict[str, Any]) -> int:
        r = self.node.search_service.search(index, {
            "size": 0, "query": query, "track_total_hits": True})
        return r["hits"]["total"]["value"]

    def _terms(self, index: str, query: Dict[str, Any], field: str,
               size: int) -> List[Dict[str, Any]]:
        r = self.node.search_service.search(index, {
            "size": 0, "query": query,
            "aggs": {"t": {"terms": {"field": field, "size": size}}}})
        return r["aggregations"]["t"]["buckets"]
