"""CCR: cross-cluster replication — follower indices tail a leader.

Mirrors the reference's x-pack CCR plugin (ref: x-pack/plugin/ccr —
`ShardFollowNodeTask.java:62` polls the leader's soft-delete op history
via ShardChangesAction and applies batches to the follower;
auto-follow patterns; pause/resume/unfollow; SURVEY.md §2.3). Re-design
for this engine: the leader exposes its per-shard op history through a
`/{index}/_ccr/changes` endpoint backed by the translog (seqno-ordered
ops); followers poll over the remote-cluster HTTP channel (the DCN
path), apply ops through the normal indexing path, and checkpoint the
last applied seqno. If the leader has trimmed the requested history the
follower falls back to a full bootstrap copy (the analogue of CCR's
restore-from-leader file copy).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ResourceAlreadyExistsException,
    ResourceNotFoundException,
)


class FollowTask:
    def __init__(self, follower_index: str, remote_cluster: str,
                 leader_index: str):
        self.follower_index = follower_index
        self.remote_cluster = remote_cluster
        self.leader_index = leader_index
        self.status = "active"               # active | paused
        self.follower_global_checkpoint = -1
        self.operations_written = 0
        self.failed_reads = 0
        self.last_error: Optional[str] = None

    def info(self) -> Dict[str, Any]:
        return {
            "follower_index": self.follower_index,
            "remote_cluster": self.remote_cluster,
            "leader_index": self.leader_index,
            "status": self.status,
            "parameters": {},
        }

    def stats(self) -> Dict[str, Any]:
        return {
            "follower_index": self.follower_index,
            "follower_global_checkpoint": self.follower_global_checkpoint,
            "operations_written": self.operations_written,
            "failed_read_requests": self.failed_reads,
            "last_error": self.last_error,
        }


class CcrService:
    """Follow-task registry + the polling loop (ref: ShardFollowTasksExecutor
    — here one thread serves all followers; `sync()` is one read/apply
    cycle and is also called inline so tests are deterministic)."""

    POLL_INTERVAL_S = 0.5

    def __init__(self, node):
        self.node = node
        self.tasks: Dict[str, FollowTask] = {}
        self.auto_follow_patterns: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------- leader
    def changes(self, index: str, from_seq_no: int,
                max_operations: int = 1024) -> Dict[str, Any]:
        """Leader side: op history from the translog (the ShardChanges
        analogue). Returns ops with seq_no >= from_seq_no in order."""
        idx = self.node.indices_service.get(index)
        ops: List[Dict[str, Any]] = []
        min_available = None
        max_seq = -1
        for shard in idx.shards:
            for op in shard.translog.read_ops(1):
                max_seq = max(max_seq, op.seq_no)
                if min_available is None or op.seq_no < min_available:
                    min_available = op.seq_no
                if op.seq_no >= from_seq_no:
                    ops.append(op.to_dict())
        ops.sort(key=lambda o: o["seq_no"])
        # history gap: translog trimmed past the requested seqno
        history_complete = (from_seq_no <= 0
                            or min_available is None
                            or min_available <= from_seq_no)
        return {"operations": ops[:max_operations],
                "max_seq_no": max_seq,
                "history_complete": history_complete}

    # ----------------------------------------------------------- follower
    def follow(self, follower_index: str, body: Dict[str, Any]):
        remote = body.get("remote_cluster")
        leader = body.get("leader_index")
        if not remote or not leader:
            raise IllegalArgumentException(
                "remote_cluster and leader_index are required")
        with self._lock:
            if follower_index in self.tasks:
                raise ResourceAlreadyExistsException(
                    f"follower index [{follower_index}] already exists")
        client = self.node.remote_cluster_service.get_client(remote)
        # bootstrap: leader mappings → create follower (the restore step)
        mapping = client.request("GET", f"/{leader}/_mapping")
        mappings = mapping.get(leader, {}).get("mappings", {})
        if follower_index not in self.node.indices_service.indices:
            self.node.indices_service.create_index(
                follower_index, {}, mappings or None)
        task = FollowTask(follower_index, remote, leader)
        with self._lock:
            self.tasks[follower_index] = task
        self.sync(follower_index)
        self._ensure_thread()
        return {"follow_index_created": True,
                "follow_index_shards_acked": True,
                "index_following_started": True}

    def sync(self, follower_index: str) -> int:
        """One read/apply cycle; returns ops applied."""
        task = self.tasks.get(follower_index)
        if task is None or task.status != "active":
            return 0
        client = self.node.remote_cluster_service.get_client(
            task.remote_cluster)
        try:
            r = client.request(
                "POST", f"/{task.leader_index}/_ccr/changes",
                {"from_seq_no": task.follower_global_checkpoint + 1})
        except Exception as e:                    # leader unreachable
            task.failed_reads += 1
            task.last_error = str(e)
            return 0
        if not r.get("history_complete", True):
            return self._bootstrap_copy(task, client)
        fidx = self.node.indices_service.get(task.follower_index)
        n = 0
        for op in r.get("operations", []):
            if op["seq_no"] <= task.follower_global_checkpoint:
                continue
            if op.get("op") == "delete":
                try:
                    fidx.delete_doc(op["id"])
                except Exception:
                    pass
            elif op.get("op") == "index":
                fidx.index_doc(op["id"], op["source"])
            task.follower_global_checkpoint = op["seq_no"]
            n += 1
        if n:
            fidx.refresh()
            task.operations_written += n
        return n

    def _bootstrap_copy(self, task: FollowTask,
                        client) -> int:
        """Full resync when leader history is unavailable (the analogue
        of CCR's restore-from-leader)."""
        fidx = self.node.indices_service.get(task.follower_index)
        # record the leader's max seqno BEFORE snapshotting: ops indexed
        # during/after the copy have higher seqnos and will be replayed
        # by later syncs from this checkpoint (re-applying a copied doc
        # is an idempotent upsert) — advancing past them would drop them
        pre_copy = client.request(
            "POST", f"/{task.leader_index}/_ccr/changes",
            {"from_seq_no": 0, "max_operations": 0})
        n = 0
        r = client.request(
            "POST", f"/{task.leader_index}/_search?scroll=1m",
            {"query": {"match_all": {}}, "size": 1000})
        while True:
            hits = r["hits"]["hits"]
            if not hits:
                break
            for h in hits:
                fidx.index_doc(h["_id"], h["_source"])
                n += 1
            r = client.request("POST", "/_search/scroll",
                               {"scroll_id": r["_scroll_id"]})
        fidx.refresh()
        task.operations_written += n
        task.follower_global_checkpoint = max(
            task.follower_global_checkpoint,
            pre_copy.get("max_seq_no", -1))
        return n

    def pause_follow(self, follower_index: str):
        self._get(follower_index).status = "paused"
        return {"acknowledged": True}

    def resume_follow(self, follower_index: str):
        self._get(follower_index).status = "active"
        self.sync(follower_index)
        return {"acknowledged": True}

    def unfollow(self, follower_index: str):
        self._get(follower_index)
        with self._lock:
            del self.tasks[follower_index]
        return {"acknowledged": True}

    def stats(self) -> Dict[str, Any]:
        return {"follow_stats": {"indices": [
            {"index": t.follower_index, "shards": [t.stats()]}
            for t in self.tasks.values()]},
            "auto_follow_stats": {
                "number_of_successful_follow_indices": 0}}

    def follow_info(self, follower_index: str) -> Dict[str, Any]:
        return {"follower_indices": [self._get(follower_index).info()]}

    def _get(self, follower_index: str) -> FollowTask:
        t = self.tasks.get(follower_index)
        if t is None:
            raise ResourceNotFoundException(
                f"follower index [{follower_index}] does not exist")
        return t

    # ------------------------------------------------------- auto-follow
    def put_auto_follow(self, name: str, body: Dict[str, Any]):
        if not body.get("remote_cluster") or not body.get(
                "leader_index_patterns"):
            raise IllegalArgumentException(
                "remote_cluster and leader_index_patterns are required")
        self.auto_follow_patterns[name] = dict(body)
        return {"acknowledged": True}

    def get_auto_follow(self, name: Optional[str] = None):
        if name is not None:
            if name not in self.auto_follow_patterns:
                raise ResourceNotFoundException(
                    f"auto-follow pattern [{name}] is missing")
            items = {name: self.auto_follow_patterns[name]}
        else:
            items = self.auto_follow_patterns
        return {"patterns": [{"name": n, "pattern": p}
                             for n, p in items.items()]}

    def delete_auto_follow(self, name: str):
        if name not in self.auto_follow_patterns:
            raise ResourceNotFoundException(
                f"auto-follow pattern [{name}] is missing")
        del self.auto_follow_patterns[name]
        return {"acknowledged": True}

    def scan_auto_follow(self):
        """One auto-follow coordinator pass: follow new leader indices
        matching registered patterns (ref: AutoFollowCoordinator)."""
        import fnmatch
        for name, pat in self.auto_follow_patterns.items():
            remote = pat["remote_cluster"]
            try:
                client = self.node.remote_cluster_service.get_client(remote)
                cat = client.request("GET", "/_cat/indices")
            except Exception:
                continue
            leader_names = []
            if isinstance(cat, dict) and "_cat" in cat:
                for line in cat["_cat"].splitlines():
                    parts = line.split()
                    if len(parts) >= 3:
                        leader_names.append(parts[2])
            prefix = pat.get("follow_index_pattern", "{{leader_index}}")
            for leader in leader_names:
                if not any(fnmatch.fnmatch(leader, p)
                           for p in pat["leader_index_patterns"]):
                    continue
                follower = prefix.replace("{{leader_index}}", leader)
                if follower in self.tasks:
                    continue
                try:
                    self.follow(follower, {"remote_cluster": remote,
                                           "leader_index": leader})
                except Exception:
                    continue

    # ---------------------------------------------------------- lifecycle
    def _ensure_thread(self):
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.POLL_INTERVAL_S):
                for name in list(self.tasks):
                    try:
                        self.sync(name)
                    except Exception:
                        pass
                if self.auto_follow_patterns:
                    self.scan_auto_follow()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="ccr-follower")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
