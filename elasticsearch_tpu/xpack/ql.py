"""QL: shared query-language core for SQL and EQL.

Mirrors the reference's x-pack `ql` module (ref: x-pack/plugin/ql — the
shared expression tree, literal/attribute resolution, and DSL translation
layer that both SQL and EQL planners build on; SURVEY.md §2.6). Re-design
for this engine: a hand-written tokenizer + expression AST whose leaves
translate directly to the framework's JSON query DSL (`to_filter`) and
evaluate row-wise on fetched documents (`evaluate`) for projections and
HAVING — the compute-heavy filtering/scoring still runs through the TPU
search path; QL is purely a front-end.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import Any, Callable, Dict, List, Optional

from elasticsearch_tpu.common.errors import ParsingException


# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------

@dataclass
class Token:
    kind: str      # KEYWORD | IDENT | STRING | NUMBER | OP | EOF
    value: Any
    pos: int


_OPS = ["<=", ">=", "!=", "<>", "==", "=", "<", ">", "+", "-", "*", "/",
        "%", "(", ")", ",", ".", ":", "[", "]", "|"]

_KEYWORDS = {
    "select", "distinct", "from", "where", "group", "by", "having",
    "order", "asc", "desc", "limit", "offset", "and", "or", "not", "in",
    "like", "rlike", "between", "is", "null", "true", "false", "as",
    "show", "tables", "columns", "functions", "describe", "desc",
    "match", "query", "exists", "any", "of", "join", "until", "sequence",
    "sample", "with", "maxspan", "untilspan", "runs", "escape", "cast",
    "nulls", "first", "last", "top", "sys", "types", "catalog",
    "table",
}


def tokenize(text: str, keywords: Optional[set] = None) -> List[Token]:
    keywords = keywords if keywords is not None else _KEYWORDS
    toks: List[Token] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c.isspace():
            i += 1
            continue
        if c == "-" and text[i:i + 2] == "--":           # line comment
            while i < n and text[i] != "\n":
                i += 1
            continue
        if c == "/" and text[i:i + 2] == "/*":           # block comment
            j = text.find("*/", i + 2)
            i = n if j < 0 else j + 2
            continue
        if c == "'":                                      # string literal
            j = i + 1
            out = []
            while j < n:
                if text[j] == "'" and j + 1 < n and text[j + 1] == "'":
                    out.append("'")
                    j += 2
                    continue
                if text[j] == "'":
                    break
                out.append(text[j])
                j += 1
            if j >= n:
                raise ParsingException(f"Unterminated string at {i}")
            toks.append(Token("STRING", "".join(out), i))
            i = j + 1
            continue
        if c == '"' or c == "`":                          # quoted identifier
            close = c
            j = text.find(close, i + 1)
            if j < 0:
                raise ParsingException(f"Unterminated identifier at {i}")
            toks.append(Token("IDENT", text[i + 1:j], i))
            i = j + 1
            continue
        m = re.match(r"\d+(\.\d+)?([eE][+-]?\d+)?", text[i:])
        if m:
            s = m.group(0)
            toks.append(Token(
                "NUMBER",
                float(s) if ("." in s or "e" in s or "E" in s) else int(s),
                i))
            i += len(s)
            continue
        m = re.match(r"[A-Za-z_][A-Za-z0-9_]*", text[i:])
        if m:
            word = m.group(0)
            kind = "KEYWORD" if word.lower() in keywords else "IDENT"
            toks.append(Token(
                kind, word.lower() if kind == "KEYWORD" else word, i))
            i += len(word)
            continue
        for op in _OPS:
            if text.startswith(op, i):
                toks.append(Token("OP", op, i))
                i += len(op)
                break
        else:
            raise ParsingException(f"Unexpected character {c!r} at {i}")
    toks.append(Token("EOF", None, n))
    return toks


# ---------------------------------------------------------------------------
# expression AST
# ---------------------------------------------------------------------------

class Expr:
    pass


@dataclass
class Literal(Expr):
    value: Any


@dataclass
class FieldRef(Expr):
    name: str


@dataclass
class Call(Expr):
    name: str                       # upper-cased function name
    args: List[Expr] = field(default_factory=list)
    distinct: bool = False


@dataclass
class Binary(Expr):
    op: str                         # = != < <= > >= + - * / % AND OR
    left: Expr
    right: Expr


@dataclass
class Unary(Expr):
    op: str                         # NOT, NEG
    operand: Expr


@dataclass
class InList(Expr):
    expr: Expr
    options: List[Expr]
    negated: bool = False


@dataclass
class Between(Expr):
    expr: Expr
    low: Expr
    high: Expr
    negated: bool = False


@dataclass
class Like(Expr):
    expr: Expr
    pattern: str                    # SQL LIKE pattern (% and _)
    negated: bool = False
    regex: bool = False             # RLIKE


@dataclass
class IsNull(Expr):
    expr: Expr
    negated: bool = False


AGGREGATE_FUNCTIONS = {"COUNT", "SUM", "AVG", "MIN", "MAX", "STDDEV",
                       "VARIANCE", "PERCENTILE", "CARDINALITY"}


def has_aggregate(e: Expr) -> bool:
    if isinstance(e, Call):
        if e.name in AGGREGATE_FUNCTIONS:
            return True
        return any(has_aggregate(a) for a in e.args)
    if isinstance(e, Binary):
        return has_aggregate(e.left) or has_aggregate(e.right)
    if isinstance(e, Unary):
        return has_aggregate(e.operand)
    if isinstance(e, (InList, Between, Like, IsNull)):
        return has_aggregate(e.expr)
    return False


def field_refs(e: Expr, out: Optional[List[str]] = None) -> List[str]:
    if out is None:
        out = []
    if isinstance(e, FieldRef):
        out.append(e.name)
    elif isinstance(e, Call):
        for a in e.args:
            field_refs(a, out)
    elif isinstance(e, Binary):
        field_refs(e.left, out)
        field_refs(e.right, out)
    elif isinstance(e, Unary):
        field_refs(e.operand, out)
    elif isinstance(e, (InList, Between, Like, IsNull)):
        field_refs(e.expr, out)
    return out


# ---------------------------------------------------------------------------
# translation to the JSON query DSL
# ---------------------------------------------------------------------------

def _literal_value(e: Expr):
    if isinstance(e, Literal):
        return e.value
    if isinstance(e, Unary) and e.op == "NEG" and isinstance(e.operand, Literal):
        return -e.operand.value
    raise ParsingException("Expected a literal value")


def to_filter(e: Expr) -> Dict[str, Any]:
    """Translate a boolean expression into the framework's query DSL.

    Field-vs-literal comparisons become term/range queries; AND/OR/NOT
    become bool queries; MATCH()/QUERY() become full-text queries (ref:
    x-pack/plugin/ql .../planner/ExpressionTranslators.java)."""
    if isinstance(e, Binary):
        if e.op == "AND":
            return {"bool": {"must": [to_filter(e.left), to_filter(e.right)]}}
        if e.op == "OR":
            return {"bool": {"should": [to_filter(e.left),
                                        to_filter(e.right)],
                             "minimum_should_match": 1}}
        if e.op in ("=", "=="):
            f, v = _field_and_value(e)
            return {"term": {f: {"value": v}}}
        if e.op in ("!=", "<>"):
            f, v = _field_and_value(e)
            return {"bool": {"must_not": [{"term": {f: {"value": v}}}]}}
        if e.op in ("<", "<=", ">", ">="):
            f, v, op = _range_parts(e)
            key = {"<": "lt", "<=": "lte", ">": "gt", ">=": "gte"}[op]
            return {"range": {f: {key: v}}}
        raise ParsingException(f"Cannot translate operator [{e.op}]"
                               " to a query")
    if isinstance(e, Unary) and e.op == "NOT":
        return {"bool": {"must_not": [to_filter(e.operand)]}}
    if isinstance(e, InList):
        f = _field_name(e.expr)
        vals = [_literal_value(o) for o in e.options]
        q = {"terms": {f: vals}}
        return {"bool": {"must_not": [q]}} if e.negated else q
    if isinstance(e, Between):
        f = _field_name(e.expr)
        q = {"range": {f: {"gte": _literal_value(e.low),
                           "lte": _literal_value(e.high)}}}
        return {"bool": {"must_not": [q]}} if e.negated else q
    if isinstance(e, Like):
        f = _field_name(e.expr)
        if e.regex:
            q = {"regexp": {f: {"value": e.pattern}}}
        else:
            q = {"wildcard": {f: {
                "value": e.pattern.replace("%", "*").replace("_", "?")}}}
        return {"bool": {"must_not": [q]}} if e.negated else q
    if isinstance(e, IsNull):
        q = {"exists": {"field": _field_name(e.expr)}}
        if e.negated:                       # IS NOT NULL
            return q
        return {"bool": {"must_not": [q]}}
    if isinstance(e, Call):
        if e.name == "MATCH":
            if len(e.args) < 2:
                raise ParsingException("MATCH requires (field, text)")
            f = _field_name(e.args[0])
            return {"match": {f: {"query": _literal_value(e.args[1])}}}
        if e.name == "QUERY":
            return {"query_string": {"query": _literal_value(e.args[0])}}
        if e.name == "EXISTS":
            return {"exists": {"field": _field_name(e.args[0])}}
        # EQL string predicates (ref: x-pack/plugin/eql function registry)
        if e.name == "WILDCARD":
            f = _field_name(e.args[0])
            pats = [{"wildcard": {f: {"value": _literal_value(a)}}}
                    for a in e.args[1:]]
            return pats[0] if len(pats) == 1 else {
                "bool": {"should": pats, "minimum_should_match": 1}}
        if e.name == "STARTSWITH":
            return {"prefix": {_field_name(e.args[0]): {
                "value": _literal_value(e.args[1])}}}
        if e.name == "ENDSWITH":
            return {"wildcard": {_field_name(e.args[0]): {
                "value": "*" + _literal_value(e.args[1])}}}
        if e.name == "STRINGCONTAINS":
            return {"wildcard": {_field_name(e.args[0]): {
                "value": "*" + _literal_value(e.args[1]) + "*"}}}
    if isinstance(e, Literal) and e.value is True:
        return {"match_all": {}}
    raise ParsingException(
        f"Cannot translate expression [{type(e).__name__}] to a query")


def _field_name(e: Expr) -> str:
    if isinstance(e, FieldRef):
        return e.name
    raise ParsingException("Expected a field reference")


def _field_and_value(e: Binary):
    if isinstance(e.left, FieldRef):
        return e.left.name, _literal_value(e.right)
    if isinstance(e.right, FieldRef):
        return e.right.name, _literal_value(e.left)
    raise ParsingException("Comparison must involve a field and a literal")


def _range_parts(e: Binary):
    if isinstance(e.left, FieldRef):
        return e.left.name, _literal_value(e.right), e.op
    if isinstance(e.right, FieldRef):
        flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
        return e.right.name, _literal_value(e.left), flip[e.op]
    raise ParsingException("Comparison must involve a field and a literal")


# ---------------------------------------------------------------------------
# row-wise evaluation (projections, HAVING)
# ---------------------------------------------------------------------------

def _dt(v) -> datetime:
    if isinstance(v, (int, float)):
        return datetime.fromtimestamp(v / 1000.0, tz=timezone.utc)
    return datetime.fromisoformat(str(v).replace("Z", "+00:00"))


_SCALARS: Dict[str, Callable] = {
    "ABS": lambda x: abs(x),
    "ROUND": lambda x, n=0: round(x, int(n)),
    "TRUNCATE": lambda x, n=0: math.trunc(x * 10 ** int(n)) / 10 ** int(n),
    "FLOOR": lambda x: math.floor(x),
    "CEIL": lambda x: math.ceil(x),
    "CEILING": lambda x: math.ceil(x),
    "SQRT": lambda x: math.sqrt(x),
    "CBRT": lambda x: x ** (1 / 3) if x >= 0 else -((-x) ** (1 / 3)),
    "EXP": lambda x: math.exp(x),
    "LOG": lambda x: math.log(x),
    "LOG10": lambda x: math.log10(x),
    "POWER": lambda x, y: x ** y,
    "MOD": lambda x, y: x % y,
    "SIGN": lambda x: (x > 0) - (x < 0),
    "SIN": math.sin, "COS": math.cos, "TAN": math.tan,
    "ASIN": math.asin, "ACOS": math.acos, "ATAN": math.atan,
    "PI": lambda: math.pi,
    "CONCAT": lambda *a: "".join(str(x) for x in a),
    "LENGTH": lambda s: len(str(s)),
    "CHAR_LENGTH": lambda s: len(str(s)),
    "UPPER": lambda s: str(s).upper(),
    "UCASE": lambda s: str(s).upper(),
    "LOWER": lambda s: str(s).lower(),
    "LCASE": lambda s: str(s).lower(),
    "LTRIM": lambda s: str(s).lstrip(),
    "RTRIM": lambda s: str(s).rstrip(),
    "TRIM": lambda s: str(s).strip(),
    "LEFT": lambda s, n: str(s)[: int(n)],
    "RIGHT": lambda s, n: str(s)[-int(n):] if int(n) else "",
    "SUBSTRING": lambda s, start, ln=None: (
        str(s)[int(start) - 1: int(start) - 1 + int(ln)]
        if ln is not None else str(s)[int(start) - 1:]),
    "REPLACE": lambda s, a, b: str(s).replace(str(a), str(b)),
    "REVERSE": lambda s: str(s)[::-1],
    "REPEAT": lambda s, n: str(s) * int(n),
    "LOCATE": lambda sub, s, start=1: (
        str(s).find(str(sub), int(start) - 1) + 1),
    "ASCII": lambda s: ord(str(s)[0]) if s else None,
    "SPACE": lambda n: " " * int(n),
    "GREATEST": lambda *a: max(a),
    "LEAST": lambda *a: min(a),
    "NULLIF": lambda a, b: None if a == b else a,
    "COALESCE": lambda *a: next((x for x in a if x is not None), None),
    "IFNULL": lambda a, b: b if a is None else a,
    "WILDCARD": lambda s, *pats: any(
        re.fullmatch(re.escape(p).replace(r"\*", ".*"), str(s)) is not None
        for p in pats),
    "STARTSWITH": lambda s, p: str(s).startswith(str(p)),
    "ENDSWITH": lambda s, p: str(s).endswith(str(p)),
    "STRINGCONTAINS": lambda s, p: str(p) in str(s),
    "ADD": lambda a, b: a + b,
    "SUBTRACT": lambda a, b: a - b,
    "MULTIPLY": lambda a, b: a * b,
    "DIVIDE": lambda a, b: a / b if b else None,
    "MODULO": lambda a, b: a % b if b else None,
    "NUMBER": lambda s: float(s),
    "STRING": lambda v: str(v),
    "YEAR": lambda v: _dt(v).year,
    "MONTH": lambda v: _dt(v).month,
    "DAY": lambda v: _dt(v).day,
    "DAY_OF_MONTH": lambda v: _dt(v).day,
    "DAY_OF_WEEK": lambda v: _dt(v).isoweekday() % 7 + 1,
    "DAY_OF_YEAR": lambda v: _dt(v).timetuple().tm_yday,
    "HOUR": lambda v: _dt(v).hour,
    "MINUTE": lambda v: _dt(v).minute,
    "SECOND": lambda v: _dt(v).second,
}


def evaluate(e: Expr, row: Callable[[str], Any]) -> Any:
    """Evaluate an expression against one row; `row(field)` supplies
    document/bucket values (the SQL analogue of Painless's doc access)."""
    if isinstance(e, Literal):
        return e.value
    if isinstance(e, FieldRef):
        return row(e.name)
    if isinstance(e, Unary):
        v = evaluate(e.operand, row)
        if e.op == "NEG":
            return None if v is None else -v
        if e.op == "NOT":
            return None if v is None else not v
    if isinstance(e, Binary):
        if e.op == "AND":
            return bool(evaluate(e.left, row)) and bool(
                evaluate(e.right, row))
        if e.op == "OR":
            return bool(evaluate(e.left, row)) or bool(
                evaluate(e.right, row))
        lv, rv = evaluate(e.left, row), evaluate(e.right, row)
        if lv is None or rv is None:
            return None
        return {
            "+": lambda: lv + rv, "-": lambda: lv - rv,
            "*": lambda: lv * rv,
            "/": lambda: lv / rv if rv else None,
            "%": lambda: lv % rv if rv else None,
            "=": lambda: lv == rv, "==": lambda: lv == rv,
            "!=": lambda: lv != rv, "<>": lambda: lv != rv,
            "<": lambda: lv < rv, "<=": lambda: lv <= rv,
            ">": lambda: lv > rv, ">=": lambda: lv >= rv,
        }[e.op]()
    if isinstance(e, InList):
        v = evaluate(e.expr, row)
        hit = any(v == _literal_value(o) for o in e.options)
        return (not hit) if e.negated else hit
    if isinstance(e, Between):
        v = evaluate(e.expr, row)
        if v is None:
            return None
        hit = _literal_value(e.low) <= v <= _literal_value(e.high)
        return (not hit) if e.negated else hit
    if isinstance(e, Like):
        v = evaluate(e.expr, row)
        if v is None:
            return None
        if e.regex:
            hit = re.fullmatch(e.pattern, str(v)) is not None
        else:
            rx = re.escape(e.pattern).replace("%", ".*").replace("_", ".")
            hit = re.fullmatch(rx, str(v)) is not None
        return (not hit) if e.negated else hit
    if isinstance(e, IsNull):
        v = evaluate(e.expr, row)
        return (v is not None) if e.negated else (v is None)
    if isinstance(e, Call):
        if e.name in AGGREGATE_FUNCTIONS:
            # aggregates resolve through the row accessor by their
            # canonical key (filled from the aggs response)
            return row(expr_key(e))
        fn = _SCALARS.get(e.name)
        if fn is None:
            raise ParsingException(f"Unknown function [{e.name}]")
        args = [evaluate(a, row) for a in e.args]
        if any(a is None for a in args) and e.name not in (
                "COALESCE", "IFNULL", "NULLIF", "CONCAT"):
            return None
        return fn(*args)
    raise ParsingException(f"Cannot evaluate [{type(e).__name__}]")


def expr_key(e: Expr) -> str:
    """Canonical textual key for an expression (column naming + agg keys)."""
    if isinstance(e, Literal):
        return repr(e.value)
    if isinstance(e, FieldRef):
        return e.name
    if isinstance(e, Call):
        inner = ", ".join(expr_key(a) for a in e.args)
        if e.distinct:
            inner = "DISTINCT " + inner
        return f"{e.name}({inner})"
    if isinstance(e, Binary):
        return f"{expr_key(e.left)} {e.op} {expr_key(e.right)}"
    if isinstance(e, Unary):
        return ("-" if e.op == "NEG" else "NOT ") + expr_key(e.operand)
    if isinstance(e, InList):
        return f"{expr_key(e.expr)} IN (...)"
    if isinstance(e, Between):
        return f"{expr_key(e.expr)} BETWEEN"
    if isinstance(e, Like):
        return f"{expr_key(e.expr)} LIKE {e.pattern!r}"
    if isinstance(e, IsNull):
        return f"{expr_key(e.expr)} IS NULL"
    return type(e).__name__
