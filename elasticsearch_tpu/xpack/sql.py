"""SQL: SQL front-end over the search engine.

Mirrors the reference's x-pack SQL plugin (ref: x-pack/plugin/sql — ANTLR
parser → logical/physical plan → query DSL + composite aggs under
`execution/search/`; JDBC/CLI wire formats; SURVEY.md §2.6). Re-design
for this engine: a recursive-descent parser over the shared QL core
(xpack/ql.py) producing a logical plan that executes in exactly two
shapes, both riding the TPU search path:

- **row plan** (no GROUP BY / aggregates): WHERE → query DSL, ORDER BY →
  sort spec, LIMIT → size; scalar projections evaluated row-wise over
  `_source` (ref: SQL's QueryContainer + HitExtractors).
- **agg plan** (GROUP BY and/or aggregate functions): grouping keys →
  the `composite` aggregation with after-key paging, aggregate functions
  → metric sub-aggs, HAVING evaluated per bucket on the coordinator
  (ref: SQL's composite-agg cursoring in execution/search/).

Also: SHOW TABLES / SHOW COLUMNS / DESCRIBE / SHOW FUNCTIONS, cursors
with fetch_size paging, and a `translate` mode returning the generated
query DSL (the `/_sql/translate` API).
"""

from __future__ import annotations

import base64
import json
import threading
import uuid
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ParsingException,
)
from elasticsearch_tpu.xpack import ql
from elasticsearch_tpu.xpack.ql import (
    Between,
    Binary,
    Call,
    Expr,
    FieldRef,
    InList,
    IsNull,
    Like,
    Literal,
    Token,
    Unary,
    evaluate,
    expr_key,
    has_aggregate,
    to_filter,
    tokenize,
)

DEFAULT_FETCH_SIZE = 1000


# ---------------------------------------------------------------------------
# statements
# ---------------------------------------------------------------------------

@dataclass
class SelectItem:
    expr: Expr
    alias: Optional[str] = None

    @property
    def name(self) -> str:
        return self.alias or expr_key(self.expr)


@dataclass
class SelectStmt:
    items: List[SelectItem]
    table: Optional[str]
    where: Optional[Expr] = None
    group_by: List[Expr] = dc_field(default_factory=list)
    having: Optional[Expr] = None
    order_by: List[Tuple[Expr, str]] = dc_field(default_factory=list)
    limit: Optional[int] = None
    distinct: bool = False


@dataclass
class ShowTables:
    pattern: Optional[str] = None


@dataclass
class ShowColumns:
    table: str = ""


@dataclass
class ShowFunctions:
    pattern: Optional[str] = None


@dataclass
class SysTables:
    pattern: Optional[str] = None


@dataclass
class SysColumns:
    table_pattern: Optional[str] = None
    column_pattern: Optional[str] = None


@dataclass
class SysTypes:
    pass


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------

class Parser:
    """Recursive-descent SQL parser (the ANTLR grammar's hand-written
    equivalent, ref: x-pack/plugin/sql/.../parser/SqlBaseParser)."""

    def __init__(self, text: str):
        self.toks = tokenize(text)
        self.i = 0

    # -- token plumbing
    def peek(self) -> Token:
        return self.toks[self.i]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_kw(self, *kws) -> Optional[str]:
        t = self.peek()
        if t.kind == "KEYWORD" and t.value in kws:
            self.i += 1
            return t.value
        return None

    def expect_kw(self, kw: str):
        if not self.accept_kw(kw):
            raise ParsingException(
                f"Expected [{kw.upper()}] but got [{self.peek().value}]")

    def accept_op(self, *ops) -> Optional[str]:
        t = self.peek()
        if t.kind == "OP" and t.value in ops:
            self.i += 1
            return t.value
        return None

    def expect_op(self, op: str):
        if not self.accept_op(op):
            raise ParsingException(
                f"Expected [{op}] but got [{self.peek().value}]")

    # -- entry
    def parse(self):
        if self.accept_kw("show"):
            if self.accept_kw("tables"):
                pat = None
                if self.accept_kw("like"):
                    pat = self._string()
                return ShowTables(pat)
            if self.accept_kw("columns"):
                self.accept_kw("from")
                return ShowColumns(self._identifier())
            if self.accept_kw("functions"):
                pat = None
                if self.accept_kw("like"):
                    pat = self._string()
                return ShowFunctions(pat)
            raise ParsingException("Expected TABLES, COLUMNS or FUNCTIONS")
        if self.accept_kw("describe") or self.accept_kw("desc"):
            return ShowColumns(self._identifier())
        if self.accept_kw("sys"):
            # ODBC catalog statements (ref: x-pack/plugin/sql
            # SysTables/SysColumns/SysTypes commands — the ODBC
            # driver's SQLTables/SQLColumns/SQLGetTypeInfo path)
            if self.accept_kw("tables"):
                pat = None
                if self.accept_kw("catalog"):
                    # single-catalog engine: the pattern only narrows
                    # to "this cluster or nothing"
                    self.expect_kw("like")
                    self._string()
                if self.accept_kw("like"):
                    pat = self._string()
                return SysTables(pat)
            if self.accept_kw("columns"):
                tpat = cpat = None
                if self.accept_kw("table"):
                    self.expect_kw("like")
                    tpat = self._string()
                if self.accept_kw("like"):
                    cpat = self._string()
                return SysColumns(tpat, cpat)
            if self.accept_kw("types"):
                return SysTypes()
            raise ParsingException("Expected TABLES, COLUMNS or TYPES")
        self.expect_kw("select")
        return self._select()

    def _select(self) -> SelectStmt:
        distinct = bool(self.accept_kw("distinct"))
        items = [self._select_item()]
        while self.accept_op(","):
            items.append(self._select_item())
        table = None
        if self.accept_kw("from"):
            table = self._identifier()
        where = group_by = having = None
        group_exprs: List[Expr] = []
        order: List[Tuple[Expr, str]] = []
        limit = None
        if self.accept_kw("where"):
            where = self._expr()
        if self.accept_kw("group"):
            self.expect_kw("by")
            group_exprs.append(self._expr())
            while self.accept_op(","):
                group_exprs.append(self._expr())
        if self.accept_kw("having"):
            having = self._expr()
        if self.accept_kw("order"):
            self.expect_kw("by")
            order.append(self._order_item())
            while self.accept_op(","):
                order.append(self._order_item())
        if self.accept_kw("limit"):
            t = self.next()
            if t.kind != "NUMBER":
                raise ParsingException("LIMIT requires a number")
            limit = int(t.value)
        if self.peek().kind != "EOF":
            raise ParsingException(
                f"Unexpected token [{self.peek().value}]")
        return SelectStmt(items, table, where, group_exprs, having, order,
                          limit, distinct)

    def _order_item(self) -> Tuple[Expr, str]:
        e = self._expr()
        direction = "asc"
        if self.accept_kw("asc"):
            direction = "asc"
        elif self.accept_kw("desc"):
            direction = "desc"
        # NULLS FIRST/LAST accepted and ignored (rows with null sort keys
        # always sort last, like ES missing:_last default)
        if self.accept_kw("nulls"):
            if not (self.accept_kw("first") or self.accept_kw("last")):
                raise ParsingException("Expected FIRST or LAST")
        return e, direction

    def _select_item(self) -> SelectItem:
        if self.accept_op("*"):
            return SelectItem(FieldRef("*"))
        e = self._expr()
        alias = None
        if self.accept_kw("as"):
            alias = self._identifier()
        elif self.peek().kind == "IDENT":
            alias = self._identifier()
        return SelectItem(e, alias)

    def _identifier(self) -> str:
        t = self.next()
        if t.kind not in ("IDENT", "STRING", "KEYWORD"):
            raise ParsingException(f"Expected identifier, got [{t.value}]")
        name = str(t.value)
        # dotted paths / index patterns (logs-*, logs-2021.01)
        while True:
            op = self.accept_op(".", "-", "*", ":")
            if op is None:
                break
            if op == "*":
                name += "*"
                continue
            nxt = self.peek()
            if nxt.kind in ("IDENT", "KEYWORD", "NUMBER"):
                self.next()
                name += op + str(
                    int(nxt.value) if isinstance(nxt.value, float)
                    and nxt.value == int(nxt.value) else nxt.value)
            elif op == "-" or op == ".":
                name += op
            else:
                raise ParsingException("Bad identifier")
        return name

    def _string(self) -> str:
        t = self.next()
        if t.kind != "STRING":
            raise ParsingException("Expected a string literal")
        return t.value

    # -- expressions (precedence climbing)
    def _expr(self) -> Expr:
        return self._or()

    def _or(self) -> Expr:
        e = self._and()
        while self.accept_kw("or"):
            e = Binary("OR", e, self._and())
        return e

    def _and(self) -> Expr:
        e = self._not()
        while self.accept_kw("and"):
            e = Binary("AND", e, self._not())
        return e

    def _not(self) -> Expr:
        if self.accept_kw("not"):
            return Unary("NOT", self._not())
        return self._predicate()

    def _predicate(self) -> Expr:
        e = self._additive()
        negated = bool(self.accept_kw("not"))
        if self.accept_kw("in"):
            self.expect_op("(")
            opts = [self._additive()]
            while self.accept_op(","):
                opts.append(self._additive())
            self.expect_op(")")
            return InList(e, opts, negated)
        if self.accept_kw("between"):
            low = self._additive()
            self.expect_kw("and")
            return Between(e, low, self._additive(), negated)
        if self.accept_kw("like"):
            return Like(e, self._string(), negated)
        if self.accept_kw("rlike"):
            return Like(e, self._string(), negated, regex=True)
        if self.accept_kw("is"):
            neg = bool(self.accept_kw("not"))
            self.expect_kw("null")
            return IsNull(e, neg)
        if negated:
            raise ParsingException("Dangling NOT")
        op = self.accept_op("=", "==", "!=", "<>", "<", "<=", ">", ">=")
        if op:
            return Binary(op, e, self._additive())
        return e

    def _additive(self) -> Expr:
        e = self._multiplicative()
        while True:
            op = self.accept_op("+", "-")
            if not op:
                return e
            e = Binary(op, e, self._multiplicative())

    def _multiplicative(self) -> Expr:
        e = self._unary()
        while True:
            op = self.accept_op("*", "/", "%")
            if not op:
                return e
            e = Binary(op, e, self._unary())

    def _unary(self) -> Expr:
        if self.accept_op("-"):
            return Unary("NEG", self._unary())
        if self.accept_op("+"):
            return self._unary()
        return self._primary()

    def _primary(self) -> Expr:
        t = self.peek()
        if t.kind == "NUMBER":
            self.next()
            return Literal(t.value)
        if t.kind == "STRING":
            self.next()
            return Literal(t.value)
        if t.kind == "KEYWORD" and t.value in ("true", "false"):
            self.next()
            return Literal(t.value == "true")
        if t.kind == "KEYWORD" and t.value == "null":
            self.next()
            return Literal(None)
        if t.kind == "OP" and t.value == "(":
            self.next()
            e = self._expr()
            self.expect_op(")")
            return e
        # MATCH/QUERY/EXISTS are keywords but also functions
        if t.kind in ("IDENT", "KEYWORD"):
            name = str(t.value)
            self.next()
            if self.peek().kind == "OP" and self.peek().value == "(":
                self.next()
                distinct = bool(self.accept_kw("distinct"))
                args: List[Expr] = []
                if not (self.peek().kind == "OP"
                        and self.peek().value == ")"):
                    if self.peek().kind == "OP" and self.peek().value == "*":
                        self.next()
                        args.append(FieldRef("*"))
                    else:
                        args.append(self._expr())
                    while self.accept_op(","):
                        args.append(self._expr())
                self.expect_op(")")
                return Call(name.upper(), args, distinct)
            # plain field reference (possibly dotted)
            full = name
            while self.accept_op("."):
                nxt = self.next()
                if nxt.kind not in ("IDENT", "KEYWORD", "NUMBER"):
                    raise ParsingException("Bad dotted identifier")
                full += "." + str(nxt.value)
            return FieldRef(full)
        raise ParsingException(f"Unexpected token [{t.value}]")


# ---------------------------------------------------------------------------
# type mapping
# ---------------------------------------------------------------------------

_SQL_TYPES = {
    "text": "text", "keyword": "keyword", "long": "long",
    "integer": "integer", "short": "short", "byte": "byte",
    "double": "double", "float": "float", "half_float": "half_float",
    "boolean": "boolean", "date": "datetime", "ip": "ip",
    "dense_vector": "dense_vector",
}


def _sql_type(es_type: str) -> str:
    return _SQL_TYPES.get(es_type, es_type)


# column display sizes reported to JDBC/ODBC clients
# (ref: x-pack/plugin/sql/.../type/SqlDataTypes.java:549 displaySize)
_DISPLAY_SIZES = {
    "null": 0, "boolean": 1, "byte": 5, "short": 6, "integer": 11,
    "long": 20, "double": 25, "float": 15, "half_float": 25,
    "scaled_float": 25, "keyword": 32766, "constant_keyword": 32766,
    "text": 2147483647, "ip": 45, "datetime": 29, "date": 29, "time": 18,
    "binary": 2147483647, "object": 0, "nested": 0, "geo_point": 58,
}


def display_size(es_type: str) -> int:
    return _DISPLAY_SIZES.get(es_type, 0)


# java.sql.Types ids the JDBC/ODBC drivers switch on (ref: sql-proto
# DataType -> sqlType mapping)
_ODBC_TYPE_IDS = {
    "null": 0, "boolean": 16, "byte": -6, "short": 5, "integer": 4,
    "long": -5, "double": 8, "float": 7, "half_float": 8,
    "scaled_float": 8, "keyword": 12, "constant_keyword": 12,
    "text": 2005, "ip": 12, "datetime": 93, "date": 91, "time": 92,
    "binary": -3, "object": 2002, "nested": 2002, "geo_point": 1111,
}


def render_literal(value: Any) -> str:
    """Render a typed parameter value as a SQL literal
    (ref: sql-proto SqlTypedParamValue — the JDBC driver sends
    ``{"type": ..., "value": ...}`` pairs for each ``?``; the declared
    type travels in the value's json representation, so rendering
    dispatches on the value itself)."""
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "TRUE" if value else "FALSE"
    if isinstance(value, float) and (value != value or value in
                                     (float("inf"), float("-inf"))):
        raise IllegalArgumentException(
            f"non-finite parameter value [{value}]")
    if isinstance(value, (int, float)):
        return repr(value)
    return "'" + str(value).replace("'", "''") + "'"


def substitute_params(sql: str, params: List[Any]) -> str:
    """Replace ``?`` placeholders with typed-parameter literals, skipping
    string literals, quoted identifiers and comments (the driver-side
    PreparedQuery does the same scan, ref: jdbc/PreparedQuery.java)."""
    out = []
    i, n, p = 0, len(sql), 0
    while i < n:
        c = sql[i]
        if c == "'" or c == '"' or c == "`":
            j = i + 1
            while j < n:
                if sql[j] == c:
                    if c == "'" and sql[j:j + 2] == "''":
                        j += 2
                        continue
                    break
                j += 1
            out.append(sql[i:min(j + 1, n)])
            i = j + 1
        elif sql[i:i + 2] == "--":
            j = sql.find("\n", i)
            j = n if j < 0 else j
            out.append(sql[i:j])
            i = j
        elif sql[i:i + 2] == "/*":
            j = sql.find("*/", i + 2)
            j = n if j < 0 else j + 2
            out.append(sql[i:j])
            i = j
        elif c == "?":
            if p >= len(params):
                raise IllegalArgumentException(
                    "Not enough actual parameters; needed more than "
                    f"{len(params)}")
            prm = params[p]
            p += 1
            out.append(render_literal(prm.get("value")
                                      if isinstance(prm, dict) else prm))
            i += 1
        else:
            out.append(c)
            i += 1
    if p < len(params):
        raise IllegalArgumentException(
            f"Too many actual parameters: {len(params)} given, {p} used")
    return "".join(out)


def _infer_type(v: Any) -> str:
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, int):
        return "long"
    if isinstance(v, float):
        return "double"
    return "keyword"


# ---------------------------------------------------------------------------
# execution
# ---------------------------------------------------------------------------

@dataclass
class _Cursor:
    kind: str                       # rows | composite
    stmt: Optional[SelectStmt] = None
    rows: Optional[List[List[Any]]] = None    # buffered rows (rows kind)
    offset: int = 0
    index: str = ""
    after_key: Optional[Dict[str, Any]] = None
    fetch_size: int = DEFAULT_FETCH_SIZE
    emitted: int = 0
    exhausted: bool = False         # no more composite pages; rows buffered
    expires_at: float = 0.0


class SqlService:
    """Parses, plans and executes SQL against the node's search service
    (ref: x-pack/plugin/sql/.../execution/PlanExecutor.java)."""

    def __init__(self, node):
        self.node = node
        self._cursors: Dict[str, _Cursor] = {}
        self._lock = threading.Lock()

    # -- public API -------------------------------------------------------
    def query(self, body: Dict[str, Any]) -> Dict[str, Any]:
        cursor = body.get("cursor")
        fetch_size = int(body.get("fetch_size", DEFAULT_FETCH_SIZE))
        mode = str(body.get("mode", "plain") or "plain").lower()
        if cursor:
            return self._continue(cursor)
        sql = body.get("query")
        if not sql:
            raise IllegalArgumentException("[query] is required")
        if body.get("params"):
            sql = substitute_params(sql, body["params"])
        stmt = Parser(sql).parse()
        if isinstance(stmt, ShowTables):
            result = self._show_tables(stmt)
        elif isinstance(stmt, ShowColumns):
            result = self._show_columns(stmt)
        elif isinstance(stmt, ShowFunctions):
            result = self._show_functions(stmt)
        elif isinstance(stmt, SysTables):
            result = self._sys_tables(stmt)
        elif isinstance(stmt, SysColumns):
            result = self._sys_columns(stmt)
        elif isinstance(stmt, SysTypes):
            result = self._sys_types()
        else:
            result = self._run_select(stmt, fetch_size)
        if mode in ("jdbc", "odbc"):
            # driver-mode responses carry column display metadata
            # (ref: TransportSqlQueryAction — Mode.isDriver adds
            # displaySize to each ColumnInfo)
            for col in result.get("columns", []):
                col["display_size"] = display_size(col.get("type", ""))
        return result

    def translate(self, body: Dict[str, Any]) -> Dict[str, Any]:
        sql = body.get("query")
        if not sql:
            raise IllegalArgumentException("[query] is required")
        stmt = Parser(sql).parse()
        if not isinstance(stmt, SelectStmt):
            raise IllegalArgumentException(
                "Cannot translate a non-SELECT statement")
        if stmt.group_by or any(has_aggregate(i.expr) for i in stmt.items):
            return self._agg_search_body(stmt, DEFAULT_FETCH_SIZE, None)
        return self._row_search_body(stmt, stmt.limit or DEFAULT_FETCH_SIZE)

    def close_cursor(self, cursor_id: str) -> bool:
        with self._lock:
            return self._cursors.pop(cursor_id, None) is not None

    # -- SYS catalog (ODBC driver surface: SQLTables/SQLColumns/
    # SQLGetTypeInfo; ref: x-pack/plugin/sql/.../plan/logical/command/
    # sys/SysTables.java and siblings) ------------------------------------
    def _sys_tables(self, stmt: "SysTables") -> Dict[str, Any]:
        import fnmatch
        names = sorted(self.node.indices_service.resolve("_all"))
        if stmt.pattern is not None:
            pat = stmt.pattern.replace("%", "*").replace("_", "?")
            names = [n for n in names if fnmatch.fnmatch(n, pat)]
        cluster = self.node.settings.get("cluster.name", "elasticsearch")
        cols = ["TABLE_CAT", "TABLE_SCHEM", "TABLE_NAME", "TABLE_TYPE",
                "REMARKS", "TYPE_CAT", "TYPE_SCHEM", "TYPE_NAME",
                "SELF_REFERENCING_COL_NAME", "REF_GENERATION"]
        return {
            "columns": [{"name": c, "type": "keyword"} for c in cols],
            "rows": [[cluster, None, n, "TABLE", "", None, None, None,
                      None, None] for n in names],
        }

    def _sys_columns(self, stmt: "SysColumns") -> Dict[str, Any]:
        import fnmatch
        names = sorted(self.node.indices_service.resolve("_all"))
        if stmt.table_pattern is not None:
            pat = stmt.table_pattern.replace("%", "*").replace("_", "?")
            names = [n for n in names if fnmatch.fnmatch(n, pat)]
        cluster = self.node.settings.get("cluster.name", "elasticsearch")
        rows = []
        for name in names:
            idx = self.node.indices_service.get(name)
            fields = sorted(idx.mapper.fields.items())
            for pos, (fname, ft) in enumerate(fields, start=1):
                # ORDINAL_POSITION is the TABLE position — computed
                # before any column-pattern filtering (ODBC clients
                # bind by it)
                if stmt.column_pattern is not None:
                    cpat = stmt.column_pattern.replace(
                        "%", "*").replace("_", "?")
                    if not fnmatch.fnmatch(fname, cpat):
                        continue
                est = _sql_type(ft.type_name)
                rows.append([cluster, None, name, fname,
                             _ODBC_TYPE_IDS.get(est, 1111), est,
                             display_size(ft.type_name), None, None, 10,
                             1, "", None, None, None, None, pos, "YES"])
        cols = ["TABLE_CAT", "TABLE_SCHEM", "TABLE_NAME", "COLUMN_NAME",
                "DATA_TYPE", "TYPE_NAME", "COLUMN_SIZE",
                "BUFFER_LENGTH", "DECIMAL_DIGITS", "NUM_PREC_RADIX",
                "NULLABLE", "REMARKS", "COLUMN_DEF", "SQL_DATA_TYPE",
                "SQL_DATETIME_SUB", "CHAR_OCTET_LENGTH",
                "ORDINAL_POSITION", "IS_NULLABLE"]
        return {"columns": [{"name": c,
                             "type": ("integer" if c in (
                                 "DATA_TYPE", "COLUMN_SIZE",
                                 "ORDINAL_POSITION", "NULLABLE",
                                 "NUM_PREC_RADIX") else "keyword")}
                            for c in cols],
                "rows": rows}

    def _sys_types(self) -> Dict[str, Any]:
        cols = ["TYPE_NAME", "DATA_TYPE", "PRECISION", "LITERAL_PREFIX",
                "LITERAL_SUFFIX", "CREATE_PARAMS", "NULLABLE",
                "CASE_SENSITIVE", "SEARCHABLE", "UNSIGNED_ATTRIBUTE",
                "FIXED_PREC_SCALE", "AUTO_INCREMENT", "LOCAL_TYPE_NAME",
                "MINIMUM_SCALE", "MAXIMUM_SCALE", "SQL_DATA_TYPE",
                "SQL_DATETIME_SUB", "NUM_PREC_RADIX",
                "INTERVAL_PRECISION"]
        rows = []
        for tname, tid in sorted(_ODBC_TYPE_IDS.items(),
                                 key=lambda e: e[1]):
            rows.append([tname, tid, display_size(tname), None, None,
                         None, 1, tname in ("keyword", "text"), 3,
                         False, False, False, tname, 0, 0, tid, None,
                         10, None])
        return {"columns": [{"name": c, "type": "keyword"}
                            for c in cols],
                "rows": rows}

    # -- SHOW / DESCRIBE --------------------------------------------------
    def _show_tables(self, stmt: ShowTables) -> Dict[str, Any]:
        import fnmatch
        names = sorted(self.node.indices_service.resolve("_all"))
        if stmt.pattern is not None:
            pat = stmt.pattern.replace("%", "*").replace("_", "?")
            names = [n for n in names if fnmatch.fnmatch(n, pat)]
        return {
            "columns": [{"name": "name", "type": "keyword"},
                        {"name": "type", "type": "keyword"},
                        {"name": "kind", "type": "keyword"}],
            "rows": [[n, "TABLE", "INDEX"] for n in names],
        }

    def _show_columns(self, stmt: ShowColumns) -> Dict[str, Any]:
        names = self.node.indices_service.resolve(stmt.table)
        cols: Dict[str, str] = {}
        for name in names:
            idx = self.node.indices_service.get(name)
            for fname in idx.mapper.field_names():
                if fname.startswith("_"):
                    continue
                ft = idx.mapper.field_type(fname)
                cols.setdefault(fname, _sql_type(ft.type_name))
        return {
            "columns": [{"name": "column", "type": "keyword"},
                        {"name": "type", "type": "keyword"},
                        {"name": "mapping", "type": "keyword"}],
            "rows": [[c, t, t] for c, t in sorted(cols.items())],
        }

    def _show_functions(self, stmt: ShowFunctions) -> Dict[str, Any]:
        import fnmatch
        names = (sorted(ql.AGGREGATE_FUNCTIONS)
                 + sorted(ql._SCALARS.keys())
                 + ["MATCH", "QUERY", "EXISTS"])
        kinds = (["AGGREGATE"] * len(ql.AGGREGATE_FUNCTIONS)
                 + ["SCALAR"] * len(ql._SCALARS)
                 + ["CONDITIONAL"] * 3)
        rows = list(zip(names, kinds))
        if stmt.pattern is not None:
            pat = stmt.pattern.replace("%", "*").replace("_", "?")
            rows = [r for r in rows if fnmatch.fnmatch(r[0], pat)]
        return {
            "columns": [{"name": "name", "type": "keyword"},
                        {"name": "type", "type": "keyword"}],
            "rows": [list(r) for r in rows],
        }

    # -- SELECT planning --------------------------------------------------
    def _run_select(self, stmt: SelectStmt, fetch_size: int):
        if stmt.table is None:
            # constant SELECT (SELECT 1+1)
            row = [evaluate(i.expr, lambda f: None) for i in stmt.items]
            return {
                "columns": [{"name": i.name, "type": _infer_type(v)}
                            for i, v in zip(stmt.items, row)],
                "rows": [row],
            }
        if stmt.group_by or any(has_aggregate(i.expr) for i in stmt.items):
            return self._agg_select(stmt, fetch_size)
        return self._row_select(stmt, fetch_size)

    # .. row plan
    def _row_search_body(self, stmt: SelectStmt, size: int):
        body: Dict[str, Any] = {"size": size}
        if stmt.where is not None:
            body["query"] = to_filter(stmt.where)
        else:
            body["query"] = {"match_all": {}}
        sort = []
        for e, direction in stmt.order_by:
            if isinstance(e, FieldRef):
                sort.append({e.name: {"order": direction}})
            elif (isinstance(e, Call) and e.name == "SCORE"
                  and not e.args):
                sort.append({"_score": {"order": direction}})
            else:
                raise IllegalArgumentException(
                    "ORDER BY supports fields and SCORE() outside of "
                    "GROUP BY")
        if sort:
            body["sort"] = sort
        return body

    def _columns_for(self, stmt: SelectStmt, index: str):
        """Expand * and compute column names/types from the mapping."""
        names = self.node.indices_service.resolve(index)
        field_types: Dict[str, str] = {}
        for name in names:
            idx = self.node.indices_service.get(name)
            for fname in idx.mapper.field_names():
                if fname.startswith("_"):
                    continue
                ft = idx.mapper.field_type(fname)
                field_types.setdefault(fname, _sql_type(ft.type_name))
        items: List[SelectItem] = []
        for it in stmt.items:
            if isinstance(it.expr, FieldRef) and it.expr.name == "*":
                for fname in sorted(field_types):
                    items.append(SelectItem(FieldRef(fname)))
            else:
                items.append(it)
        cols = []
        for it in items:
            if isinstance(it.expr, FieldRef):
                t = field_types.get(it.expr.name, "keyword")
            elif isinstance(it.expr, Call) and it.expr.name == "COUNT":
                t = "long"
            else:
                t = "double" if has_aggregate(it.expr) else "keyword"
            cols.append({"name": it.name, "type": t})
        return items, cols

    def _row_select(self, stmt: SelectStmt, fetch_size: int):
        # DISTINCT dedups AFTER fetching, so the fetch cannot be capped
        # at LIMIT (dedup would then under-fill the page)
        size = (stmt.limit if stmt.limit is not None and not stmt.distinct
                else 10000)
        body = self._row_search_body(stmt, size)
        body["_source"] = True
        r = self.node.search_service.search(stmt.table, body)
        items, cols = self._columns_for(stmt, stmt.table)
        rows: List[List[Any]] = []
        seen = set()
        for hit in r["hits"]["hits"]:
            src = hit.get("_source", {}) or {}

            def getter(fname, _src=src, _hit=hit):
                if fname == "_id":
                    return _hit.get("_id")
                v = _src
                for part in fname.split("."):
                    if isinstance(v, dict):
                        v = v.get(part)
                    else:
                        return None
                return v

            row = [evaluate(it.expr, getter) for it in items]
            if stmt.distinct:
                key = tuple(json.dumps(v, sort_keys=True, default=str)
                            for v in row)
                if key in seen:
                    continue
                seen.add(key)
            rows.append(row)
        if stmt.limit is not None:
            rows = rows[: stmt.limit]
        return self._paged_rows(cols, rows, stmt, fetch_size)

    # .. agg plan
    def _group_sources(self, stmt: SelectStmt):
        """GROUP BY expressions → composite sources."""
        sources = []
        key_exprs: Dict[str, Expr] = {}
        for ge in stmt.group_by:
            if isinstance(ge, FieldRef):
                nm = ge.name
                sources.append({nm: {"terms": {"field": nm,
                                               "missing_bucket": True}}})
                key_exprs[nm] = ge
            elif isinstance(ge, Call) and ge.name in (
                    "YEAR", "MONTH", "DAY", "HOUR", "MINUTE", "SECOND",
                    "HISTOGRAM"):
                if ge.name == "HISTOGRAM":
                    fld = ge.args[0]
                    interval = ql._literal_value(ge.args[1])
                    nm = expr_key(ge)
                    sources.append({nm: {"histogram": {
                        "field": fld.name, "interval": interval,
                        "missing_bucket": True}}})
                    key_exprs[nm] = ge
                else:
                    # date-part grouping: group on the raw field via a
                    # calendar interval where it matches
                    cal = {"YEAR": "year", "MONTH": "month", "DAY": "day",
                           "HOUR": "hour", "MINUTE": "minute",
                           "SECOND": "second"}[ge.name]
                    fld = ge.args[0]
                    nm = expr_key(ge)
                    sources.append({nm: {"date_histogram": {
                        "field": fld.name, "calendar_interval": cal,
                        "missing_bucket": True}}})
                    key_exprs[nm] = ge
            else:
                raise IllegalArgumentException(
                    f"Unsupported GROUP BY expression [{expr_key(ge)}]")
        return sources, key_exprs

    def _agg_exprs(self, stmt: SelectStmt) -> List[Call]:
        """All aggregate calls appearing in SELECT/HAVING/ORDER BY."""
        out: Dict[str, Call] = {}

        def walk(e: Expr):
            if isinstance(e, Call):
                if e.name in ql.AGGREGATE_FUNCTIONS:
                    out.setdefault(expr_key(e), e)
                    return
                for a in e.args:
                    walk(a)
            elif isinstance(e, Binary):
                walk(e.left)
                walk(e.right)
            elif isinstance(e, Unary):
                walk(e.operand)
            elif isinstance(e, (InList, Between, Like, IsNull)):
                walk(e.expr)

        for it in stmt.items:
            walk(it.expr)
        if stmt.having is not None:
            walk(stmt.having)
        for e, _ in stmt.order_by:
            walk(e)
        return list(out.values())

    def _metric_agg_body(self, call: Call) -> Optional[Dict[str, Any]]:
        if call.name == "COUNT":
            arg = call.args[0] if call.args else FieldRef("*")
            if isinstance(arg, FieldRef) and arg.name == "*":
                return None                     # doc_count
            if call.distinct:
                return {"cardinality": {"field": arg.name}}
            return {"value_count": {"field": arg.name}}
        fld = call.args[0]
        if not isinstance(fld, FieldRef):
            raise IllegalArgumentException(
                f"{call.name} requires a field argument")
        m = {"SUM": "sum", "AVG": "avg", "MIN": "min", "MAX": "max",
             "CARDINALITY": "cardinality"}
        if call.name in m:
            return {m[call.name]: {"field": fld.name}}
        if call.name in ("STDDEV", "VARIANCE"):
            return {"extended_stats": {"field": fld.name}}
        if call.name == "PERCENTILE":
            pct = ql._literal_value(call.args[1])
            return {"percentiles": {"field": fld.name, "percents": [pct]}}
        raise IllegalArgumentException(
            f"Unknown aggregate function [{call.name}]")

    def _agg_search_body(self, stmt: SelectStmt, fetch_size: int,
                         after: Optional[Dict[str, Any]]):
        body: Dict[str, Any] = {"size": 0}
        if stmt.where is not None:
            body["query"] = to_filter(stmt.where)
        metric_aggs: Dict[str, Any] = {}
        for call in self._agg_exprs(stmt):
            ab = self._metric_agg_body(call)
            if ab is not None:
                metric_aggs[expr_key(call)] = ab
        if stmt.group_by:
            sources, _ = self._group_sources(stmt)
            comp: Dict[str, Any] = {"size": fetch_size, "sources": sources}
            if after is not None:
                comp["after"] = after
            node: Dict[str, Any] = {"composite": comp}
            if metric_aggs:
                node["aggs"] = metric_aggs
            body["aggs"] = {"groupby": node}
        else:
            body["aggs"] = metric_aggs
        return body

    @staticmethod
    def _metric_value(container: Dict[str, Any], call: Call,
                      doc_count: int):
        key = expr_key(call)
        if call.name == "COUNT" and (
                not call.args or (isinstance(call.args[0], FieldRef)
                                  and call.args[0].name == "*")):
            return doc_count
        v = container.get(key, {})
        if call.name == "STDDEV":
            return v.get("std_deviation")
        if call.name == "VARIANCE":
            return v.get("variance")
        if call.name == "PERCENTILE":
            vals = v.get("values", {})
            return next(iter(vals.values()), None)
        return v.get("value")

    def _bucket_rows(self, stmt: SelectStmt, buckets, items, agg_calls,
                     key_exprs) -> List[List[Any]]:
        """Composite buckets → projected rows with HAVING applied."""
        rows: List[List[Any]] = []
        for b in buckets:
            values: Dict[str, Any] = {}
            for nm, ge in key_exprs.items():
                v = b["key"].get(nm)
                # date-part group keys come back as bucket-start epoch
                # ms; convert to the named part (YEAR(ts) → 2021)
                if (v is not None and isinstance(ge, Call)
                        and ge.name in ("YEAR", "MONTH", "DAY", "HOUR",
                                        "MINUTE", "SECOND")):
                    v = ql._SCALARS[ge.name](v)
                values[nm] = v
            for call in agg_calls:
                values[expr_key(call)] = self._metric_value(
                    b, call, b["doc_count"])

            def getter(name, _v=values):
                return _v.get(name)

            def col_value(expr, _g=getter, _v=values):
                # group keys referenced in SELECT resolve by their
                # expression key (bare field or YEAR(ts) alike)
                k = expr_key(expr)
                if k in _v:
                    return _v[k]
                return evaluate(expr, _g)

            if stmt.having is not None and not evaluate(
                    stmt.having, getter):
                continue
            rows.append([col_value(it.expr) for it in items])
        return rows

    def _sort_grouped_rows(self, stmt: SelectStmt, rows, items):
        col_index = {it.name: j for j, it in enumerate(items)}
        for e, direction in reversed(stmt.order_by):
            key = expr_key(e)
            j = col_index.get(key)
            if j is None:
                # aliases: ORDER BY may reference a select alias
                for jj, it in enumerate(items):
                    if expr_key(it.expr) == key or it.name == key:
                        j = jj
                        break
            if j is None:
                raise IllegalArgumentException(
                    f"ORDER BY [{key}] must appear in SELECT for "
                    "grouped queries")
            nulls = [r for r in rows if r[j] is None]
            nonnull = [r for r in rows if r[j] is not None]
            nonnull.sort(key=lambda r, _j=j: r[_j],
                         reverse=(direction == "desc"))
            rows[:] = nonnull + nulls

    def _agg_select(self, stmt: SelectStmt, fetch_size: int,
                    after: Optional[Dict[str, Any]] = None,
                    emitted: int = 0,
                    prefix: Optional[List[List[Any]]] = None,
                    more: bool = True):
        agg_calls = self._agg_exprs(stmt)
        items, cols = self._columns_for(stmt, stmt.table)

        if not stmt.group_by:
            body = self._agg_search_body(stmt, fetch_size, None)
            r = self.node.search_service.search(stmt.table, body)
            aggs = r.get("aggregations", {})
            values = {expr_key(c): self._metric_value(
                aggs, c, r["hits"]["total"]["value"]) for c in agg_calls}

            def getter(name, _v=values):
                return _v.get(name)

            return {"columns": cols,
                    "rows": [[evaluate(it.expr, getter) for it in items]]}

        _, key_exprs = self._group_sources(stmt)

        def fetch_page(after_k, page_size):
            body = self._agg_search_body(stmt, page_size, after_k)
            r = self.node.search_service.search(stmt.table, body)
            g = r.get("aggregations", {}).get("groupby", {})
            buckets = g.get("buckets", [])
            nxt = g.get("after_key") if len(buckets) >= page_size else None
            return buckets, nxt

        if stmt.order_by:
            # ordering needs EVERY group before sorting — drain all
            # composite pages, sort coordinator-side, page with a rows
            # cursor (ref: SQL's local sorting for ordered GROUP BY)
            rows: List[List[Any]] = []
            after_k = None
            while True:
                buckets, after_k = fetch_page(
                    after_k, max(fetch_size, DEFAULT_FETCH_SIZE))
                rows.extend(self._bucket_rows(stmt, buckets, items,
                                              agg_calls, key_exprs))
                if after_k is None:
                    break
            self._sort_grouped_rows(stmt, rows, items)
            if stmt.limit is not None:
                rows = rows[: stmt.limit]
            return self._paged_rows(cols, rows, stmt, fetch_size)

        # unordered: stream pages, applying HAVING per page, until the
        # requested page is filled or groups are exhausted
        needed = fetch_size
        if stmt.limit is not None:
            needed = min(needed, max(0, stmt.limit - emitted))
        rows = list(prefix or [])
        after_k = after
        exhausted = not more
        while len(rows) < needed and not exhausted:
            buckets, nxt = fetch_page(after_k, fetch_size)
            rows.extend(self._bucket_rows(stmt, buckets, items,
                                          agg_calls, key_exprs))
            after_k = nxt
            if after_k is None:
                exhausted = True
        extra_rows = rows[needed:]
        rows = rows[:needed]
        out: Dict[str, Any] = {"columns": cols, "rows": rows}
        hit_limit = (stmt.limit is not None
                     and emitted + len(rows) >= stmt.limit)
        if not hit_limit and (extra_rows or not exhausted):
            cur = _Cursor(kind="composite", stmt=stmt, index=stmt.table,
                          rows=extra_rows or None,
                          after_key=None if exhausted else after_k,
                          fetch_size=fetch_size,
                          emitted=emitted + len(rows))
            cur.exhausted = exhausted
            out["cursor"] = self._save(cur)
        return out

    # -- paging -----------------------------------------------------------
    def _paged_rows(self, cols, rows, stmt, fetch_size):
        if len(rows) <= fetch_size:
            return {"columns": cols, "rows": rows}
        cur = _Cursor(kind="rows", rows=rows, offset=fetch_size,
                      fetch_size=fetch_size)
        return {"columns": cols, "rows": rows[:fetch_size],
                "cursor": self._save(cur)}

    CURSOR_KEEP_ALIVE = 300.0       # seconds (abandoned cursors expire)

    def _save(self, cur: _Cursor) -> str:
        import time
        cid = base64.urlsafe_b64encode(
            uuid.uuid4().bytes).decode().rstrip("=")
        cur.expires_at = time.time() + self.CURSOR_KEEP_ALIVE
        with self._lock:
            now = time.time()
            for k in [k for k, c in self._cursors.items()
                      if c.expires_at < now]:
                del self._cursors[k]
            self._cursors[cid] = cur
        return cid

    def _continue(self, cursor_id: str) -> Dict[str, Any]:
        with self._lock:
            cur = self._cursors.pop(cursor_id, None)
        if cur is None:
            raise IllegalArgumentException(
                f"Unknown cursor [{cursor_id}]")
        if cur.kind == "rows":
            rows = cur.rows[cur.offset: cur.offset + cur.fetch_size]
            out: Dict[str, Any] = {"rows": rows}
            if cur.offset + cur.fetch_size < len(cur.rows):
                cur.offset += cur.fetch_size
                out["cursor"] = self._save(cur)
            return out
        # composite continuation: emit buffered overflow rows first, then
        # re-run the agg from the saved after key
        r = self._agg_select(cur.stmt, cur.fetch_size, after=cur.after_key,
                             emitted=cur.emitted, prefix=cur.rows,
                             more=not cur.exhausted)
        r.pop("columns", None)
        return r
