"""SQL line protocol + interactive CLI (the JDBC/CLI seam).

The reference ships JDBC/ODBC drivers and an interactive CLI speaking a
binary protocol against the SQL plugin (ref: x-pack/plugin/sql/jdbc/,
x-pack/plugin/sql/sql-cli/ — SqlQueryRequest over the HTTP binary
content type). This module is that seam for external processes:

- **wire**: length-prefixed JSON frames over TCP —
  ``[u32 len][json]`` both directions. Requests:
  ``{"query": "...", "fetch_size": N}`` or ``{"cursor": "..."}`` or
  ``{"close": "<cursor>"}``; responses mirror the REST SQL payload
  (columns/rows/cursor) or ``{"error": ...}``. Simple enough that any
  driver (a JDBC shim included) can speak it from ~50 lines.
- **server**: a thread-per-connection TCP listener bound on
  ``xpack.sql.port`` next to the HTTP port, delegating to the same
  SqlService (cursors included, so paging works across frames).
- **client/CLI**: `python -m elasticsearch_tpu.xpack.sql_protocol
  --port N [--execute SQL]` — an interactive REPL with aligned table
  output and automatic cursor paging; `--execute` runs one statement
  and exits (scripting mode).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Any, Dict, Optional

_LEN = struct.Struct(">I")
MAX_FRAME = 32 << 20


def _send_frame(sock: socket.socket, payload: Dict[str, Any]) -> None:
    body = json.dumps(payload).encode("utf-8")
    sock.sendall(_LEN.pack(len(body)) + body)


def _recv_frame(sock: socket.socket) -> Optional[Dict[str, Any]]:
    head = b""
    while len(head) < 4:
        chunk = sock.recv(4 - len(head))
        if not chunk:
            return None
        head += chunk
    (ln,) = _LEN.unpack(head)
    if ln > MAX_FRAME:
        raise ValueError(f"frame too large ({ln})")
    body = b""
    while len(body) < ln:
        chunk = sock.recv(min(65536, ln - len(body)))
        if not chunk:
            return None
        body += chunk
    return json.loads(body)


class SqlProtocolServer:
    """TCP front for SqlService — one thread per connection (driver
    connections are few and long-lived, unlike search traffic).

    Security: with x-pack security enabled, every connection must carry
    ``username``/``password`` fields on its first frame (the JDBC
    credential model); the realm chain authenticates and the SAME
    privilege the REST /_sql route demands is enforced — the protocol
    port is never an authz bypass."""

    def __init__(self, sql_service, host: str = "127.0.0.1",
                 port: int = 0, security_service=None):
        self.sql = sql_service
        self.security = security_service
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._closed = False
        self._thread = threading.Thread(target=self._accept,
                                        name="sql-protocol",
                                        daemon=True)
        self._thread.start()

    def close(self):
        self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass

    def _accept(self):
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _authenticate(self, req):
        """User for this connection (None when security is off)."""
        import base64

        from elasticsearch_tpu.xpack.security import required_privilege
        creds = f"{req.pop('username', '')}:{req.pop('password', '')}"
        headers = {"authorization": "Basic "
                   + base64.b64encode(creds.encode()).decode()}
        user = self.security.authenticate(headers)
        kind, priv, index = required_privilege("POST", "/_sql")
        if priv != "none":
            self.security.authorize(user, kind, priv, index)
        return user

    def _serve(self, conn: socket.socket):
        user = None
        try:
            while True:
                req = _recv_frame(conn)
                if req is None:
                    return
                try:
                    if self.security is not None \
                            and self.security.enabled:
                        if user is None or "username" in req:
                            user = self._authenticate(req)
                    else:
                        req.pop("username", None)
                        req.pop("password", None)
                    if "close" in req:
                        ok = self.sql.close_cursor(req["close"])
                        _send_frame(conn, {"succeeded": bool(ok)})
                        continue
                    resp = self.sql.query(req)
                    _send_frame(conn, resp)
                except Exception as e:  # noqa: BLE001 — wire errors back
                    _send_frame(conn, {
                        "error": {"type": type(e).__name__,
                                  "reason": str(e)}})
        except (OSError, ValueError):
            pass
        finally:
            conn.close()


# ------------------------------------------------------------------ client

class SqlClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 timeout: float = 30.0, username: str = None,
                 password: str = None):
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)
        self._creds_pending = (
            {"username": username, "password": password}
            if username is not None else None)

    def close(self):
        self._sock.close()

    def request(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        if self._creds_pending is not None:
            payload = {**self._creds_pending, **payload}
            self._creds_pending = None
        _send_frame(self._sock, payload)
        resp = _recv_frame(self._sock)
        if resp is None:
            raise ConnectionError("server closed the connection")
        if "error" in resp:
            raise RuntimeError(
                f"{resp['error'].get('type')}: "
                f"{resp['error'].get('reason')}")
        return resp

    def query(self, sql: str, fetch_size: int = 1000):
        """Yields (columns, rows) pages, following cursors."""
        resp = self.request({"query": sql, "fetch_size": fetch_size})
        columns = resp.get("columns", [])
        while True:
            yield columns, resp.get("rows", [])
            cursor = resp.get("cursor")
            if not cursor:
                return
            resp = self.request({"cursor": cursor})


def _render_table(columns, rows) -> str:
    names = [c["name"] for c in columns]
    cells = [[("" if v is None else str(v)) for v in row]
             for row in rows]
    widths = [max([len(n)] + [len(r[i]) for r in cells])
              for i, n in enumerate(names)]
    def line(vals):
        return " | ".join(v.ljust(w) for v, w in zip(vals, widths))
    out = [line(names), "-+-".join("-" * w for w in widths)]
    out += [line(r) for r in cells]
    return "\n".join(out)


def main(argv=None) -> int:
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="estpu-sql",
        description="Interactive SQL CLI over the line protocol "
                    "(ref: x-pack sql-cli)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--fetch-size", type=int, default=1000)
    ap.add_argument("--user", "-u")
    ap.add_argument("--password", "-p")
    ap.add_argument("--execute", "-e",
                    help="run one statement and exit")
    args = ap.parse_args(argv)

    client = SqlClient(args.host, args.port, username=args.user,
                       password=args.password)

    def run_one(sql: str) -> int:
        total = 0
        try:
            first = True
            for columns, rows in client.query(sql, args.fetch_size):
                if first and columns:
                    print(_render_table(columns, rows))
                    first = False
                elif rows:
                    print(_render_table(columns, rows).split("\n", 2)[2])
                total += len(rows)
        except RuntimeError as e:
            print(f"ERROR: {e}", file=sys.stderr)
            return 1
        print(f"({total} rows)")
        return 0

    try:
        if args.execute:
            return run_one(args.execute)
        print("estpu-sql — interactive SQL (end statements with ';', "
              "'exit;' quits)")
        buf = ""
        while True:
            try:
                line = input("sql> " if not buf else "   > ")
            except EOFError:
                break
            buf += (" " if buf else "") + line.strip()
            if not buf.endswith(";"):
                continue
            stmt = buf[:-1].strip()
            buf = ""
            if stmt.lower() in ("exit", "quit"):
                break
            if stmt:
                run_one(stmt)
        return 0
    finally:
        client.close()


if __name__ == "__main__":
    raise SystemExit(main())
