"""Watcher: alerting — triggers → input → condition → actions.

Mirrors the reference's x-pack watcher plugin (ref: x-pack/plugin/watcher
— Watch model (trigger/input/condition/actions), ExecutionService running
watches on schedule ticks, watch history written to an index;
SURVEY.md §2.6). Re-design for this engine: watches are registered with
a schedule (interval) trigger driven by one scheduler thread; inputs run
through the TPU search path; conditions are the compare/always/never
family evaluated host-side on the payload; actions append to indices,
log records, or record webhook intents (no egress). Every execution is
recorded in `.watcher-history`.
"""

from __future__ import annotations

import logging
import re
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ResourceNotFoundException,
)


logger = logging.getLogger("elasticsearch_tpu.watcher")


def _interval_seconds(expr: str) -> float:
    m = re.fullmatch(r"(\d+)(ms|s|m|h|d)?", str(expr))
    if not m:
        raise IllegalArgumentException(f"bad interval [{expr}]")
    mult = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0,
            "d": 86400.0, None: 1.0}[m.group(2)]
    return int(m.group(1)) * mult


def _path_get(obj: Any, path: str):
    """ctx.payload.hits.total style dotted access."""
    cur = obj
    for part in path.split("."):
        if isinstance(cur, dict):
            cur = cur.get(part)
        elif isinstance(cur, list) and part.isdigit():
            i = int(part)
            cur = cur[i] if i < len(cur) else None
        else:
            return None
    return cur


class Watch:
    def __init__(self, watch_id: str, body: Dict[str, Any]):
        self.id = watch_id
        self.trigger = body.get("trigger", {})
        self.input = body.get("input", {"none": {}})
        self.condition = body.get("condition", {"always": {}})
        self.actions = body.get("actions", {})
        self.metadata = body.get("metadata", {})
        self.active = True
        self.status: Dict[str, Any] = {
            "state": {"active": True},
            "actions": {},
            "execution_state": None,
        }
        sched = self.trigger.get("schedule", {})
        self.interval_s: Optional[float] = None
        if "interval" in sched:
            self.interval_s = _interval_seconds(sched["interval"])
        self.next_fire = (time.time() + self.interval_s
                          if self.interval_s else None)

    def body_dict(self) -> Dict[str, Any]:
        return {"trigger": self.trigger, "input": self.input,
                "condition": self.condition, "actions": self.actions,
                "metadata": self.metadata}


class WatcherService:
    HISTORY_INDEX = ".watcher-history"

    def __init__(self, node):
        self.node = node
        self.watches: Dict[str, Watch] = {}
        self._lock = threading.Lock()
        self._state = "started"
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.execution_count = 0
        # last rendered webhook requests (bounded) — what WOULD have
        # been sent; tests and operators inspect these
        self.webhook_requests: List[Dict[str, Any]] = []
        # delivered/rendered notifications (bounded): email, slack,
        # pagerduty (ref: watcher/notification/*)
        self.notifications: List[Dict[str, Any]] = []

    # ----------------------------------------------------------- lifecycle
    def start_scheduler(self):
        """Background trigger engine (ref: TickerScheduleTriggerEngine)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(0.1):
                now = time.time()
                due = []
                with self._lock:
                    for w in self.watches.values():
                        if (w.active and w.next_fire is not None
                                and now >= w.next_fire):
                            w.next_fire = now + w.interval_s
                            due.append(w)
                for w in due:
                    try:
                        self.execute_watch(w.id, record=True)
                    except Exception:
                        pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="watcher-ticker")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        self._state = "stopped"

    # --------------------------------------------------------------- CRUD
    def put_watch(self, watch_id: str, body: Dict[str, Any]):
        w = Watch(watch_id, body or {})
        with self._lock:
            created = watch_id not in self.watches
            self.watches[watch_id] = w
        return {"_id": watch_id, "created": created}

    def get_watch(self, watch_id: str) -> Watch:
        w = self.watches.get(watch_id)
        if w is None:
            raise ResourceNotFoundException(
                f"watch [{watch_id}] not found")
        return w

    def delete_watch(self, watch_id: str):
        self.get_watch(watch_id)
        with self._lock:
            del self.watches[watch_id]
        return {"_id": watch_id, "found": True}

    def activate(self, watch_id: str, active: bool):
        w = self.get_watch(watch_id)
        w.active = active
        w.status["state"]["active"] = active
        if active and w.interval_s:
            w.next_fire = time.time() + w.interval_s
        return {"status": w.status}

    # ----------------------------------------------------------- execution
    def execute_watch(self, watch_id: str,
                      trigger_data: Optional[Dict[str, Any]] = None,
                      record: bool = True,
                      alternative_input: Optional[Dict[str, Any]] = None):
        """One watch execution cycle (ref: ExecutionService.execute:
        input → condition → actions, history record)."""
        w = self.get_watch(watch_id)
        execution_id = f"{watch_id}_{uuid.uuid4().hex[:12]}"
        started = time.time()
        payload = (alternative_input if alternative_input is not None
                   else self._run_input(w.input))
        ctx = {"watch_id": watch_id, "payload": payload,
               "metadata": w.metadata,
               "trigger": trigger_data or {},
               "execution_time": started}
        met = self._check_condition(w.condition, ctx)
        action_results = []
        if met:
            for name, spec in w.actions.items():
                action_results.append(
                    self._run_action(name, spec, ctx))
        self.execution_count += 1
        result = {
            "watch_id": watch_id,
            "_id": execution_id,
            "state": ("executed" if met else "execution_not_needed"),
            "condition_met": met,
            "result": {
                "input": {"payload": payload},
                "condition": {"met": met},
                "actions": action_results,
            },
        }
        w.status["execution_state"] = result["state"]
        w.status["last_checked"] = int(started * 1000)
        if met:
            w.status["last_met_condition"] = int(started * 1000)
        if record:
            self._record_history(result)
        return result

    def _run_input(self, input_spec: Dict[str, Any]) -> Dict[str, Any]:
        if "search" in input_spec:
            req = input_spec["search"].get("request", {})
            indices = req.get("indices", ["_all"])
            if isinstance(indices, str):
                indices = [indices]
            body = req.get("body", {})
            return self.node.search_service.search(
                ",".join(indices), body)
        if "simple" in input_spec:
            return dict(input_spec["simple"])
        if "http" in input_spec:
            # zero-egress build: record the intent, return empty payload
            return {"_http_request": input_spec["http"].get("request", {})}
        return {}

    def _check_condition(self, cond: Dict[str, Any],
                         ctx: Dict[str, Any]) -> bool:
        if "always" in cond:
            return True
        if "never" in cond:
            return False
        if "compare" in cond:
            for path, check in cond["compare"].items():
                actual = _path_get(ctx, path)
                for op, expected in check.items():
                    if not self._compare(actual, op, expected):
                        return False
            return True
        if "array_compare" in cond:
            for path, spec in cond["array_compare"].items():
                arr = _path_get(ctx, path) or []
                field = spec.get("path", "")
                for op, body in ((k, v) for k, v in spec.items()
                                 if k != "path"):
                    expected = body.get("value")
                    quantifier = body.get("quantifier", "some")
                    hits = [self._compare(
                        _path_get(e, field) if field else e, op, expected)
                        for e in arr]
                    ok = (all(hits) if quantifier == "all"
                          else any(hits))
                    if not ok:
                        return False
            return True
        if "script" in cond:
            # restricted expression over ctx (the painless-lite family)
            src = cond["script"]
            if isinstance(src, dict):
                src = src.get("source", "true")
            return bool(self._eval_script(src, ctx))
        raise IllegalArgumentException(
            f"Unknown condition type {list(cond)}")

    @staticmethod
    def _compare(actual, op: str, expected) -> bool:
        if op == "eq":
            return actual == expected
        if op == "not_eq":
            return actual != expected
        if actual is None:
            return False
        try:
            if op == "gt":
                return actual > expected
            if op == "gte":
                return actual >= expected
            if op == "lt":
                return actual < expected
            if op == "lte":
                return actual <= expected
        except TypeError:
            return False
        raise IllegalArgumentException(f"Unknown compare op [{op}]")

    @staticmethod
    def _eval_script(src: str, ctx: Dict[str, Any]) -> Any:
        """Watcher script conditions run the FULL Painless engine
        (script/ — statements, loops, per-type method allowlists; ref:
        Watcher's ScriptCondition compiles a Painless script against the
        WatcherConditionContext). Scripts the Painless parser rejects
        fall back to the shared QL expression core, never the host
        interpreter (the sandbox discipline)."""
        from elasticsearch_tpu.script import contexts as _plctx

        if _plctx.try_compile(src):
            try:
                # the FULL ctx tree (payload, trigger, execution_time,
                # watch_id, metadata, ...) — a Map inside the engine
                return bool(_plctx.run_watcher_script(src, ctx))
            except Exception:
                logger.debug("watcher script condition error",
                             exc_info=True)
                return False
        from elasticsearch_tpu.xpack import sql as _sql

        try:
            parser = _sql.Parser(src)
            expr = parser._expr()
            from elasticsearch_tpu.xpack.ql import evaluate
            return bool(evaluate(
                expr, lambda path: _path_get({"ctx": ctx}, path)))
        except Exception:
            return False

    def _run_action(self, name: str, spec: Dict[str, Any],
                    ctx: Dict[str, Any]) -> Dict[str, Any]:
        (atype, body), = ((k, v) for k, v in spec.items()
                          if k not in ("condition", "transform",
                                       "throttle_period"))
        if atype == "logging":
            text = self._render(body.get("text", ""), ctx)
            return {"id": name, "type": "logging",
                    "status": "success",
                    "logging": {"logged_text": text}}
        if atype == "index":
            index = body.get("index")
            doc = {"watch_id": ctx["watch_id"],
                   "payload": ctx["payload"],
                   "timestamp": int(time.time() * 1000)}
            if index not in self.node.indices_service.indices:
                self.node.indices_service.create_index(index, {}, None)
            idx = self.node.indices_service.get(index)
            idx.index_doc(uuid.uuid4().hex, doc)
            idx.refresh()
            return {"id": name, "type": "index", "status": "success",
                    "index": {"response": {"index": index}}}
        if atype == "webhook":
            # FULLY render the request the way the reference's
            # HttpClient would send it (ref: actions/webhook/
            # ExecutableWebhookAction + HttpRequestTemplate.render —
            # scheme/host/port/path/params/headers/body all template
            # over ctx), then record instead of sending (zero-egress,
            # disclosed). Rendering is the testable contract: auth
            # headers, mustache substitutions, the URL.
            import json as _json
            rendered = {
                "method": str(body.get("method", "post")).upper(),
                "scheme": body.get("scheme", "http"),
                "host": self._render(str(body.get("host", "")), ctx),
                "port": int(body.get("port", 80)),
                "path": self._render(str(body.get("path", "/")), ctx),
                "params": {k: self._render(str(v), ctx)
                           for k, v in (body.get("params") or {}).items()},
                "headers": {k: self._render(str(v), ctx)
                            for k, v in
                            (body.get("headers") or {}).items()},
                "body": self._render(
                    body.get("body") if isinstance(body.get("body"), str)
                    else _json.dumps(body.get("body"))
                    if body.get("body") is not None else "", ctx),
            }
            auth = (body.get("auth") or {}).get("basic")
            if auth:
                import base64 as _b64
                creds = f"{auth.get('username', '')}:"                         f"{auth.get('password', '')}"
                rendered["headers"]["Authorization"] = (
                    "Basic "
                    + _b64.b64encode(creds.encode()).decode())
            url = (f"{rendered['scheme']}://{rendered['host']}:"
                   f"{rendered['port']}{rendered['path']}")
            rendered["url"] = url
            self.webhook_requests.append(
                {"watch_id": ctx["watch_id"], "action": name,
                 "request": rendered})
            del self.webhook_requests[:-256]
            return {"id": name, "type": "webhook", "status": "simulated",
                    "webhook": {"request": rendered}}
        if atype == "email":
            return self._run_email_action(name, body, ctx)
        if atype == "slack":
            return self._run_slack_action(name, body, ctx)
        if atype == "pagerduty":
            return self._run_pagerduty_action(name, body, ctx)
        return {"id": name, "type": atype, "status": "simulated"}

    # ------------------------------------------------- notification actions
    #
    # Ref: x-pack/plugin/watcher/.../actions/email/EmailAction.java:30,
    # slack/SlackAction.java, pagerduty/PagerDutyAction.java. Account
    # config follows the reference's settings layout
    # (xpack.notification.{email,slack,pagerduty}.account.<name>.*).
    # Delivery policy in this zero-egress engine: email sends REAL SMTP
    # to the configured account host (tests run an in-process SMTP
    # fixture); slack/pagerduty POST over real HTTP when the target is
    # loopback (test fixtures), and otherwise record the FULLY RENDERED
    # request — the testable contract — as the webhook action does.

    def _account(self, kind: str, name: Optional[str]) -> Dict[str, Any]:
        accounts = self.node.settings.by_prefix(
            f"xpack.notification.{kind}.account").as_nested_dict()
        if not isinstance(accounts, dict):
            return {}
        if name:
            acct = accounts.get(name)
            return acct if isinstance(acct, dict) else {}
        default = self.node.settings.get(
            f"xpack.notification.{kind}.default_account")
        if default and isinstance(accounts.get(default), dict):
            return accounts[default]
        for v in accounts.values():     # single-account convenience
            if isinstance(v, dict):
                return v
        return {}

    def _run_email_action(self, name, body, ctx):
        import email.utils
        from email.mime.application import MIMEApplication
        from email.mime.multipart import MIMEMultipart
        from email.mime.text import MIMEText

        acct = self._account("email", body.get("account"))
        sender = self._render(
            str(body.get("from")
                or acct.get("email_defaults", {}).get("from")
                or "watcher@localhost"), ctx)
        to = body.get("to") or []
        if isinstance(to, str):
            to = [to]
        to = [self._render(str(t), ctx) for t in to]
        subject = self._render(str(body.get("subject", "")), ctx)
        tbody = body.get("body", "")
        if isinstance(tbody, dict):
            html = tbody.get("html")
            text = tbody.get("text", "")
            content = self._render(str(html or text), ctx)
            subtype = "html" if html else "plain"
        else:
            content, subtype = self._render(str(tbody), ctx), "plain"
        attachments = body.get("attachments") or {}
        if attachments:
            msg = MIMEMultipart()
            msg.attach(MIMEText(content, subtype))
            import json as _json
            for aname, spec in attachments.items():
                # data attachment: the payload serialized (ref:
                # notification/email/attachment/DataAttachment.java)
                part = MIMEApplication(
                    _json.dumps(ctx.get("payload", {}),
                                default=str).encode(),
                    Name=aname)
                part["Content-Disposition"] = \
                    f'attachment; filename="{aname}"'
                msg.attach(part)
        else:
            msg = MIMEText(content, subtype)
        msg["From"] = sender
        msg["To"] = ", ".join(to)
        msg["Subject"] = subject
        msg["Date"] = email.utils.formatdate()
        msg["Message-ID"] = email.utils.make_msgid(domain="watcher")
        record = {"watch_id": ctx["watch_id"], "action": name,
                  "type": "email", "from": sender, "to": to,
                  "subject": subject, "body": content}
        smtp = acct.get("smtp") or {}
        host = smtp.get("host")
        # same loopback-only egress gate as slack/pagerduty/webhook:
        # this zero-egress engine delivers for real only to in-process
        # fixtures; any other host records the rendered message
        if host and not self._is_loopback(str(host)):
            record["status"] = "simulated"
            record["smtp_host"] = str(host)
            self._note(record)
            return {"id": name, "type": "email", "status": "simulated",
                    "email": {"message": {"from": sender, "to": to,
                                          "subject": subject}}}
        if host:
            import smtplib
            try:
                with smtplib.SMTP(host, int(smtp.get("port", 25)),
                                  timeout=10) as s:
                    user = smtp.get("user")
                    if user:
                        s.login(user, str(smtp.get("password", "")))
                    s.sendmail(sender, to, msg.as_string())
                status = "success"
            except Exception as e:
                record["error"] = repr(e)
                status = "failure"
        else:
            status = "simulated"    # no account configured: rendered
        record["status"] = status
        self._note(record)
        return {"id": name, "type": "email", "status": status,
                "email": {"message": {"from": sender, "to": to,
                                      "subject": subject}}}

    @staticmethod
    def _is_loopback(host: str) -> bool:
        import ipaddress
        if host == "localhost":
            return True
        try:
            return ipaddress.ip_address(host).is_loopback
        except ValueError:
            return False

    def _post_loopback(self, url: str, payload: Dict[str, Any]):
        """POST to loopback fixtures for real; record anything else
        (zero-egress). Returns (status, http_status_or_None)."""
        import urllib.request
        from urllib.parse import urlparse

        if not self._is_loopback(urlparse(url).hostname or ""):
            return "simulated", None
        import json as _json
        req = urllib.request.Request(
            url, data=_json.dumps(payload).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                return "success", resp.status
        except Exception:
            return "failure", None

    def _run_slack_action(self, name, body, ctx):
        acct = self._account("slack", body.get("account"))
        m = body.get("message") or {}
        payload = {
            "username": self._render(str(m.get("from", "watcher")), ctx),
            "channel": [self._render(str(c), ctx)
                        for c in (m.get("to") or [])],
            "text": self._render(str(m.get("text", "")), ctx),
            "attachments": m.get("attachments") or [],
        }
        url = str(acct.get("secure_url") or acct.get("url") or "")
        status, http = ("simulated", None)
        if url:
            status, http = self._post_loopback(url, payload)
        self._note({"watch_id": ctx["watch_id"], "action": name,
                    "type": "slack", "payload": payload, "url": url,
                    "status": status, "http_status": http})
        return {"id": name, "type": "slack", "status": status,
                "slack": {"message": payload}}

    def _run_pagerduty_action(self, name, body, ctx):
        acct = self._account("pagerduty", body.get("account"))
        payload = {
            "routing_key": str(acct.get("service_api_key", "")),
            "event_action": str(body.get("event_type", "trigger")),
            "dedup_key": self._render(
                str(body.get("incident_key", "")), ctx) or None,
            "payload": {
                "summary": self._render(
                    str(body.get("description", "")), ctx),
                "source": "watcher/" + str(ctx["watch_id"]),
                "severity": "error",
                "custom_details": {"client": body.get("client",
                                                      "watcher")},
            },
        }
        url = str(acct.get("url") or "")
        status, http = ("simulated", None)
        if url:
            status, http = self._post_loopback(url, payload)
        self._note({"watch_id": ctx["watch_id"], "action": name,
                    "type": "pagerduty", "payload": payload, "url": url,
                    "status": status, "http_status": http})
        return {"id": name, "type": "pagerduty", "status": status,
                "pagerduty": {"event": payload}}

    def _note(self, record: Dict[str, Any]):
        self.notifications.append(record)
        del self.notifications[:-256]

    @staticmethod
    def _render(template: str, ctx: Dict[str, Any]) -> str:
        def sub(m):
            v = _path_get({"ctx": ctx}, m.group(1).strip())
            return "" if v is None else str(v)
        return re.sub(r"\{\{(.+?)\}\}", sub, template)

    def _record_history(self, result: Dict[str, Any]):
        if self.HISTORY_INDEX not in self.node.indices_service.indices:
            self.node.indices_service.create_index(
                self.HISTORY_INDEX, {}, None)
        idx = self.node.indices_service.get(self.HISTORY_INDEX)
        idx.index_doc(result["_id"], {
            "watch_id": result["watch_id"],
            "state": result["state"],
            "result_condition_met": result["condition_met"],
            "timestamp": int(time.time() * 1000)})
        idx.refresh()

    def stats(self) -> Dict[str, Any]:
        return {"watcher_state": self._state,
                "watch_count": len(self.watches),
                "execution_count": self.execution_count}
