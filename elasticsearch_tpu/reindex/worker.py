"""Scroll+bulk worker behind _reindex / _update_by_query / _delete_by_query.

ref: modules/reindex/.../AbstractAsyncBulkByScrollAction.java — scroll a
snapshot of the source, transform each hit (script / dest rewrite), bulk
into the destination, loop until exhausted; count created/updated/deleted/
noops/version_conflicts; throttle by requests_per_second; `conflicts:
proceed` turns version conflicts into counters instead of failures.
Slicing (ref: ReindexSliceAction / search/slice/SliceBuilder.java)
partitions the id space by murmur3 hash.
"""

from __future__ import annotations

import ast
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ScriptException,
    VersionConflictEngineException,
)

_SCROLL_KEEPALIVE = "5m"
_DEFAULT_BATCH = 1000


# ---------------------------------------------------------------- update script

_ALLOWED_STMT = (ast.Module, ast.Assign, ast.AugAssign, ast.Expr, ast.If,
                 ast.Pass)
_ALLOWED_EXPR = (
    ast.BinOp, ast.UnaryOp, ast.BoolOp, ast.Compare, ast.Call, ast.IfExp,
    ast.Attribute, ast.Subscript, ast.Name, ast.Constant, ast.List,
    ast.Dict, ast.Tuple, ast.Load, ast.Store, ast.Add, ast.Sub, ast.Mult,
    ast.Div, ast.Mod, ast.Pow, ast.FloorDiv, ast.USub, ast.UAdd, ast.Not,
    ast.And, ast.Or, ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE,
    ast.In, ast.NotIn, ast.Is, ast.IsNot,
)


class _SourceProxy:
    """``ctx._source`` — attribute/item access onto the source dict, so the
    painless idioms ``ctx._source.counter += 1`` and
    ``ctx._source['tags'] = [...]`` both work."""

    def __init__(self, source: Dict[str, Any]):
        object.__setattr__(self, "_data", source)

    def __getattr__(self, name):
        try:
            v = self._data[name]
        except KeyError:
            return None
        return _SourceProxy(v) if isinstance(v, dict) else v

    def __setattr__(self, name, value):
        self._data[name] = value

    def __getitem__(self, name):
        return getattr(self, name)

    def __setitem__(self, name, value):
        self._data[name] = value

    def __contains__(self, name):
        return name in self._data

    def remove(self, name):
        self._data.pop(name, None)

    def containsKey(self, name):  # painless Map surface
        return name in self._data

    def get(self, name, default=None):
        return self._data.get(name, default)


class _Ctx:
    """The update-script ``ctx`` variable (ref: UpdateHelper — exposes
    _source, _index, _id, _version, and the mutable ``op``)."""

    def __init__(self, source, index, doc_id, version):
        self._source = _SourceProxy(source)
        self._index = index
        self._id = doc_id
        self._version = version
        self.op = "index"


_SAFE_FUNCS = {
    "abs": abs, "min": min, "max": max, "round": round, "len": len,
    "str": str, "int": int, "float": float, "bool": bool,
}


def _painless_to_python(source: str) -> str:
    """Normalize painless-isms (``;`` statement ends, ``&&``/``||``/``!``)
    to python, WITHOUT touching quoted string literals."""
    out = []
    quote = None
    i = 0
    n = len(source)
    while i < n:
        c = source[i]
        if quote is not None:
            out.append(c)
            if c == "\\" and i + 1 < n:
                out.append(source[i + 1])
                i += 2
                continue
            if c == quote:
                quote = None
            i += 1
            continue
        if c in "'\"":
            quote = c
            out.append(c)
            i += 1
            continue
        two = source[i:i + 2]
        if two == "&&":
            out.append(" and ")
            i += 2
        elif two == "||":
            out.append(" or ")
            i += 2
        elif two == "!=":
            out.append("!=")
            i += 2
        elif c == "!":
            out.append(" not ")
            i += 1
        elif c == ";":
            out.append("\n")  # statement end; indentation of the next
            i += 1            # physical line still governs blocks
            while i < n and source[i] == " ":
                i += 1
        else:
            out.append(c)
            i += 1
    lines = [l for l in "".join(out).split("\n") if l.strip()]
    return "\n".join(lines)


class UpdateScript:
    """Compiled update-context script (the painless analogue for ctx
    mutation; ref: modules/lang-painless update/reindex script contexts)."""

    def __init__(self, source: str, params: Optional[Dict[str, Any]] = None):
        self.source = source
        self.params = params or {}
        # full-language path first (script/ — statements, loops,
        # functions); legacy translation only for what it can't parse
        from elasticsearch_tpu.script import contexts as _plctx
        self._painless = _plctx.try_compile(source)
        if self._painless:
            self._code = None
            return
        py = _painless_to_python(source)
        try:
            tree = ast.parse(py, mode="exec")
        except SyntaxError as e:
            raise ScriptException(f"compile error in script [{source}]: {e}")
        for node in ast.walk(tree):
            if not isinstance(node, _ALLOWED_STMT + _ALLOWED_EXPR):
                raise ScriptException(
                    f"illegal construct [{type(node).__name__}] in script")
            if isinstance(node, ast.Name) and node.id not in (
                    "ctx", "params") and node.id not in _SAFE_FUNCS:
                raise ScriptException(f"unknown variable [{node.id}]")
            if isinstance(node, ast.Attribute) and node.attr.startswith("__"):
                raise ScriptException("dunder access is not allowed")
        self._code = compile(tree, "<update-script>", "exec")

    def run(self, ctx: _Ctx) -> None:
        if self._painless:
            from elasticsearch_tpu.script import contexts as _plctx
            from elasticsearch_tpu.script.interp import PainlessError
            try:
                _plctx.run_update_script(self.source, ctx, self.params)
            except PainlessError as e:
                raise ScriptException(str(e))
            return
        scope = dict(_SAFE_FUNCS)
        scope["ctx"] = ctx
        scope["params"] = _ScriptParams(self.params)
        exec(self._code, {"__builtins__": {}}, scope)


class _ScriptParams(dict):
    def __init__(self, d):
        super().__init__(d)

    def __getattr__(self, name):
        try:
            return self[name]
        except KeyError:
            raise ScriptException(f"missing script parameter [{name}]")


def compile_update_script(spec: Any) -> Optional[UpdateScript]:
    if spec is None:
        return None
    if isinstance(spec, str):
        return UpdateScript(spec)
    return UpdateScript(spec.get("source", ""), spec.get("params"))


# ----------------------------------------------------------------- the worker


@dataclass
class BulkByScrollResponse:
    took_millis: int = 0
    total: int = 0
    created: int = 0
    updated: int = 0
    deleted: int = 0
    noops: int = 0
    batches: int = 0
    version_conflicts: int = 0
    throttled_millis: int = 0
    requests_per_second: float = -1.0
    failures: List[Dict[str, Any]] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "took": self.took_millis, "timed_out": False,
            "total": self.total, "created": self.created,
            "updated": self.updated, "deleted": self.deleted,
            "batches": self.batches, "noops": self.noops,
            "version_conflicts": self.version_conflicts,
            "retries": {"bulk": 0, "search": 0},
            "throttled_millis": self.throttled_millis,
            "requests_per_second": self.requests_per_second,
            "throttled_until_millis": 0,
            "failures": self.failures,
        }


def _remote_scroll_batches(remote: Dict[str, Any], index, search_body,
                           batch_size):
    """Reindex-from-remote source (ref: modules/reindex remote mode —
    RemoteScrollableHitSource scrolling the source cluster over HTTP):
    the typed client drives the remote's scroll API; hits stream back
    batch by batch."""
    from elasticsearch_tpu.client import Elasticsearch

    host = remote.get("host")
    if not host:
        raise IllegalArgumentException("[host] must be specified to reindex from a remote cluster")
    auth = None
    if remote.get("username"):
        auth = (remote["username"], remote.get("password", ""))
    es = Elasticsearch([host], basic_auth=auth,
                       ca_certs=remote.get("ca_certs"),
                       verify_certs=not remote.get(
                           "insecure", False))
    body = dict(search_body)
    body["size"] = batch_size
    r = es.search(index, body, scroll=_SCROLL_KEEPALIVE)
    scroll_id = r.get("_scroll_id")
    try:
        while True:
            hits = r.get("hits", {}).get("hits", [])
            if not hits:
                return
            yield hits
            if scroll_id is None:
                return
            r = es.scroll(scroll_id, _SCROLL_KEEPALIVE)
            scroll_id = r.get("_scroll_id")
    finally:
        if scroll_id:
            es.clear_scroll(scroll_id)


def _scroll_batches(node, index, search_body, batch_size, task=None):
    """Yield lists of hits from a scroll snapshot of `index`.

    Rides the resumable cursor drain: a scroll context lost mid-drain
    (node bounce, reaped keep-alive) re-opens at the last continuation
    point, so a bulk-by-scroll operation retries from where it was
    instead of restarting — and never double-applies a batch."""
    from elasticsearch_tpu.search.service import resumable_scroll_batches
    yield from resumable_scroll_batches(
        node.search_service, index, search_body, batch_size,
        keep_alive=_SCROLL_KEEPALIVE, task=task)


def _slice_filter(slices: int, slice_id: int, hit_id: str) -> bool:
    if slices <= 1:
        return True
    from elasticsearch_tpu.index.service import murmur3_hash
    return abs(murmur3_hash(hit_id)) % slices == slice_id


class _Throttle:
    """requests_per_second pacing (ref: WorkerBulkByScrollTaskState —
    delay between batches = batch_size / rps, minus time already spent).
    ``rps`` is read per batch, so _rethrottle can change it mid-flight."""

    def __init__(self, rps: float):
        self.rps = rps
        self.throttled_ms = 0

    def pause_after(self, n_ops: int, elapsed_s: float):
        if self.rps is None or self.rps <= 0:
            return
        target = n_ops / self.rps
        delay = target - elapsed_s
        if delay > 0:
            # cap any single pause so tests/tasks stay responsive
            delay = min(delay, 1.0)
            time.sleep(delay)
            self.throttled_ms += int(delay * 1000)


def _parse_rps(params: Dict[str, Any]) -> float:
    raw = params.get("requests_per_second", "-1")
    if raw in ("-1", -1, "", None, "unlimited"):
        return -1.0
    return float(raw)


def reindex(node, body: Dict[str, Any], params: Dict[str, Any],
            task=None) -> BulkByScrollResponse:
    """POST /_reindex (ref: modules/reindex/.../TransportReindexAction)."""
    body = body or {}
    source = body.get("source") or {}
    dest = body.get("dest") or {}
    src_index = source.get("index")
    dest_index = dest.get("index")
    if not src_index or not dest_index:
        raise IllegalArgumentException("_reindex requires source.index and dest.index")
    if isinstance(src_index, list):
        src_index = ",".join(src_index)
    conflicts = body.get("conflicts", "abort")
    max_docs = body.get("max_docs") or body.get("size")
    op_type = dest.get("op_type", "index")
    version_type = dest.get("version_type", "internal")
    pipeline = dest.get("pipeline")
    script = compile_update_script(body.get("script"))
    slices = int(params.get("slices", 1) or 1)
    rps = _parse_rps(params)
    throttle = _Throttle(rps)

    search_body: Dict[str, Any] = {}
    if "query" in source:
        search_body["query"] = source["query"]
    if "_source" in source:
        search_body["_source"] = source["_source"]
    if version_type == "external":
        search_body["version"] = True

    resp = BulkByScrollResponse(requests_per_second=rps)
    if task is not None:
        task.reindex_throttle = throttle  # live handle for _rethrottle
    start = time.monotonic()
    batch_size = min(int(source.get("size", _DEFAULT_BATCH) or _DEFAULT_BATCH),
                     max_docs or 10**9)

    dest_idx = _ensure_dest(node, dest_index)
    done = False
    remote = source.get("remote")
    batches = (_remote_scroll_batches(remote, src_index, search_body,
                                      batch_size)
               if remote else
               _scroll_batches(node, src_index, search_body, batch_size,
                               task=task))
    for hits in batches:
        if task is not None:
            task.ensure_not_cancelled()
        t_batch = time.monotonic()
        resp.batches += 1
        n_ops = 0
        for hit in hits:
            if not _slice_filter(slices, int(params.get("slice_id", 0)),
                                 hit["_id"]):
                continue
            if max_docs is not None and resp.total >= max_docs:
                done = True
                break
            resp.total += 1
            n_ops += 1
            doc_id = hit["_id"]
            src = dict(hit.get("_source") or {})
            op = "index"
            if script is not None:
                ctx = _Ctx(src, dest_index, doc_id, hit.get("_version", 1))
                script.run(ctx)
                op = ctx.op
                doc_id = ctx._id
                src = ctx._source._data
            if op == "noop":
                resp.noops += 1
                continue
            if op == "delete":
                r = dest_idx.delete_doc(doc_id)
                if getattr(r, "found", False):
                    resp.deleted += 1
                else:
                    resp.noops += 1
                continue
            if pipeline:
                out = node.ingest_service.process(pipeline, dest_index,
                                                  doc_id, src)
                if out is None:  # dropped
                    resp.noops += 1
                    continue
                src = out.source
            try:
                kwargs: Dict[str, Any] = {}
                if op_type == "create":
                    kwargs["op_type"] = "create"
                if version_type == "external":
                    # only-overwrite-when-newer contract (ref: reindex with
                    # dest.version_type=external): the dest doc's version
                    # must be below the source's
                    cur = dest_idx.get_doc(doc_id)
                    if cur.found and cur.version >= hit.get("_version", 1):
                        raise VersionConflictEngineException(
                            doc_id,
                            f"current version [{cur.version}] is higher or "
                            f"equal to the one provided "
                            f"[{hit.get('_version', 1)}]")
                r = dest_idx.index_doc(doc_id, src, **kwargs)
                if getattr(r, "created", True):
                    resp.created += 1
                else:
                    resp.updated += 1
            except VersionConflictEngineException as e:
                resp.version_conflicts += 1
                if conflicts != "proceed":
                    resp.failures.append({"index": dest_index, "id": doc_id,
                                          "cause": str(e), "status": 409})
                    done = True
                    break
        throttle.pause_after(n_ops, time.monotonic() - t_batch)
        if done:
            break
    if params.get("refresh") in ("true", True, ""):
        dest_idx.refresh()
    resp.throttled_millis = throttle.throttled_ms
    resp.took_millis = int((time.monotonic() - start) * 1000)
    return resp


def _ensure_dest(node, index: str):
    from elasticsearch_tpu.common.errors import IndexNotFoundException
    index = node.metadata_service.write_target(index)
    try:
        return node.indices_service.get(index)
    except IndexNotFoundException:
        return node.metadata_service.create_index_from_template(index)


def update_by_query(node, index: str, body: Dict[str, Any],
                    params: Dict[str, Any], task=None) -> BulkByScrollResponse:
    """POST /{index}/_update_by_query (ref: reindex module
    TransportUpdateByQueryAction — snapshot scroll, script each doc, write
    back with seqno optimistic concurrency)."""
    body = body or {}
    conflicts = body.get("conflicts", "abort")
    max_docs = body.get("max_docs")
    script = compile_update_script(body.get("script"))
    rps = _parse_rps(params)
    throttle = _Throttle(rps)
    resp = BulkByScrollResponse(requests_per_second=rps)
    if task is not None:
        task.reindex_throttle = throttle
    start = time.monotonic()
    search_body: Dict[str, Any] = {}
    if "query" in body:
        search_body["query"] = body["query"]

    idx_cache: Dict[str, Any] = {}

    def idx_for(name):
        if name not in idx_cache:
            idx_cache[name] = node.indices_service.get(name)
        return idx_cache[name]

    done = False
    for hits in _scroll_batches(node, index, search_body, _DEFAULT_BATCH,
                                task=task):
        if task is not None:
            task.ensure_not_cancelled()
        t_batch = time.monotonic()
        resp.batches += 1
        n_ops = 0
        for hit in hits:
            if max_docs is not None and resp.total >= max_docs:
                done = True
                break
            resp.total += 1
            n_ops += 1
            target = hit.get("_index", index)
            idx = idx_for(target)
            doc_id = hit["_id"]
            current = idx.get_doc(doc_id)
            if not current.found:
                resp.version_conflicts += 1
                if conflicts != "proceed":
                    done = True
                    break
                continue
            src = dict(current.source)
            op = "index"
            if script is not None:
                ctx = _Ctx(src, target, doc_id, current.version)
                script.run(ctx)
                op = ctx.op
                src = ctx._source._data
            if op == "noop":
                resp.noops += 1
                continue
            if op == "delete":
                r = idx.delete_doc(doc_id)
                if getattr(r, "found", False):
                    resp.deleted += 1
                continue
            try:
                idx.index_doc(doc_id, src, if_seq_no=current.seq_no,
                              if_primary_term=current.primary_term)
                resp.updated += 1
            except VersionConflictEngineException as e:
                resp.version_conflicts += 1
                if conflicts != "proceed":
                    resp.failures.append({"index": target, "id": doc_id,
                                          "cause": str(e), "status": 409})
                    done = True
                    break
        throttle.pause_after(n_ops, time.monotonic() - t_batch)
        if done:
            break
    for idx in idx_cache.values():
        if params.get("refresh") in ("true", True, ""):
            idx.refresh()
    resp.throttled_millis = throttle.throttled_ms
    resp.took_millis = int((time.monotonic() - start) * 1000)
    return resp


def delete_by_query(node, index: str, body: Dict[str, Any],
                    params: Dict[str, Any], task=None) -> BulkByScrollResponse:
    """POST /{index}/_delete_by_query (ref: reindex module
    TransportDeleteByQueryAction)."""
    body = body or {}
    if "query" not in body:
        raise IllegalArgumentException("_delete_by_query requires a query")
    conflicts = body.get("conflicts", "abort")
    max_docs = body.get("max_docs")
    rps = _parse_rps(params)
    throttle = _Throttle(rps)
    resp = BulkByScrollResponse(requests_per_second=rps)
    if task is not None:
        task.reindex_throttle = throttle
    start = time.monotonic()
    search_body = {"query": body["query"]}
    idx_cache: Dict[str, Any] = {}
    done = False
    for hits in _scroll_batches(node, index, search_body, _DEFAULT_BATCH,
                                task=task):
        if task is not None:
            task.ensure_not_cancelled()
        t_batch = time.monotonic()
        resp.batches += 1
        n_ops = 0
        for hit in hits:
            if max_docs is not None and resp.total >= max_docs:
                done = True
                break
            resp.total += 1
            n_ops += 1
            target = hit.get("_index", index)
            if target not in idx_cache:
                idx_cache[target] = node.indices_service.get(target)
            r = idx_cache[target].delete_doc(hit["_id"])
            if getattr(r, "found", False):
                resp.deleted += 1
            else:
                resp.version_conflicts += 1
                if conflicts != "proceed":
                    done = True
                    break
        throttle.pause_after(n_ops, time.monotonic() - t_batch)
        if done:
            break
    if params.get("refresh") in ("true", True, ""):
        for idx in idx_cache.values():
            idx.refresh()
    resp.throttled_millis = throttle.throttled_ms
    resp.took_millis = int((time.monotonic() - start) * 1000)
    return resp
