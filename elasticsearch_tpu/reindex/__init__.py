"""Reindex module: ``_reindex``, ``_update_by_query``, ``_delete_by_query``.

TPU-native analogue of the reference's reindex module (ref:
modules/reindex — scroll+bulk worker with throttling, ``conflicts=proceed``,
slicing, and task management; ``AbstractAsyncBulkByScrollAction``). The
worker here drives the in-process scroll API in batches, applies an
optional update script, and bulk-writes to the destination with
seqno-based optimistic concurrency for conflict detection.
"""

from elasticsearch_tpu.reindex.worker import (  # noqa: F401
    BulkByScrollResponse,
    delete_by_query,
    reindex,
    update_by_query,
)
