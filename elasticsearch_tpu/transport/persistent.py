"""Persistent tasks: durable task assignments that survive restarts.

ref: server persistent/ — PersistentTasksClusterService stores task rows in
cluster state (PersistentTasksCustomMetadata), the master assigns each to a
node, PersistentTasksNodeService starts an AllocatedPersistentTask via the
registered PersistentTasksExecutor; tasks checkpoint state and are
reassigned after restart. CCR follow tasks, transforms, and ML jobs all
ride this (ref: node/Node.java:581-592).

Here the registry persists to disk under the node data path (the cluster
state analogue) and `reassign()` restarts unfinished tasks through their
executors — called on service construction, so a rebuilt node resumes its
tasks exactly as the reference's node service does when cluster state
arrives.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ResourceNotFoundException,
)


class AllocatedPersistentTask:
    """A running instance handed to executors (ref:
    AllocatedPersistentTask): carries params + mutable state, exposes
    checkpointing and completion."""

    def __init__(self, service: "PersistentTasksService", task_id: str,
                 task_name: str, params: Dict[str, Any],
                 state: Optional[Dict[str, Any]]):
        self.service = service
        self.id = task_id
        self.task_name = task_name
        self.params = params
        self.state = state or {}
        self.cancelled = threading.Event()

    def update_state(self, state: Dict[str, Any]):
        """Checkpoint progress (ref: updatePersistentTaskState — CCR/
        transform store seqno checkpoints here)."""
        self.state = state
        self.service._update_state(self.id, state)

    def complete(self):
        self.service._complete(self.id)

    def fail(self, reason: str):
        self.service._fail(self.id, reason)

    def is_cancelled(self) -> bool:
        return self.cancelled.is_set()


# executor: called to (re)start a task; returns an object with an optional
# `stop()` — threads, schedulers, or nothing for poll-driven tasks
Executor = Callable[[AllocatedPersistentTask], Any]


class PersistentTasksService:
    def __init__(self, data_path: Optional[str] = None):
        self._lock = threading.Lock()
        self._executors: Dict[str, Executor] = {}
        self._rows: Dict[str, Dict[str, Any]] = {}
        self._live: Dict[str, AllocatedPersistentTask] = {}
        self._handles: Dict[str, Any] = {}
        self._path = (os.path.join(data_path, "_persistent_tasks.json")
                      if data_path else None)
        if self._path and os.path.exists(self._path):
            with open(self._path) as fh:
                self._rows = json.load(fh)

    def _persist(self):
        if not self._path:
            return
        tmp = self._path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self._rows, fh)
        os.replace(tmp, self._path)

    # ----------------------------------------------------------- registry
    def register_executor(self, task_name: str, executor: Executor):
        self._executors[task_name] = executor

    def reassign(self):
        """(Re)start every unfinished task with a registered executor —
        the restart-recovery path (ref: PersistentTasksNodeService
        startTask on cluster-state application)."""
        for task_id, row in list(self._rows.items()):
            if row.get("finished") or task_id in self._live:
                continue
            ex = self._executors.get(row["task_name"])
            if ex is None:
                continue
            self._start_allocated(task_id, row, ex)

    # ---------------------------------------------------------- lifecycle
    def start_task(self, task_name: str, params: Dict[str, Any],
                   task_id: Optional[str] = None) -> str:
        if task_name not in self._executors:
            raise IllegalArgumentException(
                f"unknown persistent task [{task_name}]")
        task_id = task_id or uuid.uuid4().hex[:16]
        with self._lock:
            if task_id in self._rows and not self._rows[task_id].get("finished"):
                raise IllegalArgumentException(
                    f"task with id [{task_id}] already exists")
            row = {"task_name": task_name, "params": params, "state": {},
                   "allocation_id": 1, "finished": False, "failure": None,
                   # estpu: allow[ESTPU-DET01] epoch display field (ES persistent-task parity), not used for scheduling
                   "start_time": int(time.time() * 1000)}
            self._rows[task_id] = row
            self._persist()
        self._start_allocated(task_id, row, self._executors[task_name])
        return task_id

    def _start_allocated(self, task_id: str, row: Dict[str, Any],
                         executor: Executor):
        task = AllocatedPersistentTask(self, task_id, row["task_name"],
                                       row.get("params", {}),
                                       row.get("state"))
        self._live[task_id] = task
        handle = executor(task)
        if handle is not None:
            self._handles[task_id] = handle

    def cancel_task(self, task_id: str):
        """Remove the task (ref: TransportRemovePersistentTaskAction)."""
        with self._lock:
            if task_id not in self._rows:
                raise ResourceNotFoundException(
                    f"persistent task [{task_id}] not found")
            live = self._live.pop(task_id, None)
            handle = self._handles.pop(task_id, None)
            del self._rows[task_id]
            self._persist()
        if live is not None:
            live.cancelled.set()
        if handle is not None and hasattr(handle, "stop"):
            handle.stop()

    # ------------------------------------------------------- task callbacks
    def _update_state(self, task_id: str, state: Dict[str, Any]):
        with self._lock:
            if task_id in self._rows:
                self._rows[task_id]["state"] = state
                self._persist()

    def _complete(self, task_id: str):
        with self._lock:
            if task_id in self._rows:
                self._rows[task_id]["finished"] = True
                self._persist()
            self._live.pop(task_id, None)
            self._handles.pop(task_id, None)

    def _fail(self, task_id: str, reason: str):
        with self._lock:
            if task_id in self._rows:
                self._rows[task_id]["finished"] = True
                self._rows[task_id]["failure"] = reason
                self._persist()
            self._live.pop(task_id, None)
            self._handles.pop(task_id, None)

    # -------------------------------------------------------------- lookup
    def get(self, task_id: str) -> Dict[str, Any]:
        if task_id not in self._rows:
            raise ResourceNotFoundException(
                f"persistent task [{task_id}] not found")
        return {"id": task_id, **self._rows[task_id]}

    def live_task(self, task_id: str) -> Optional[AllocatedPersistentTask]:
        return self._live.get(task_id)

    def list(self, task_name: Optional[str] = None) -> List[Dict[str, Any]]:
        return [{"id": tid, **row} for tid, row in self._rows.items()
                if task_name is None or row["task_name"] == task_name]

    def stop_all(self):
        for task_id in list(self._live):
            task = self._live.pop(task_id, None)
            if task is not None:
                task.cancelled.set()
            handle = self._handles.pop(task_id, None)
            if handle is not None and hasattr(handle, "stop"):
                handle.stop()
