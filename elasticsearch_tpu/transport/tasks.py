"""Task management: every action execution is a registered task.

Ref: tasks/TaskManager.java:76,121,143-163 — every transport action
registers a Task; tasks form a parent/child tree across nodes; cancellable
tasks support cooperative cancellation with ban propagation (a cancelled
parent bans its id so late-arriving children are cancelled on arrival);
`_tasks` list/cancel APIs sit on top.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclass(frozen=True)
class TaskId:
    node_id: str
    id: int

    def __str__(self) -> str:
        return f"{self.node_id}:{self.id}"

    @staticmethod
    def parse(s: str) -> "TaskId":
        from elasticsearch_tpu.common.errors import IllegalArgumentException
        node, _, num = s.rpartition(":")
        try:
            return TaskId(node, int(num))
        except ValueError:
            raise IllegalArgumentException(
                f"malformed task id {s}")


EMPTY_TASK_ID = TaskId("", -1)


class Task:
    def __init__(self, task_id: int, type_: str, action: str,
                 description: str = "",
                 parent_task_id: TaskId = EMPTY_TASK_ID):
        self.id = task_id
        self.type = type_
        self.action = action
        self.description = description
        self.parent_task_id = parent_task_id
        self.start_time = time.time()
        self.start_nanos = time.monotonic_ns()

    def running_time_nanos(self) -> int:
        return time.monotonic_ns() - self.start_nanos

    def to_dict(self, node_id: str) -> Dict[str, Any]:
        d = {
            "node": node_id,
            "id": self.id,
            "type": self.type,
            "action": self.action,
            "description": self.description,
            "start_time_in_millis": int(self.start_time * 1000),
            "running_time_in_nanos": self.running_time_nanos(),
            "cancellable": isinstance(self, CancellableTask),
        }
        if self.parent_task_id is not EMPTY_TASK_ID and \
                self.parent_task_id.id != -1:
            d["parent_task_id"] = str(self.parent_task_id)
        if isinstance(self, CancellableTask):
            d["cancelled"] = self.is_cancelled()
        return d


# re-exported for callers; an ElasticsearchTpuException so the REST layer
# maps a cancelled request to a 400 instead of a dropped connection
from elasticsearch_tpu.common.errors import TaskCancelledException  # noqa: E402


class CancellableTask(Task):
    """Cooperative cancellation: long-running work polls
    ``ensure_not_cancelled()`` (ref: CancellableTask.java)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._cancelled = threading.Event()
        self._reason: Optional[str] = None
        self._listeners: List[Callable[[], None]] = []
        self._lock = threading.Lock()

    def cancel(self, reason: str = "by user request") -> None:
        with self._lock:
            if self._cancelled.is_set():
                return
            self._reason = reason
            self._cancelled.set()
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn()
            except Exception:
                pass

    def add_cancellation_listener(self, fn: Callable[[], None]) -> None:
        with self._lock:
            if self._cancelled.is_set():
                run_now = True
            else:
                self._listeners.append(fn)
                run_now = False
        if run_now:
            fn()

    def is_cancelled(self) -> bool:
        return self._cancelled.is_set()

    def cancellation_reason(self) -> Optional[str]:
        return self._reason

    def ensure_not_cancelled(self) -> None:
        if self.is_cancelled():
            raise TaskCancelledException(
                f"task cancelled [{self._reason}]")


class TaskManager:
    """Per-node live-task registry + cancellation bans (ref:
    TaskManager.register / cancelTaskAndDescendants / setBan)."""

    def __init__(self, node_id: str):
        self.node_id = node_id
        self._seq = 0
        self._lock = threading.Lock()
        self._tasks: Dict[int, Task] = {}
        # banned parent ids: children arriving after the ban are cancelled
        # immediately (ref: TaskManager bans + ban propagation RPCs)
        self._bans: Dict[TaskId, str] = {}

    def register(self, type_: str, action: str, description: str = "",
                 parent_task_id: TaskId = EMPTY_TASK_ID,
                 cancellable: bool = False) -> Task:
        with self._lock:
            self._seq += 1
            cls = CancellableTask if cancellable else Task
            task = cls(self._seq, type_, action, description, parent_task_id)
            self._tasks[task.id] = task
            ban_reason = self._bans.get(parent_task_id)
        if ban_reason is not None and isinstance(task, CancellableTask):
            task.cancel(f"parent banned [{ban_reason}]")
        return task

    def unregister(self, task: Task) -> None:
        with self._lock:
            self._tasks.pop(task.id, None)
            # the ban (if any) dies with the task (ref: TaskManager
            # removes bans when the parent unregisters)
            self._bans.pop(TaskId(self.node_id, task.id), None)

    def get_task(self, task_id: int) -> Optional[Task]:
        with self._lock:
            return self._tasks.get(task_id)

    def list_tasks(self, actions: Optional[str] = None) -> List[Task]:
        with self._lock:
            tasks = list(self._tasks.values())
        if actions:
            import fnmatch
            patterns = [p.strip() for p in actions.split(",") if p.strip()]
            tasks = [t for t in tasks
                     if any(fnmatch.fnmatch(t.action, p) for p in patterns)]
        return tasks

    def cancel(self, task: CancellableTask, reason: str,
               ban_children: bool = True) -> None:
        task.cancel(reason)
        if ban_children:
            self.set_ban(TaskId(self.node_id, task.id), reason)
            # cancel already-registered local descendants
            for child in self._children_of(TaskId(self.node_id, task.id)):
                if isinstance(child, CancellableTask):
                    self.cancel(child, reason, ban_children=True)

    def set_ban(self, parent: TaskId, reason: str) -> None:
        with self._lock:
            self._bans[parent] = reason

    def remove_ban(self, parent: TaskId) -> None:
        with self._lock:
            self._bans.pop(parent, None)

    def _children_of(self, parent: TaskId) -> List[Task]:
        with self._lock:
            return [t for t in self._tasks.values()
                    if t.parent_task_id == parent]

    def task_scope(self, type_: str, action: str, description: str = "",
                   parent_task_id: TaskId = EMPTY_TASK_ID,
                   cancellable: bool = False) -> "_TaskScope":
        return _TaskScope(self, type_, action, description, parent_task_id,
                          cancellable)


class _TaskScope:
    def __init__(self, manager: TaskManager, type_: str, action: str,
                 description: str, parent: TaskId, cancellable: bool):
        self._manager = manager
        self._args = (type_, action, description, parent, cancellable)
        self.task: Optional[Task] = None

    def __enter__(self) -> Task:
        t, a, d, p, c = self._args
        self.task = self._manager.register(t, a, d, p, c)
        return self.task

    def __exit__(self, *exc) -> None:
        if self.task is not None:
            self._manager.unregister(self.task)
