"""Task management: every action execution is a registered task.

Ref: tasks/TaskManager.java:76,121,143-163 — every transport action
registers a Task; tasks form a parent/child tree across nodes; cancellable
tasks support cooperative cancellation with ban propagation (a cancelled
parent bans its id so late-arriving children are cancelled on arrival);
`_tasks` list/cancel APIs sit on top.

Cluster integration (the transport half lives in telemetry/context.py +
transport/transport.py): a registered task made ambient via
``telemetry.context.activate_task`` is stamped into the ``__headers``
carrier of every outgoing request (``task.id``/``task.parent``), and the
dispatch side installs the incoming ``task.id`` so handlers register
their work as a CHILD of the remote caller's task. Tasks also record the
ambient ``trace.id`` at registration, so ``GET /_tasks`` and
``GET /_traces`` cross-link.
"""

from __future__ import annotations

import fnmatch
import threading
import time
import weakref
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

# every live TaskManager, for the test-suite leak guard (mirror of
# telemetry/tracing.py's open-span registry): a task registered during a
# test and never unregistered is a leak
_MANAGERS: "weakref.WeakSet[TaskManager]" = weakref.WeakSet()


def open_task_keys() -> set:
    """(node_id, task_id, action) of every currently registered task,
    across all live managers in the process."""
    out = set()
    for mgr in list(_MANAGERS):
        with mgr._lock:
            for t in mgr._tasks.values():
                out.add((mgr.node_id, t.id, t.action))
    return out


@dataclass(frozen=True)
class TaskId:
    node_id: str
    id: int

    def __str__(self) -> str:
        return f"{self.node_id}:{self.id}"

    @staticmethod
    def parse(s: str) -> "TaskId":
        from elasticsearch_tpu.common.errors import IllegalArgumentException
        node, _, num = s.rpartition(":")
        try:
            return TaskId(node, int(num))
        except ValueError:
            raise IllegalArgumentException(
                f"malformed task id {s}")


EMPTY_TASK_ID = TaskId("", -1)


def encode_node_scoped_id(node_id: str, seq: int) -> str:
    """Opaque id that embeds its owning node — any node can route a
    get/delete to the owner without cluster-wide lookup (ref:
    AsyncExecutionId: the async-search id encodes node + task)."""
    import base64
    raw = f"{node_id}:{seq}"
    return base64.urlsafe_b64encode(raw.encode()).decode().rstrip("=")


def decode_node_scoped_id(s: str) -> "TaskId":
    """Inverse of encode_node_scoped_id; malformed ids raise typed
    ResourceNotFoundException (an unroutable id IS a missing resource)."""
    import base64

    from elasticsearch_tpu.common.errors import ResourceNotFoundException
    try:
        pad = "=" * (-len(s) % 4)
        raw = base64.urlsafe_b64decode((s + pad).encode()).decode()
        node_id, _, num = raw.rpartition(":")
        if not node_id:
            raise ValueError(raw)
        return TaskId(node_id, int(num))
    except Exception:
        raise ResourceNotFoundException(s)


class Task:
    def __init__(self, task_id: int, type_: str, action: str,
                 description: str = "",
                 parent_task_id: TaskId = EMPTY_TASK_ID,
                 clock: Optional[Callable[[], float]] = None):
        self.id = task_id
        self.type = type_
        self.action = action
        self.description = description
        self.parent_task_id = parent_task_id
        self.start_time = time.time()  # estpu: allow[ESTPU-DET01] epoch display field (ES _tasks parity); running time uses the injected clock
        # the task's CURRENT profile stage (rewrite/bind/launch/fetch/
        # ...), published by the ambient profile.stage_hook the search
        # paths install — `_tasks?detailed=true` and hot_threads show
        # WHERE a long-running task is, not just how long it has run
        self.profile_stage: Optional[str] = None
        # running time reads the manager's clock (virtual time under the
        # deterministic harness, so replayed runs report identical trees)
        self._clock = clock or time.monotonic
        self._start = self._clock()
        # cross-link with the trace that was ambient at registration,
        # plus the client's X-Opaque-Id (ref: Task.HEADERS_TO_COPY)
        from elasticsearch_tpu.telemetry import context as _telectx
        ctx = _telectx.current()
        self.trace_id: Optional[str] = ctx.trace_id if ctx else None
        self.opaque_id: Optional[str] = _telectx.current_opaque_id()
        self.tenant: Optional[str] = _telectx.current_tenant()
        self.workload_class: Optional[str] = \
            _telectx.current_workload_class()

    def running_time_nanos(self) -> int:
        return int((self._clock() - self._start) * 1e9)

    def to_dict(self, node_id: str) -> Dict[str, Any]:
        d = {
            "node": node_id,
            "id": self.id,
            "type": self.type,
            "action": self.action,
            "description": self.description,
            "start_time_in_millis": int(self.start_time * 1000),
            "running_time_in_nanos": self.running_time_nanos(),
            "cancellable": isinstance(self, CancellableTask),
        }
        if self.trace_id is not None:
            d["trace.id"] = self.trace_id
        if self.opaque_id is not None:
            d["headers"] = {"X-Opaque-Id": self.opaque_id}
        if self.tenant is not None:
            d["tenant"] = self.tenant
        if self.workload_class is not None:
            d["search.class"] = self.workload_class
        if self.profile_stage is not None:
            d["profile_stage"] = self.profile_stage
        if self.parent_task_id is not EMPTY_TASK_ID and \
                self.parent_task_id.id != -1:
            d["parent_task_id"] = str(self.parent_task_id)
        if isinstance(self, CancellableTask):
            d["cancelled"] = self.is_cancelled()
        return d


# re-exported for callers; an ElasticsearchTpuException so the REST layer
# maps a cancelled request to a 400 instead of a dropped connection
from elasticsearch_tpu.common.errors import TaskCancelledException  # noqa: E402


class CancellableTask(Task):
    """Cooperative cancellation: long-running work polls
    ``ensure_not_cancelled()`` (ref: CancellableTask.java)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._cancelled = threading.Event()
        self._reason: Optional[str] = None
        self._listeners: List[Callable[[], None]] = []
        self._lock = threading.Lock()

    def cancel(self, reason: str = "by user request") -> None:
        with self._lock:
            if self._cancelled.is_set():
                return
            self._reason = reason
            self._cancelled.set()
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn()
            except Exception:
                pass

    def add_cancellation_listener(self, fn: Callable[[], None]) -> None:
        with self._lock:
            if self._cancelled.is_set():
                run_now = True
            else:
                self._listeners.append(fn)
                run_now = False
        if run_now:
            fn()

    def is_cancelled(self) -> bool:
        return self._cancelled.is_set()

    def cancellation_reason(self) -> Optional[str]:
        return self._reason

    def ensure_not_cancelled(self) -> None:
        if self.is_cancelled():
            raise TaskCancelledException(
                f"task cancelled [{self._reason}]")


class TaskManager:
    """Per-node live-task registry + cancellation bans (ref:
    TaskManager.register / cancelTaskAndDescendants / setBan).

    ``metrics`` (a telemetry MetricsRegistry, optional) receives
    ``tasks.started``/``tasks.completed``/``tasks.cancelled`` counters
    labeled by action and the live ``tasks.current`` gauge; ``clock``
    (optional) drives running-time so the deterministic harness reports
    replay-identical task trees."""

    def __init__(self, node_id: str, metrics=None,
                 clock: Optional[Callable[[], float]] = None):
        self.node_id = node_id
        self.metrics = metrics
        self.clock = clock
        self._seq = 0
        self._lock = threading.Lock()
        self._tasks: Dict[int, Task] = {}
        # banned parent ids: children arriving after the ban are cancelled
        # immediately (ref: TaskManager bans + ban propagation RPCs)
        self._bans: Dict[TaskId, str] = {}
        # lifetime accounting for stats()/bench
        self.started_total = 0
        self.completed_total = 0
        self.cancelled_total = 0
        self.peak_concurrent = 0
        _MANAGERS.add(self)

    def register(self, type_: str, action: str, description: str = "",
                 parent_task_id: TaskId = EMPTY_TASK_ID,
                 cancellable: bool = False) -> Task:
        with self._lock:
            self._seq += 1
            cls = CancellableTask if cancellable else Task
            task = cls(self._seq, type_, action, description,
                       parent_task_id, clock=self.clock)
            self._tasks[task.id] = task
            self.started_total += 1
            self.peak_concurrent = max(self.peak_concurrent,
                                       len(self._tasks))
            live = len(self._tasks)
            ban_reason = self._bans.get(parent_task_id)
        if self.metrics is not None:
            self.metrics.inc("tasks.started", action=action)
            self.metrics.set_gauge("tasks.current", live)
        if ban_reason is not None and isinstance(task, CancellableTask):
            self._count_cancelled(task)
            task.cancel(f"parent banned [{ban_reason}]")
        return task

    def unregister(self, task: Task) -> None:
        with self._lock:
            removed = self._tasks.pop(task.id, None)
            if removed is not None:
                self.completed_total += 1
            live = len(self._tasks)
            # the ban (if any) dies with the task (ref: TaskManager
            # removes bans when the parent unregisters)
            self._bans.pop(TaskId(self.node_id, task.id), None)
        if removed is not None and self.metrics is not None:
            self.metrics.inc("tasks.completed", action=task.action)
            self.metrics.set_gauge("tasks.current", live)

    def get_task(self, task_id: int) -> Optional[Task]:
        with self._lock:
            return self._tasks.get(task_id)

    def list_tasks(self, actions: Optional[str] = None,
                   parent_task_id: Optional[TaskId] = None) -> List[Task]:
        with self._lock:
            tasks = list(self._tasks.values())
        if actions:
            patterns = [p.strip() for p in actions.split(",") if p.strip()]
            tasks = [t for t in tasks
                     if any(fnmatch.fnmatch(t.action, p) for p in patterns)]
        if parent_task_id is not None:
            tasks = [t for t in tasks
                     if t.parent_task_id == parent_task_id]
        return tasks

    def cancel(self, task: CancellableTask, reason: str,
               ban_children: bool = True) -> None:
        self._count_cancelled(task)
        # the ban goes up BEFORE listeners run: a cancellation listener
        # may complete-and-unregister the task synchronously (the
        # coordinator's search does), and unregistration is what sweeps
        # the ban — setting it afterwards would orphan it forever
        if ban_children:
            self.set_ban(TaskId(self.node_id, task.id), reason)
        task.cancel(reason)
        if ban_children:
            # cancel already-registered local descendants
            for child in self._children_of(TaskId(self.node_id, task.id)):
                if isinstance(child, CancellableTask):
                    self.cancel(child, reason, ban_children=True)

    def _count_cancelled(self, task: CancellableTask) -> None:
        if task.is_cancelled():
            return  # idempotent cancel: count the transition once
        self.cancelled_total += 1
        if self.metrics is not None:
            self.metrics.inc("tasks.cancelled", action=task.action)

    def set_ban(self, parent: TaskId, reason: str,
                cancel_children: bool = False) -> None:
        """Ban a parent id so late-arriving children die on arrival;
        with ``cancel_children`` also cancel its ALREADY-registered
        local children — the remote half of ``cancel()`` (ref: the
        SetBan RPC of TaskManager ban propagation)."""
        with self._lock:
            self._bans[parent] = reason
        if cancel_children:
            for child in self._children_of(parent):
                if isinstance(child, CancellableTask):
                    self.cancel(child, f"parent banned [{reason}]",
                                ban_children=True)

    def remove_ban(self, parent: TaskId) -> None:
        with self._lock:
            self._bans.pop(parent, None)

    def ban_count(self) -> int:
        with self._lock:
            return len(self._bans)

    def _children_of(self, parent: TaskId) -> List[Task]:
        with self._lock:
            return [t for t in self._tasks.values()
                    if t.parent_task_id == parent]

    def task_scope(self, type_: str, action: str, description: str = "",
                   parent_task_id: TaskId = EMPTY_TASK_ID,
                   cancellable: bool = False) -> "_TaskScope":
        return _TaskScope(self, type_, action, description, parent_task_id,
                          cancellable)

    def stats(self) -> Dict[str, Any]:
        """The ``tasks`` stats section (nodes stats + BENCH json)."""
        with self._lock:
            current = len(self._tasks)
        return {"current": current,
                "peak_concurrent": self.peak_concurrent,
                "started": self.started_total,
                "completed": self.completed_total,
                "cancelled": self.cancelled_total,
                "bans": self.ban_count()}


class TaskResultStore:
    """Completed results of async (``wait_for_completion=false``)
    actions, keyed by task-id string — the in-memory analogue of the
    reference's ``.tasks`` result index (ref: tasks/TaskResultsService:
    completed task results are stored so ``GET /_tasks/{id}`` can answer
    after the task unregistered). Bounded FIFO: the oldest result falls
    off past ``capacity``."""

    def __init__(self, capacity: int = 256):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._results: Dict[str, Dict[str, Any]] = {}
        self._order: List[str] = []

    def store(self, task_id: str, response: Any = None,
              error: Any = None) -> None:
        entry: Dict[str, Any] = {"completed": True}
        if error is not None:
            to_x = getattr(error, "to_xcontent", None)
            entry["error"] = (to_x() if to_x is not None
                              else {"type": type(error).__name__,
                                    "reason": str(error)})
        else:
            entry["response"] = response
        with self._lock:
            if task_id not in self._results:
                self._order.append(task_id)
            self._results[task_id] = entry
            while len(self._order) > self.capacity:
                self._results.pop(self._order.pop(0), None)

    def get(self, task_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._results.get(task_id)


class _TaskScope:
    def __init__(self, manager: TaskManager, type_: str, action: str,
                 description: str, parent: TaskId, cancellable: bool):
        self._manager = manager
        self._args = (type_, action, description, parent, cancellable)
        self.task: Optional[Task] = None

    def __enter__(self) -> Task:
        t, a, d, p, c = self._args
        self.task = self._manager.register(t, a, d, p, c)
        return self.task

    def __exit__(self, *exc) -> None:
        if self.task is not None:
            self._manager.unregister(self.task)


# ---------------------------------------------------------------------------
# `_tasks` response shaping — shared by the single-node REST handlers and
# the cluster fan-out (`ClusterNode.list_tasks`), so the two surfaces can
# never drift (ref: rest/action/admin/cluster/RestListTasksAction
# group-by rendering over TransportListTasksAction node responses).
# ---------------------------------------------------------------------------

def filter_task_dicts(tasks: List[Dict[str, Any]],
                      actions: Optional[str] = None,
                      parent_task_id: Optional[str] = None,
                      detailed: bool = True) -> List[Dict[str, Any]]:
    """Apply the `_tasks` request filters to serialized task dicts."""
    out = []
    patterns = [p.strip() for p in (actions or "").split(",") if p.strip()]
    for t in tasks:
        if patterns and not any(fnmatch.fnmatch(t.get("action", ""), p)
                                for p in patterns):
            continue
        if parent_task_id and t.get("parent_task_id") != parent_task_id:
            continue
        if not detailed:
            t = {k: v for k, v in t.items()
                 if k not in ("description", "profile_stage")}
        out.append(t)
    return out


def build_tasks_response(node_infos: Dict[str, Dict[str, Any]],
                         group_by: str = "nodes",
                         node_failures: Optional[List[Dict]] = None
                         ) -> Dict[str, Any]:
    """Render the `_tasks` response from per-node task lists.

    ``node_infos``: node_id -> {"name": str, "tasks": [task dicts]}.
    ``group_by``: nodes (default, the per-node map), none (flat map), or
    parents (top-level tasks with nested ``children``).
    """
    out: Dict[str, Any] = {}
    if node_failures:
        out["node_failures"] = node_failures
    if group_by == "none":
        out["tasks"] = {
            f"{nid}:{t['id']}": t
            for nid, info in node_infos.items()
            for t in info.get("tasks", [])}
        return out
    if group_by == "parents":
        by_id: Dict[str, Dict] = {}
        for nid, info in node_infos.items():
            for t in info.get("tasks", []):
                by_id[f"{nid}:{t['id']}"] = dict(t)
        roots: Dict[str, Dict] = {}
        for tid, t in by_id.items():
            parent = t.get("parent_task_id")
            if parent and parent in by_id:
                by_id[parent].setdefault("children", []).append(t)
            else:
                roots[tid] = t
        for t in by_id.values():
            if "children" in t:
                t["children"].sort(
                    key=lambda c: (c["node"], c["id"]))
        out["tasks"] = roots
        return out
    if group_by != "nodes":
        from elasticsearch_tpu.common.errors import (
            IllegalArgumentException)
        raise IllegalArgumentException(
            f"unknown group_by [{group_by}], expected one of "
            "[nodes, parents, none]")
    out["nodes"] = {
        nid: {"name": info.get("name", nid),
              "tasks": {f"{nid}:{t['id']}": t
                        for t in info.get("tasks", [])}}
        for nid, info in node_infos.items()}
    return out


def render_cat_tasks(node_infos: Dict[str, Dict[str, Any]]) -> str:
    """`_cat/tasks` lines from the same per-node task lists the `_tasks`
    fan-out produces: action, task id, parent, type, start time, node."""
    lines = []
    for nid, info in sorted(node_infos.items()):
        name = info.get("name", nid)
        for t in sorted(info.get("tasks", []), key=lambda t: t["id"]):
            lines.append(
                f"{t['action']} {nid}:{t['id']} "
                f"{t.get('parent_task_id', '-')} {t['type']} "
                f"{t['start_time_in_millis']} {name}")
    return "\n".join(lines)


def node_task_slice(task_manager: "TaskManager", node_id: str,
                    name: Optional[str] = None,
                    actions: Optional[str] = None,
                    parent_task_id: Optional[str] = None,
                    detailed: bool = True,
                    task_id: Optional[str] = None) -> Dict[str, Any]:
    """One node's slice of the `_tasks` fan-out shape
    (``{"name": ..., "tasks": [task dicts]}``) — the single builder
    behind BOTH the cluster fan-out handler and the single-node REST
    surface, so the per-node shaping cannot drift. ``task_id`` narrows
    the slice to one task (the ``get_task`` wire probe, so the owner
    doesn't serialize its whole task table per lookup)."""
    tasks = [t.to_dict(node_id) for t in task_manager.list_tasks()]
    if task_id is not None:
        tid = TaskId.parse(str(task_id))
        tasks = [t for t in tasks if t["id"] == tid.id]
    return {"name": name or node_id,
            "tasks": filter_task_dicts(tasks, actions=actions,
                                       parent_task_id=parent_task_id,
                                       detailed=detailed)}


def hot_threads_text(task_manager: "TaskManager", node_name: str,
                     node_id: str, limit: int = 3) -> str:
    """One node's `_nodes/hot_threads` section: the top running tasks
    (running time on the MANAGER's clock — virtual under the
    deterministic harness) with their current profile stage, in the
    reference's text format (ref: monitor/jvm/HotThreads.java renders
    the busiest threads; here the schedulable unit is the task, so the
    occupancy report is the task table — actually diagnostic, unlike a
    Python-thread stack dump that always shows the interpreter loop)."""
    tasks = sorted(task_manager.list_tasks(),
                   key=lambda t: -t.running_time_nanos())
    lines = [f"::: {{{node_name}}}{{{node_id}}}", ""]
    total_ns = sum(t.running_time_nanos() for t in tasks) or 1
    for t in tasks[:limit]:
        ns = t.running_time_nanos()
        pct = 100.0 * ns / total_ns
        stage = t.profile_stage or "-"
        lines.append(
            f"   {pct:.1f}% ({ns / 1e6:.1f}ms out of "
            f"{total_ns / 1e6:.1f}ms) occupancy by task "
            f"'{t.action}' (id {node_id}:{t.id}, stage {stage})")
        if t.description:
            lines.append(f"     {t.description}")
        lines.append("")
    if len(tasks) == 0:
        lines.append("   0.0% occupancy — no running tasks")
        lines.append("")
    return "\n".join(lines)


def parse_bool_param(value: Any, default: bool = False) -> bool:
    """REST-style boolean param: accepts real bools and the string forms
    the REST layer passes through ("true"/"false"); None → default. Both
    `_tasks` surfaces (single-node REST and the cluster fan-out) parse
    through here so their defaults cannot drift."""
    if value is None:
        return default
    if isinstance(value, bool):
        return value
    return str(value).strip().lower() == "true"


def register_child_of_incoming(task_manager: Optional["TaskManager"],
                               action: str, description: str = ""):
    """Register handler work as a cancellable CHILD of the remote
    caller's task (the ``task.id`` request header the transport dispatch
    installed) — None when no task manager is wired. A child whose
    parent was banned before it arrived comes back already cancelled
    (the ban-table race the reference's design exists for). Shared by
    every data-node handler family so the child-registration contract
    lives in one place."""
    if task_manager is None:
        return None
    from elasticsearch_tpu.telemetry import context as _telectx
    parent_s = _telectx.incoming_parent_task()
    parent = TaskId.parse(parent_s) if parent_s else EMPTY_TASK_ID
    return task_manager.register("transport", action,
                                 description=description,
                                 parent_task_id=parent, cancellable=True)
