"""Binary wire format: vint-based streams.

The framework's `StreamOutput`/`StreamInput` analogue (ref:
common/io/stream/StreamOutput.java — variable-length ints, length-prefixed
strings, versioned payloads). Used by the transport frame codec and by
anything that needs a compact, stable binary encoding (translog already
has its own record format; cluster-state persistence and RPC payloads use
this one).

Payloads on the wire are JSON-in-binary by default (`write_obj`) — the
framework's requests/responses are dict-shaped like the REST layer — but
the primitive codecs here keep hot structures (docid arrays, checkpoints)
compact when needed.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Optional


class StreamOutput:
    """Append-only binary buffer with vint/zigzag/string codecs."""

    def __init__(self) -> None:
        self._parts: list = []

    def bytes(self) -> bytes:
        return b"".join(self._parts)

    def write_byte(self, b: int) -> None:
        self._parts.append(struct.pack("B", b & 0xFF))

    def write_bytes(self, data: bytes) -> None:
        self._parts.append(data)

    def write_vint(self, value: int) -> None:
        """Unsigned LEB128 (ref: StreamOutput.writeVInt)."""
        if value < 0:
            raise ValueError(f"vint must be >= 0, got {value}")
        out = bytearray()
        while value >= 0x80:
            out.append((value & 0x7F) | 0x80)
            value >>= 7
        out.append(value)
        self._parts.append(bytes(out))

    def write_zlong(self, value: int) -> None:
        """Zigzag-encoded signed long (ref: StreamOutput.writeZLong).
        Python's arbitrary-precision arithmetic shift makes the classic
        ``(v << 1) ^ (v >> 63)`` zigzag identity hold for any int."""
        self.write_vint((value << 1) ^ (value >> 63))

    def write_long(self, value: int) -> None:
        self._parts.append(struct.pack(">q", value))

    def write_int(self, value: int) -> None:
        self._parts.append(struct.pack(">i", value))

    def write_double(self, value: float) -> None:
        self._parts.append(struct.pack(">d", value))

    def write_bool(self, value: bool) -> None:
        self.write_byte(1 if value else 0)

    def write_string(self, value: str) -> None:
        data = value.encode("utf-8")
        self.write_vint(len(data))
        self._parts.append(data)

    def write_optional_string(self, value: Optional[str]) -> None:
        if value is None:
            self.write_bool(False)
        else:
            self.write_bool(True)
            self.write_string(value)

    def write_len_bytes(self, data: bytes) -> None:
        self.write_vint(len(data))
        self._parts.append(data)

    def write_obj(self, obj: Any) -> None:
        """JSON-serializable payload, length-prefixed."""
        self.write_len_bytes(json.dumps(obj, separators=(",", ":"),
                                        default=_json_default).encode("utf-8"))


def _json_default(o):
    # numpy scalars/arrays show up in responses; coerce to plain python
    tolist = getattr(o, "tolist", None)
    if tolist is not None:
        return tolist()
    item = getattr(o, "item", None)
    if item is not None:
        return item()
    raise TypeError(f"not JSON serializable: {type(o)!r}")


class StreamInput:
    """Cursor over a bytes buffer, mirroring StreamOutput."""

    def __init__(self, data: bytes, pos: int = 0) -> None:
        self._data = data
        self._pos = pos

    def remaining(self) -> int:
        return len(self._data) - self._pos

    def read_byte(self) -> int:
        v = self._data[self._pos]
        self._pos += 1
        return v

    def read_bytes(self, n: int) -> bytes:
        v = self._data[self._pos:self._pos + n]
        if len(v) != n:
            raise EOFError(f"need {n} bytes, have {len(v)}")
        self._pos += n
        return v

    def read_vint(self) -> int:
        result = 0
        shift = 0
        while True:
            b = self.read_byte()
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                return result
            shift += 7

    def read_zlong(self) -> int:
        v = self.read_vint()
        return (v >> 1) ^ -(v & 1)

    def read_long(self) -> int:
        return struct.unpack(">q", self.read_bytes(8))[0]

    def read_int(self) -> int:
        return struct.unpack(">i", self.read_bytes(4))[0]

    def read_double(self) -> float:
        return struct.unpack(">d", self.read_bytes(8))[0]

    def read_bool(self) -> bool:
        return self.read_byte() != 0

    def read_string(self) -> str:
        n = self.read_vint()
        return self.read_bytes(n).decode("utf-8")

    def read_optional_string(self) -> Optional[str]:
        return self.read_string() if self.read_bool() else None

    def read_len_bytes(self) -> bytes:
        return self.read_bytes(self.read_vint())

    def read_obj(self) -> Any:
        return json.loads(self.read_len_bytes().decode("utf-8"))
