"""Remote clusters: connections to other clusters for CCS/CCR.

Mirrors the reference's remote-cluster layer (ref: transport/
RemoteClusterService.java:430 — per-alias connections with sniff/proxy
strategies; `alias:index` expressions resolved in TransportSearchAction;
SURVEY.md §2.3 "Cross-cluster search"). Re-design for this engine:
remote clusters register via the same `cluster.remote.{alias}.seeds`
settings surface, but the connection is an HTTP JSON client to the
remote node's REST port (this framework's inter-cluster DCN path) —
the in-cluster ICI/RPC transport stays reserved for intra-cluster
traffic, matching the reference's separation of remote-cluster
connections from local cluster transport.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuException,
    IllegalArgumentException,
    ResourceNotFoundException,
)

REMOTE_CLUSTER_INDEX_SEPARATOR = ":"


class RemoteClusterClient:
    """Minimal JSON-over-HTTP client to one remote cluster node."""

    def __init__(self, alias: str, seeds: List[str], timeout: float = 10.0):
        self.alias = alias
        self.seeds = seeds
        self.timeout = timeout

    def request(self, method: str, path: str,
                body: Optional[Any] = None) -> Dict[str, Any]:
        last_err: Optional[Exception] = None
        for seed in self.seeds:
            url = f"http://{seed}{path}"
            data = (json.dumps(body).encode()
                    if body is not None else None)
            req = urllib.request.Request(
                url, data=data, method=method,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(
                        req, timeout=self.timeout) as resp:
                    text = resp.read().decode()
                    try:
                        return json.loads(text)
                    except ValueError:     # _cat family plain text
                        return {"_cat": text}
            except urllib.error.HTTPError as e:
                detail = e.read().decode(errors="replace")
                raise ElasticsearchTpuException(
                    f"remote cluster [{self.alias}] returned {e.code}: "
                    f"{detail[:400]}")
            except OSError as e:           # connection refused, timeout
                last_err = e
                continue
        raise ElasticsearchTpuException(
            f"cannot connect to remote cluster [{self.alias}] "
            f"(seeds {self.seeds}): {last_err}")

    def search(self, index: str, body: Dict[str, Any]) -> Dict[str, Any]:
        return self.request("POST", f"/{index}/_search", body)


class ProxyRemoteClusterClient(RemoteClusterClient):
    """Proxy connection strategy (ref: transport/
    ProxyConnectionStrategy.java:49): ONE configured address — usually
    a load balancer in front of the remote cluster — with a bounded
    pool of PERSISTENT connections and no sniffing (the local cluster
    never learns remote topology, which is the point: proxy mode works
    where only the LB is routable). Re-design for this engine's
    HTTP-based DCN path: the pool holds keep-alive
    ``http.client.HTTPConnection`` objects, checked out per request,
    re-dialed transparently when the LB drops one."""

    def __init__(self, alias: str, proxy_address: str,
                 socket_connections: int = 6, timeout: float = 10.0):
        super().__init__(alias, [proxy_address], timeout)
        self.proxy_address = proxy_address
        self.socket_connections = max(1, int(socket_connections))
        self._pool: List[Any] = []
        self._pool_lock = threading.Lock()
        self._created = 0

    def _checkout(self):
        import http.client
        with self._pool_lock:
            if self._pool:
                return self._pool.pop()
            self._created += 1
        host, _, port = self.proxy_address.partition(":")
        return http.client.HTTPConnection(
            host, int(port or 80), timeout=self.timeout)

    def _checkin(self, conn):
        with self._pool_lock:
            if len(self._pool) < self.socket_connections:
                self._pool.append(conn)
                return
            self._created -= 1
        try:
            conn.close()
        except Exception:
            pass

    def _dial_fresh(self):
        import http.client
        with self._pool_lock:
            self._created += 1
        host, _, port = self.proxy_address.partition(":")
        return http.client.HTTPConnection(
            host, int(port or 80), timeout=self.timeout)

    def request(self, method: str, path: str,
                body: Optional[Any] = None) -> Dict[str, Any]:
        import http.client

        data = json.dumps(body).encode() if body is not None else None
        last_err: Optional[Exception] = None
        # attempt 0 may pop a stale pooled socket (LB idle timeout);
        # the retry dials FRESH — several pooled sockets can be dead
        # at once, so popping the pool again would just fail again
        for attempt in range(2):
            conn = self._checkout() if attempt == 0 else \
                self._dial_fresh()
            try:
                conn.request(method, path, body=data,
                             headers={"Content-Type":
                                      "application/json"})
                resp = conn.getresponse()
                text = resp.read().decode()
                if resp.status >= 400:
                    self._checkin(conn)
                    raise ElasticsearchTpuException(
                        f"remote cluster [{self.alias}] returned "
                        f"{resp.status}: {text[:400]}")
                self._checkin(conn)
                try:
                    return json.loads(text)
                except ValueError:
                    return {"_cat": text}
            except ElasticsearchTpuException:
                raise
            except (OSError, http.client.HTTPException) as e:
                # stale pooled socket, LB reset, malformed LB response
                last_err = e
                try:
                    conn.close()
                except Exception:
                    pass
                with self._pool_lock:
                    self._created -= 1
                continue
        raise ElasticsearchTpuException(
            f"cannot connect to remote cluster [{self.alias}] via "
            f"proxy {self.proxy_address}: {last_err}")

    def pool_stats(self) -> Dict[str, int]:
        with self._pool_lock:
            return {"pooled": len(self._pool),
                    "created": self._created,
                    "max": self.socket_connections}


class RemoteClusterService:
    """Registry of remote clusters + index-expression resolution (ref:
    RemoteClusterService.groupIndices)."""

    def __init__(self, node):
        self.node = node
        self._clusters: Dict[str, RemoteClusterClient] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------ configuration
    def apply_settings(self, settings: Dict[str, Any]):
        """Consume cluster.remote.{alias}.seeds entries from a settings
        update (the _cluster/settings surface)."""
        remote = settings.get("cluster", {}).get("remote", {})
        # also accept flat keys "cluster.remote.alias.seeds"
        flat: Dict[str, Any] = {}
        for k, v in settings.items():
            if k.startswith("cluster.remote."):
                rest = k[len("cluster.remote."):]
                alias, _, leaf = rest.partition(".")
                flat.setdefault(alias, {})[leaf] = v
        merged = {**remote, **flat}
        for alias, cfg in merged.items():
            mode = str(cfg.get("mode", "sniff"))
            if mode == "proxy" or "proxy_address" in cfg:
                # proxy connection strategy (ref:
                # ProxyConnectionStrategy.java:49)
                addr = cfg.get("proxy_address")
                if addr in (None, ""):
                    with self._lock:
                        self._clusters.pop(alias, None)
                    continue
                with self._lock:
                    self._clusters[alias] = ProxyRemoteClusterClient(
                        alias, str(addr),
                        socket_connections=int(cfg.get(
                            "proxy_socket_connections", 6)))
                continue
            if "seeds" not in cfg:
                continue            # unrelated leaf (skip_unavailable, …)
            seeds = cfg["seeds"]
            if seeds in (None, [], ""):
                # explicit null/empty removes the connection
                with self._lock:
                    self._clusters.pop(alias, None)
                continue
            if isinstance(seeds, str):
                seeds = [seeds]
            with self._lock:
                self._clusters[alias] = RemoteClusterClient(alias, seeds)

    def register(self, alias: str, seeds: List[str]):
        with self._lock:
            self._clusters[alias] = RemoteClusterClient(alias, seeds)

    def get_client(self, alias: str) -> RemoteClusterClient:
        c = self._clusters.get(alias)
        if c is None:
            raise ResourceNotFoundException(
                f"no such remote cluster: [{alias}]")
        return c

    def info(self) -> Dict[str, Any]:
        out = {}
        for alias, c in self._clusters.items():
            connected = True
            try:
                c.request("GET", "/")
            except ElasticsearchTpuException:
                connected = False
            if isinstance(c, ProxyRemoteClusterClient):
                out[alias] = {
                    "connected": connected, "mode": "proxy",
                    "proxy_address": c.proxy_address,
                    "max_proxy_socket_connections":
                        c.socket_connections,
                    "num_proxy_sockets_connected":
                        c.pool_stats()["created"] if connected else 0}
            else:
                out[alias] = {"connected": connected, "seeds": c.seeds,
                              "mode": "sniff",
                              "num_nodes_connected":
                                  1 if connected else 0}
        return out

    # -------------------------------------------------------- resolution
    def group_indices(self, expression: str
                      ) -> Tuple[List[str], Dict[str, List[str]]]:
        """Split an index expression into (local, {alias: [indices]})."""
        local: List[str] = []
        remote: Dict[str, List[str]] = {}
        for part in expression.split(","):
            part = part.strip()
            if not part:
                continue
            if REMOTE_CLUSTER_INDEX_SEPARATOR in part:
                alias, _, index = part.partition(
                    REMOTE_CLUSTER_INDEX_SEPARATOR)
                if alias in self._clusters:
                    remote.setdefault(alias, []).append(index)
                    continue
            local.append(part)
        return local, remote

    @property
    def has_remotes(self) -> bool:
        return bool(self._clusters)


def merge_search_responses(
        responses: List[Tuple[Optional[str], Dict[str, Any]]],
        size: int = 10,
        sort_dirs: Optional[List[str]] = None) -> Dict[str, Any]:
    """Merge independently reduced per-cluster search responses (ref:
    action/search/SearchResponseMerger — the ccs_minimize_roundtrips
    topology): hits re-sorted by score/sort values (honoring the request
    sort directions), totals summed, shard counts summed. Remote hit
    _index gets the `alias:` prefix."""
    import functools

    all_hits: List[Dict[str, Any]] = []
    total = 0
    relation = "eq"
    max_score = None
    took = 0
    shards = {"total": 0, "successful": 0, "skipped": 0, "failed": 0}
    for alias, r in responses:
        hits = r.get("hits", {})
        t = hits.get("total", {})
        total += t.get("value", 0)
        if t.get("relation", "eq") != "eq":
            relation = "gte"
        ms = hits.get("max_score")
        if ms is not None:
            max_score = ms if max_score is None else max(max_score, ms)
        took = max(took, r.get("took", 0))
        for k in shards:
            shards[k] += r.get("_shards", {}).get(k, 0)
        for h in hits.get("hits", []):
            h = dict(h)
            if alias:
                h["_index"] = f"{alias}:{h['_index']}"
            all_hits.append(h)

    dirs = sort_dirs or []

    def hit_cmp(a, b):
        sa, sb = a.get("sort"), b.get("sort")
        if sa and sb:
            for i, (v1, v2) in enumerate(zip(sa, sb)):
                if v1 == v2:
                    continue
                if v1 is None:
                    return 1                     # missing sorts last
                if v2 is None:
                    return -1
                try:
                    c = -1 if v1 < v2 else 1
                except TypeError:
                    c = -1 if str(v1) < str(v2) else 1
                d = dirs[i] if i < len(dirs) else "asc"
                return c if d == "asc" else -c
            return 0
        s1 = a.get("_score") or 0.0
        s2 = b.get("_score") or 0.0
        return -1 if s1 > s2 else (1 if s1 < s2 else 0)

    all_hits.sort(key=functools.cmp_to_key(hit_cmp))
    return {
        "took": took,
        "timed_out": any(r.get("timed_out") for _, r in responses),
        "num_reduce_phases": len(responses),
        "_shards": shards,
        "_clusters": {"total": len(responses),
                      "successful": len(responses), "skipped": 0},
        "hits": {"total": {"value": total, "relation": relation},
                 "max_score": max_score,
                 "hits": all_hits[:size]},
    }
