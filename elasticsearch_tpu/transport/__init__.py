"""Distributed communication backend (DCN control plane).

Framed async RPC with action dispatch, QoS lanes, versioned handshakes,
and task management (ref: server transport/ + tasks/, SURVEY.md §5.8).
The data plane — sharded scoring + collective top-k merges — rides XLA
collectives in ``parallel/``; this package moves control messages:
coordination, replication, query/fetch.
"""

from elasticsearch_tpu.transport.transport import (  # noqa: F401
    CURRENT_VERSION,
    ConnectTransportException,
    DiscoveryNode,
    InProcessTransport,
    NodeNotConnectedException,
    ReceiveTimeoutTransportException,
    RemoteTransportException,
    ResponseHandler,
    TcpTransport,
    TransportChannel,
    TransportService,
    make_inprocess_cluster_registry,
    new_node_id,
)
from elasticsearch_tpu.transport.tasks import (  # noqa: F401
    CancellableTask,
    Task,
    TaskCancelledException,
    TaskId,
    TaskManager,
)
