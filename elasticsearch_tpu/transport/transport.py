"""Distributed communication backend: framed async RPC with action
dispatch.

The TPU framework's node-to-node control plane (ref: SURVEY.md §5.8;
transport/TransportService.java:71,177,521; transport/TcpTransport.java;
transport/TcpHeader.java:27-43). Reproduces the reference's essentials,
redesigned for a Python/C++ host runtime around the TPU compute path:

- **action-name dispatch**: handlers registered by action string
  (`internal:...`, `indices:data/read/...`), responses matched by
  request id (ref: RequestHandlerRegistry, InboundHandler).
- **QoS lanes**: each node pair keeps per-class channels
  (recovery/bulk/reg/state/ping) so bulk traffic can't starve
  cluster-state publication (ref: ConnectionProfile.java:76-90 — 13
  sockets/node-pair partitioned by traffic class). The TCP transport
  opens one socket per lane; the in-process transport keeps per-lane
  FIFO queues.
- **versioned handshake** on connect (ref: TransportHandshaker.java).
- **interceptor chain** wrapping send + dispatch (the seam where
  security/task-propagation insert themselves, ref:
  TransportInterceptor consumed in TransportService ctor).
- **timeouts** on pending responses; connection failure fails all
  pending requests to that node.

XLA collectives over ICI handle the data plane (sharded top-k merges in
`parallel/sharded.py`); this layer is the DCN control plane: cluster
coordination, replication, the query/fetch two-phase protocol.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
import traceback
import uuid
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import (
    CircuitBreakingException,
    ElasticsearchTpuException,
)
from elasticsearch_tpu.telemetry import context as _telectx
from elasticsearch_tpu.transport.wire import StreamInput, StreamOutput
from elasticsearch_tpu.utils.breaker import (
    CircuitBreaker,
    payload_size_bytes,
)

# version 2 adds the staged peer-recovery protocol (snapshot-under-lease
# phase 1, seqno-addressed translog batches, primary-handoff finalize);
# a version-1 peer still recovers through the single-RPC legacy path
CURRENT_VERSION = 2
# oldest wire version this build interoperates with (ref:
# TransportHandshaker + Version.minimumCompatibilityVersion — a rolling
# upgrade requires version N and N+1 nodes to form one cluster)
MIN_COMPATIBLE_VERSION = 1
# Frame marker (ref: TcpHeader 'E','S' marker bytes)
MARKER = b"ET"

# QoS lanes (ref: ConnectionProfile.ConnectionTypeHandle — counts
# recovery(2)/bulk(3)/reg(6)/state(1)/ping(1); here one queue/socket per
# class is enough because lanes are the isolation unit, not a perf knob)
LANE_RECOVERY = "recovery"
LANE_BULK = "bulk"
LANE_REG = "reg"
LANE_STATE = "state"
LANE_PING = "ping"
LANES = (LANE_RECOVERY, LANE_BULK, LANE_REG, LANE_STATE, LANE_PING)

HANDSHAKE_ACTION = "internal:tcp/handshake"

# status byte flags (ref: TransportStatus)
STATUS_REQUEST = 1 << 0
STATUS_ERROR = 1 << 1


class ConnectTransportException(ElasticsearchTpuException):
    pass


class NodeNotConnectedException(ElasticsearchTpuException):
    pass


class ReceiveTimeoutTransportException(ElasticsearchTpuException):
    pass


class RemoteTransportException(ElasticsearchTpuException):
    """An exception raised by the remote handler, rethrown locally."""

    def __init__(self, message: str, remote_type: str = "exception"):
        super().__init__(message)
        self.remote_type = remote_type


@dataclass(frozen=True)
class DiscoveryNode:
    """Identity + address of a node (ref: cluster/node/DiscoveryNode)."""

    node_id: str
    name: str = ""
    host: str = "127.0.0.1"
    port: int = 0
    roles: Tuple[str, ...] = ("master", "data", "ingest")

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def is_master_eligible(self) -> bool:
        return "master" in self.roles

    def is_voting_only(self) -> bool:
        """Participates in elections/quorums but never becomes master
        itself (ref: x-pack voting-only-node VotingOnlyNodePlugin)."""
        return "voting_only" in self.roles

    def is_data_node(self) -> bool:
        return "data" in self.roles

    def to_dict(self) -> Dict[str, Any]:
        return {"node_id": self.node_id, "name": self.name,
                "host": self.host, "port": self.port,
                "roles": list(self.roles)}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "DiscoveryNode":
        return DiscoveryNode(node_id=d["node_id"], name=d.get("name", ""),
                             host=d.get("host", "127.0.0.1"),
                             port=d.get("port", 0),
                             roles=tuple(d.get("roles", ())))


@dataclass
class RequestHandler:
    action: str
    handler: Callable  # (request, channel, task) -> None
    executor: str = "generic"
    can_trip_breaker: bool = True


class TransportChannel:
    """Response channel handed to request handlers (ref:
    TransportChannel — sendResponse / sendException)."""

    def __init__(self, send_fn: Callable[[Any, bool], None], action: str):
        self._send = send_fn
        self.action = action
        self._done = False

    def send_response(self, response: Any) -> None:
        if self._done:
            raise RuntimeError(f"channel for {self.action} already completed")
        self._done = True
        self._send(response, False)

    def send_exception(self, exc: BaseException) -> None:
        if self._done:
            return
        self._done = True
        # a proxied failure keeps its ROOT remote type: a handler that
        # rethrows a RemoteTransportException must not mask the original
        # exception class — failover uses it to distinguish retryable
        # (connect/timeout) from non-retryable (parse/illegal-argument)
        # failures
        remote_type = getattr(exc, "remote_type", None) \
            or type(exc).__name__
        self._send({"type": remote_type, "reason": str(exc)}, True)


@dataclass
class ResponseContext:
    handler: "ResponseHandler"
    node: DiscoveryNode
    action: str
    deadline: Optional[float]


class ResponseHandler:
    """Caller-side completion callbacks (ref: TransportResponseHandler)."""

    def __init__(self,
                 on_response: Callable[[Any], None],
                 on_failure: Optional[Callable[[BaseException], None]] = None):
        self.on_response = on_response
        self.on_failure = on_failure or (lambda e: None)


def attach_headers(request: Any,
                   headers: Optional[Dict[str, Any]]) -> Any:
    """Carry request headers on the wire: dict payloads get a copied
    ``__headers`` section (the transport-request analogue of the
    reference's ThreadContext headers riding every TransportRequest);
    the dispatch side strips it before the handler sees the request."""
    if headers and isinstance(request, dict):
        request = dict(request)
        request["__headers"] = dict(headers)
    return request


def pop_headers(payload: Any) -> Optional[Dict[str, Any]]:
    if isinstance(payload, dict) and "__headers" in payload:
        return payload.pop("__headers")
    return None


def charge_inflight(breaker_service, action: str,
                    payload: Any) -> Optional[Callable[[], None]]:
    """Charge the in_flight_requests breaker for an inbound transport
    message (ref: InboundAggregator.finishAggregation — the message is
    accounted BEFORE its handler runs and released when the request
    cycle completes). Returns a release() callback, or None when no
    breaker service is attached. Raises CircuitBreakingException when
    the node is out of headroom — the caller turns that into a 429-class
    remote failure the sender can retry on another copy.

    Sizing re-serializes structured payloads (one extra O(payload) pass
    per inbound hop, same order as the wire decode that just ran);
    plumbing the already-known frame length through _dispatch_request
    would remove it for the TCP transport — a follow-up if profiles
    show it mattering."""
    if breaker_service is None:
        return None
    breaker = breaker_service.get_breaker(
        CircuitBreaker.IN_FLIGHT_REQUESTS)
    nbytes = payload_size_bytes(payload)
    breaker.add_estimate_bytes_and_maybe_break(
        nbytes, label=f"<transport_request>[{action}]")

    def release() -> None:
        breaker.release(nbytes)

    return release


def instrument_send(telemetry, action: str, request: Any,
                    handler: ResponseHandler,
                    headers: Optional[Dict[str, Any]]):
    """The shared send-side telemetry seam (production TransportService
    and the sim DisruptableTransport call this, so counting/header
    semantics cannot drift between them): stamp the ambient task
    (``task.id``/``task.parent`` — a send issued under a registered
    task parents the remote handler's child task to it), attach the
    header carrier, count the outbound request, wrap the handler with
    round-trip timing. Returns the (request, handler) pair to send."""
    headers = _telectx.stamp_task_headers(headers)
    request = attach_headers(request, headers)
    if telemetry is not None:
        telemetry.metrics.inc("transport.requests.sent", action=action)
        handler = timed_handler(telemetry, action, handler)
    return request, handler


def instrument_inbound(telemetry, action: str,
                       payload: Any) -> Optional[Dict[str, Any]]:
    """The shared dispatch-side seam: strip the header carrier before
    the handler sees the payload and count the inbound request.
    Returns the stripped headers (for ambient trace installation)."""
    headers = pop_headers(payload)
    if telemetry is not None:
        telemetry.metrics.inc("transport.requests.received",
                              action=action)
    return headers


def timed_handler(telemetry, action: str,
                  handler: ResponseHandler) -> ResponseHandler:
    """Wrap a ResponseHandler with per-action telemetry: round-trip
    latency histogram + ok/failure counters, on the telemetry clock."""
    metrics = telemetry.metrics
    t0 = metrics.clock()

    def ok(resp):
        metrics.observe("transport.latency",
                        (metrics.clock() - t0) * 1000.0, action=action)
        metrics.inc("transport.responses", action=action)
        handler.on_response(resp)

    def fail(exc):
        metrics.observe("transport.latency",
                        (metrics.clock() - t0) * 1000.0, action=action)
        metrics.inc("transport.failures", action=action)
        handler.on_failure(exc)

    return ResponseHandler(ok, fail)


def _encode_frame(request_id: int, status: int, version: int, action: str,
                  payload: Any) -> bytes:
    out = StreamOutput()
    out.write_vint(request_id)
    out.write_byte(status)
    out.write_vint(version)
    out.write_string(action)
    out.write_obj(payload)
    body = out.bytes()
    return MARKER + struct.pack(">I", len(body)) + body


def _decode_frame(body: bytes) -> Tuple[int, int, int, str, Any]:
    sin = StreamInput(body)
    request_id = sin.read_vint()
    status = sin.read_byte()
    version = sin.read_vint()
    action = sin.read_string()
    payload = sin.read_obj()
    return request_id, status, version, action, payload


class BaseTransport:
    """Shared plumbing: request-id allocation, pending-response table,
    handler registry, dispatch. Subclasses move bytes."""

    def __init__(self, local_node: DiscoveryNode,
                 executor: Optional[ThreadPoolExecutor] = None):
        self.local_node = local_node
        self._request_id = 0
        self._id_lock = threading.Lock()
        self._pending: Dict[int, ResponseContext] = {}
        self._pending_lock = threading.Lock()
        self._handlers: Dict[str, RequestHandler] = {}
        self._executor = executor or ThreadPoolExecutor(
            max_workers=8, thread_name_prefix=f"transport-{local_node.name}")
        self._owns_executor = executor is None
        self._closed = False
        # node telemetry bundle; None keeps instrumented sites one branch
        self.telemetry = None
        # node breaker service (utils/breaker.py); when wired, inbound
        # requests charge in_flight_requests before dispatch — the
        # RequestHandler.can_trip_breaker flag gates which actions may
        # be shed (coordination/handshake traffic is exempt)
        self.breaker_service = None

    # -- registry ---------------------------------------------------------

    def register_handler(self, action: str, handler: Callable,
                         executor: str = "generic",
                         can_trip_breaker: bool = True) -> None:
        if action in self._handlers:
            raise ValueError(f"handler for [{action}] already registered")
        self._handlers[action] = RequestHandler(action, handler, executor,
                                                can_trip_breaker)

    def new_request_id(self) -> int:
        with self._id_lock:
            self._request_id += 1
            return self._request_id

    def _submit(self, fn: Callable, *args) -> None:
        """Executor submit that tolerates concurrent close."""
        try:
            self._executor.submit(fn, *args)
        except RuntimeError:
            if not self._closed:
                raise

    # -- inbound ----------------------------------------------------------

    def _dispatch_request(self, source: DiscoveryNode, request_id: int,
                          action: str, payload: Any,
                          reply: Callable[[bytes], None]) -> None:
        reg = self._handlers.get(action)
        # strip the request-header carrier before the handler sees the
        # payload; the trace context it carries becomes ambient for the
        # duration of the handler (Dapper-style RPC propagation)
        headers = instrument_inbound(self.telemetry, action, payload)
        release_box: Dict[str, Callable] = {}

        def send_response(response: Any, is_error: bool) -> None:
            # in_flight_requests releases when the request cycle ends
            # (first completion wins; TransportChannel guards doubles)
            rel = release_box.pop("release", None)
            if rel is not None:
                rel()
            status = STATUS_ERROR if is_error else 0
            reply(_encode_frame(request_id, status, CURRENT_VERSION,
                                action, response))

        channel = TransportChannel(send_response, action)
        if reg is None:
            channel.send_exception(
                ElasticsearchTpuException(
                    f"No handler for action [{action}]"))
            return
        if self.breaker_service is not None and reg.can_trip_breaker:
            try:
                rel = charge_inflight(self.breaker_service, action,
                                      payload)
                if rel is not None:
                    release_box["release"] = rel
            except CircuitBreakingException as e:
                # shed BEFORE any handler work: the sender sees a typed,
                # retryable 429-class failure (failover walks to another
                # copy; replication retries with backoff)
                channel.send_exception(e)
                return

        def run():
            try:
                with _telectx.incoming(headers):
                    reg.handler(payload, channel, source)
            except BaseException as e:  # noqa: BLE001 — handler fault barrier
                try:
                    channel.send_exception(e)
                except Exception:
                    traceback.print_exc()

        self._submit(run)

    def _dispatch_response(self, request_id: int, status: int,
                           payload: Any) -> None:
        with self._pending_lock:
            ctx = self._pending.pop(request_id, None)
        if ctx is None:
            return  # late response after timeout — dropped
        if status & STATUS_ERROR:
            exc = RemoteTransportException(
                f"[{ctx.node.name}][{ctx.action}] {payload.get('reason')}",
                remote_type=payload.get("type", "exception"))
            self._submit(ctx.handler.on_failure, exc)
        else:
            self._submit(ctx.handler.on_response, payload)

    # -- timeouts / failures ---------------------------------------------

    def register_pending(self, request_id: int, ctx: ResponseContext) -> None:
        with self._pending_lock:
            self._pending[request_id] = ctx

    def sweep_timeouts(self) -> None:
        now = time.monotonic()
        expired: List[ResponseContext] = []
        with self._pending_lock:
            for rid in [r for r, c in self._pending.items()
                        if c.deadline is not None and c.deadline <= now]:
                expired.append(self._pending.pop(rid))
        for ctx in expired:
            self._submit(
                ctx.handler.on_failure,
                ReceiveTimeoutTransportException(
                    f"[{ctx.node.name}][{ctx.action}] request timed out"))

    def fail_pending_to(self, node_id: str, reason: str) -> None:
        failed: List[ResponseContext] = []
        with self._pending_lock:
            for rid in [r for r, c in self._pending.items()
                        if c.node.node_id == node_id]:
                failed.append(self._pending.pop(rid))
        for ctx in failed:
            self._submit(
                ctx.handler.on_failure,
                NodeNotConnectedException(
                    f"[{ctx.node.name}][{ctx.action}] {reason}"))

    def close(self) -> None:
        self._closed = True
        with self._pending_lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for ctx in pending:
            try:
                ctx.handler.on_failure(
                    NodeNotConnectedException("transport closed"))
            except Exception:
                pass
        if self._owns_executor:
            self._executor.shutdown(wait=False, cancel_futures=True)


class InProcessTransport(BaseTransport):
    """In-JVM-style transport: nodes in one process wired through a shared
    registry, delivery via per-lane FIFO ordering (ref: the test
    framework's MockTransport; also the NodeClient local-execution
    optimization, node/Node.java:365)."""

    _REGISTRY_LOCK = threading.Lock()

    def __init__(self, local_node: DiscoveryNode,
                 registry: Dict[str, "InProcessTransport"],
                 executor: Optional[ThreadPoolExecutor] = None):
        super().__init__(local_node, executor)
        self._registry = registry
        with self._REGISTRY_LOCK:
            registry[local_node.node_id] = self

    def connect(self, node: DiscoveryNode) -> None:
        if node.node_id not in self._registry:
            raise ConnectTransportException(
                f"cannot connect to {node.name}: unknown node")

    def send(self, node: DiscoveryNode, request_id: int, action: str,
             payload: Any, lane: str = LANE_REG,
             wire_version: int = CURRENT_VERSION) -> None:
        target = self._registry.get(node.node_id)
        if target is None or target._closed:
            raise NodeNotConnectedException(
                f"node [{node.name}] not connected")
        me = self.local_node

        def reply(frame: bytes) -> None:
            rid, status, _ver, _action, resp_payload = _decode_frame(frame[6:])
            if not self._closed:
                self._dispatch_response(rid, status, resp_payload)

        target._dispatch_request(me, request_id, action, payload, reply)


class TcpTransport(BaseTransport):
    """Real-socket transport: framed protocol, one socket per QoS lane per
    peer (ref: TcpTransport.java:97,261,339,665; InboundPipeline.java:77-89
    decode → aggregate → dispatch)."""

    def __init__(self, local_node: DiscoveryNode, bind_port: int = 0,
                 executor: Optional[ThreadPoolExecutor] = None,
                 ssl_config: Optional[Dict] = None,
                 ip_filter: Optional[Tuple[str, str]] = None):
        super().__init__(local_node, executor)
        # accept-time IP filtering (ref: x-pack IPFilter on the
        # transport profile — allow wins, allow-only implies deny);
        # same semantics as the HTTP front
        from elasticsearch_tpu.rest.http_server import HttpServer
        self._ip_allow, self._ip_deny = HttpServer._parse_ip_filter(
            ip_filter)
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((local_node.host, bind_port))
        self._server.listen(64)
        self.bound_port = self._server.getsockname()[1]
        # node-to-node TLS (ref: xpack.security.transport.ssl.* —
        # SecurityNetty4ServerTransport): with certificate_authorities
        # configured, verification is MUTUAL (the reference's transport
        # default, verification_mode=certificate). Handshakes run
        # per-connection in the reader thread (common/tls.py), never in
        # the accept loop.
        self._ssl_client_ctx = None
        self._ssl_server_ctx = None
        if ssl_config:
            from elasticsearch_tpu.common.tls import (client_context,
                                                      server_context)
            self._ssl_server_ctx = server_context(ssl_config)
            self._ssl_client_ctx = client_context(ssl_config)
        self.local_node = DiscoveryNode(
            node_id=local_node.node_id, name=local_node.name,
            host=local_node.host, port=self.bound_port,
            roles=local_node.roles)
        # (node_id, lane) -> (socket, per-socket write lock); guarded by
        # _conn_lock. Writes must be serialized per socket or concurrent
        # sendall calls interleave frame bytes.
        self._conns: Dict[Tuple[str, str],
                          Tuple[socket.socket, threading.Lock]] = {}
        self._conn_lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"tcp-accept-{local_node.name}",
            daemon=True)
        self._accept_thread.start()

    # -- server side ------------------------------------------------------

    def _accept_loop(self) -> None:
        import ipaddress
        while not self._closed:
            try:
                conn, _addr = self._server.accept()
            except OSError:
                return
            if self._ip_allow or self._ip_deny:
                try:
                    addr = ipaddress.ip_address(_addr[0])
                except ValueError:
                    conn.close()
                    continue
                allowed = (any(addr in net for net in self._ip_allow)
                           or (not any(addr in net
                                       for net in self._ip_deny)
                               and not self._ip_allow))
                if not allowed:
                    # ref: IPFilter — rejected at accept, no response
                    conn.close()
                    continue
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        """Per-connection thread: bounded TLS handshake (a stalled or
        plaintext peer affects only its own connection), then the frame
        reader."""
        if self._ssl_server_ctx is not None:
            from elasticsearch_tpu.common.tls import handshake
            try:
                conn = handshake(conn, self._ssl_server_ctx)
            except OSError:
                try:
                    conn.close()
                finally:
                    return
        self._read_loop(conn, None)

    def _read_loop(self, conn: socket.socket,
                   peer: Optional[DiscoveryNode]) -> None:
        """Decode frames off one socket; dispatch requests/responses."""
        write_lock = threading.Lock()  # serializes replies on this conn
        try:
            buf = b""
            while not self._closed:
                need = 6  # marker + length
                while len(buf) < need:
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                if buf[:2] != MARKER:
                    raise IOError("bad frame marker")
                (length,) = struct.unpack(">I", buf[2:6])
                while len(buf) < 6 + length:
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                body, buf = buf[6:6 + length], buf[6 + length:]
                rid, status, ver, action, payload = _decode_frame(body)
                if self.telemetry is not None:
                    self.telemetry.metrics.inc("transport.bytes.received",
                                               6 + length, action=action)
                if status & STATUS_REQUEST:
                    source = (DiscoveryNode.from_dict(payload.pop("__source"))
                              if isinstance(payload, dict)
                              and "__source" in payload else peer)

                    def reply(frame: bytes, _c=conn,
                              _lk=write_lock) -> None:
                        with _lk:
                            _c.sendall(frame)

                    self._dispatch_request(source, rid, action, payload,
                                           reply)
                else:
                    self._dispatch_response(rid, status, payload)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- client side ------------------------------------------------------

    def connect(self, node: DiscoveryNode) -> None:
        """Eagerly open the `reg` lane (others open on demand)."""
        self._socket_for(node, LANE_REG)

    def _socket_for(self, node: DiscoveryNode,
                    lane: str) -> Tuple[socket.socket, threading.Lock]:
        key = (node.node_id, lane)
        with self._conn_lock:
            entry = self._conns.get(key)
            if entry is not None:
                return entry
        try:
            sock = socket.create_connection(node.address, timeout=5.0)
            if self._ssl_client_ctx is not None:
                sock = self._ssl_client_ctx.wrap_socket(
                    sock, server_hostname=node.host)
            sock.settimeout(None)
        except OSError as e:
            raise ConnectTransportException(
                f"cannot connect to [{node.name}] {node.address}: {e}") from e
        entry = (sock, threading.Lock())
        with self._conn_lock:
            existing = self._conns.get(key)
            if existing is not None:
                sock.close()
                return existing
            self._conns[key] = entry
        threading.Thread(target=self._read_loop, args=(sock, node),
                         daemon=True).start()
        return entry

    def send(self, node: DiscoveryNode, request_id: int, action: str,
             payload: Any, lane: str = LANE_REG,
             wire_version: int = CURRENT_VERSION) -> None:
        if isinstance(payload, dict):
            payload = dict(payload)
            payload["__source"] = self.local_node.to_dict()
        # frames to a peer are encoded at the NEGOTIATED version (today
        # a single format exists; a future format change keys on this)
        frame = _encode_frame(request_id, STATUS_REQUEST, wire_version,
                              action, payload)
        if self.telemetry is not None:
            self.telemetry.metrics.inc("transport.bytes.sent", len(frame),
                                       action=action)
        try:
            sock, write_lock = self._socket_for(node, lane)
            with write_lock:
                sock.sendall(frame)
        except (OSError, ConnectTransportException) as e:
            with self._conn_lock:
                entry = self._conns.pop((node.node_id, lane), None)
            if entry is not None:
                try:
                    entry[0].close()
                except OSError:
                    pass
            self.fail_pending_to(node.node_id, f"send failed: {e}")
            raise NodeNotConnectedException(str(e)) from e

    def close(self) -> None:
        super().close()
        try:
            self._server.close()
        except OSError:
            pass
        with self._conn_lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for s, _lk in conns:
            try:
                s.close()
            except OSError:
                pass


# Default lane per action prefix (ref: each channel type's traffic class)
def lane_for_action(action: str) -> str:
    if action.startswith("internal:index/shard/recovery"):
        return LANE_RECOVERY
    if "data/write" in action or "[bulk" in action:
        return LANE_BULK
    if action.startswith("internal:cluster/coordination") or \
            action.startswith("internal:cluster/publish"):
        return LANE_STATE
    if action.endswith("/ping") or action == HANDSHAKE_ACTION:
        return LANE_PING
    return LANE_REG


class TransportService:
    """The facade every service talks to (ref:
    TransportService.java:521 sendRequest / :177 registerRequestHandler).

    Adds over the raw transport: handshake-validated connections, local
    short-circuit (requests to self dispatch in-process), interceptors,
    timeout sweeping, and a connection listener list for fault detection.
    """

    def __init__(self, transport: BaseTransport,
                 interceptors: Optional[List] = None,
                 timeout_sweep_interval: float = 0.5):
        self.transport = transport
        self.local_node = transport.local_node
        self.telemetry = None
        self._connected: Dict[str, DiscoveryNode] = {}
        self._peer_versions: Dict[str, int] = {}
        self._conn_lock = threading.Lock()
        self._interceptors = list(interceptors or [])
        self._connection_listeners: List[Callable[[DiscoveryNode, str], None]] = []
        self._sweeper_stop = threading.Event()
        self._sweeper = threading.Thread(
            target=self._sweep_loop, args=(timeout_sweep_interval,),
            daemon=True, name=f"timeout-sweep-{self.local_node.name}")
        self.register_request_handler(
            HANDSHAKE_ACTION,
            lambda req, channel, src: channel.send_response(
                {"version": CURRENT_VERSION,
                 "node": self.local_node.to_dict()}),
            can_trip_breaker=False)
        self._sweeper.start()

    # -- lifecycle --------------------------------------------------------

    def close(self) -> None:
        self._sweeper_stop.set()
        self.transport.close()

    def _sweep_loop(self, interval: float) -> None:
        while not self._sweeper_stop.wait(interval):
            self.transport.sweep_timeouts()

    # -- connections ------------------------------------------------------

    def add_connection_listener(
            self, fn: Callable[[DiscoveryNode, str], None]) -> None:
        """fn(node, event) with event in {connected, disconnected}."""
        self._connection_listeners.append(fn)

    def connect_to_node(self, node: DiscoveryNode,
                        timeout: float = 5.0) -> None:
        if node.node_id == self.local_node.node_id:
            return
        with self._conn_lock:
            if node.node_id in self._connected:
                return
        self.transport.connect(node)
        # versioned handshake (ref: TransportHandshaker — connection is
        # usable only after version compatibility is proven)
        result: Dict[str, Any] = {}
        done = threading.Event()

        def on_resp(resp):
            result["resp"] = resp
            done.set()

        def on_fail(exc):
            result["exc"] = exc
            done.set()

        self._do_send(node, HANDSHAKE_ACTION, {},
                      ResponseHandler(on_resp, on_fail), timeout=timeout)
        if not done.wait(timeout):
            raise ConnectTransportException(
                f"handshake with [{node.name}] timed out")
        if "exc" in result:
            raise ConnectTransportException(
                f"handshake with [{node.name}] failed: {result['exc']}")
        their_version = result["resp"].get("version", 0)
        # range check, not equality: peers at or above our minimum
        # compatible version interoperate (each side enforces its own
        # minimum — the newer node knows both formats)
        if their_version < MIN_COMPATIBLE_VERSION:
            raise ConnectTransportException(
                f"[{node.name}] incompatible version [{their_version}] "
                f"< minimum compatible [{MIN_COMPATIBLE_VERSION}]")
        with self._conn_lock:
            self._connected[node.node_id] = node
            # record the NEGOTIATED version (min of both ends): a newer
            # build keys any down-level serialization for this peer on
            # it — without this, accepting older peers at handshake has
            # no mechanism backing it (ref: TcpChannel's per-connection
            # Version from TransportHandshaker)
            self._peer_versions[node.node_id] = min(their_version,
                                                    CURRENT_VERSION)
        for fn in self._connection_listeners:
            fn(node, "connected")

    def negotiated_version(self, node_id: str) -> int:
        """Wire version agreed with a connected peer (CURRENT_VERSION
        when unknown)."""
        with self._conn_lock:
            return self._peer_versions.get(node_id, CURRENT_VERSION)

    def disconnect_from_node(self, node: DiscoveryNode) -> None:
        with self._conn_lock:
            removed = self._connected.pop(node.node_id, None)
            self._peer_versions.pop(node.node_id, None)
        if removed is not None:
            self.transport.fail_pending_to(node.node_id, "disconnected")
            for fn in self._connection_listeners:
                fn(node, "disconnected")

    def node_connected(self, node: DiscoveryNode) -> bool:
        return (node.node_id == self.local_node.node_id
                or node.node_id in self._connected)

    # -- request handling -------------------------------------------------

    def register_request_handler(self, action: str, handler: Callable,
                                 executor: str = "generic",
                                 can_trip_breaker: bool = True) -> None:
        for icpt in self._interceptors:
            wrap = getattr(icpt, "intercept_handler", None)
            if wrap is not None:
                handler = wrap(action, handler)
        self.transport.register_handler(action, handler, executor,
                                        can_trip_breaker)

    def send_request(self, node: DiscoveryNode, action: str, request: Any,
                     handler: ResponseHandler,
                     timeout: Optional[float] = None,
                     headers: Optional[Dict[str, Any]] = None) -> None:
        request, handler = instrument_send(self.telemetry, action,
                                           request, handler, headers)
        sender = self._do_send
        for icpt in reversed(self._interceptors):
            wrap = getattr(icpt, "intercept_sender", None)
            if wrap is not None:
                sender = wrap(sender)
        sender(node, action, request, handler, timeout)

    def _do_send(self, node: DiscoveryNode, action: str, request: Any,
                 handler: ResponseHandler,
                 timeout: Optional[float] = None) -> None:
        # local short-circuit (ref: TransportService.sendLocalRequest)
        request_id = self.transport.new_request_id()
        deadline = (time.monotonic() + timeout) if timeout else None
        self.transport.register_pending(
            request_id, ResponseContext(handler, node, action, deadline))
        if node.node_id == self.local_node.node_id:
            def reply(frame: bytes) -> None:
                rid, status, _v, _a, payload = _decode_frame(frame[6:])
                self.transport._dispatch_response(rid, status, payload)

            self.transport._dispatch_request(
                self.local_node, request_id, action, request, reply)
            return
        try:
            self.transport.send(node, request_id, action, request,
                                lane=lane_for_action(action),
                                wire_version=self.negotiated_version(
                                    node.node_id))
        except BaseException as e:  # noqa: BLE001
            with self.transport._pending_lock:
                ctx = self.transport._pending.pop(request_id, None)
            if ctx is not None:
                handler.on_failure(e)

    def send_request_sync(self, node: DiscoveryNode, action: str,
                          request: Any, timeout: float = 30.0) -> Any:
        """Blocking convenience used by tests and simple callers."""
        done = threading.Event()
        box: Dict[str, Any] = {}

        def ok(resp):
            box["resp"] = resp
            done.set()

        def fail(exc):
            box["exc"] = exc
            done.set()

        self.send_request(node, action, request, ResponseHandler(ok, fail),
                          timeout=timeout)
        if not done.wait(timeout + 1.0):
            raise ReceiveTimeoutTransportException(
                f"[{node.name}][{action}] sync wait timed out")
        if "exc" in box:
            raise box["exc"]
        return box["resp"]


def wire_breaker_service(transport, breaker_service) -> None:
    """Attach a node breaker service to every layer of a (possibly
    wrapped) transport stack — the inbound in_flight_requests charge
    happens at whichever layer dispatches (BaseTransport in production,
    DisruptableTransport under simulation); wrapper layers delegate."""
    seen = set()
    t = transport
    while t is not None and id(t) not in seen:
        seen.add(id(t))
        try:
            t.breaker_service = breaker_service
        except Exception:  # noqa: BLE001 — read-only wrapper layers
            pass
        t = getattr(t, "inner", None) or getattr(t, "transport", None)


def make_inprocess_cluster_registry() -> Dict[str, InProcessTransport]:
    """A fresh shared registry for an in-process node cluster."""
    return {}


def new_node_id() -> str:
    return uuid.uuid4().hex[:20]
