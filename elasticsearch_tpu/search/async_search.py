"""Async search: submit, poll partial results, cancel.

ref: x-pack/plugin/async-search (AsyncSearchTask.java,
MutableSearchResponse.java, built on SearchProgressActionListener):
``POST /{index}/_async_search`` starts the search on a background thread
as a cancellable task; ``GET /_async_search/{id}`` polls; responses carry
``is_running`` / ``is_partial``. ``wait_for_completion_timeout`` (default
1s) lets fast searches complete synchronously — slow ones return an id.

TPU note: with scoring as single dense kernel launches, per-shard partial
results arrive at kernel-completion granularity; the mutable response here
exposes the same shape (total/completed shards) the reference streams.
"""

from __future__ import annotations

import base64
import threading
import time
import uuid
from typing import Any, Callable, Dict, Optional

from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuException,
    ResourceNotFoundException,
)
from elasticsearch_tpu.common.settings import parse_time_value
from elasticsearch_tpu.transport.tasks import TaskCancelledException

DEFAULT_KEEP_ALIVE = 5 * 24 * 3600.0  # 5d, ref: async-search default


class _AsyncSearch:
    def __init__(self, search_id: str, index_expression: str,
                 body: Dict[str, Any], keep_alive: float,
                 clock: Optional[Callable[[], float]] = None):
        self.clock = clock or time.time
        self.id = search_id
        self.index_expression = index_expression
        self.body = body
        self.start_ms = int(self.clock() * 1000)
        self.expires_at = self.clock() + keep_alive
        self.done = threading.Event()
        self.response: Optional[Dict[str, Any]] = None
        self.error: Optional[Dict[str, Any]] = None
        self.error_status = 500
        self.completed_ms: Optional[int] = None
        self.task = None  # CancellableTask once started


class AsyncSearchService:
    def __init__(self, search_service, task_manager,
                 clock: Optional[Callable[[], float]] = None):
        self.search_service = search_service
        self.task_manager = task_manager
        # injectable wall-clock seam (expiry/display epochs) so the
        # deterministic harness can drive keep-alive reaping
        self.clock = clock or time.time
        self._searches: Dict[str, _AsyncSearch] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------- submit
    def submit(self, index_expression: str, body: Dict[str, Any],
               params: Dict[str, str]) -> Dict[str, Any]:
        wait = parse_time_value(
            params.get("wait_for_completion_timeout", "1s"),
            "wait_for_completion_timeout")
        keep_alive = parse_time_value(params.get("keep_alive", "5d"),
                                      "keep_alive")
        search_id = base64.urlsafe_b64encode(
            uuid.uuid4().bytes).decode().rstrip("=")
        search = _AsyncSearch(search_id, index_expression, body or {},
                              keep_alive, clock=self.clock)
        task = self.task_manager.register(
            "transport", "indices:data/read/async_search/submit",
            description=f"async_search indices[{index_expression}]",
            cancellable=True)
        search.task = task
        with self._lock:
            self._reap_locked()
            self._searches[search_id] = search

        def run():
            try:
                search.response = self.search_service.search(
                    index_expression, search.body, task=task)
            except TaskCancelledException:
                search.error = {"type": "task_cancelled_exception",
                                "reason": "async search was cancelled"}
                search.error_status = 400
            except ElasticsearchTpuException as e:
                search.error = e.to_xcontent()
                search.error_status = e.status
            except Exception as e:  # pragma: no cover - defensive
                search.error = {"type": "exception", "reason": str(e)}
            finally:
                search.completed_ms = int(self.clock() * 1000)
                self.task_manager.unregister(task)
                search.done.set()

        threading.Thread(target=run, daemon=True,
                         name=f"async_search-{search_id[:8]}").start()
        search.done.wait(timeout=wait)
        return self._render(search)

    # ---------------------------------------------------------------- get
    def get(self, search_id: str,
            params: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
        params = params or {}
        search = self._lookup(search_id)
        if "keep_alive" in params:
            search.expires_at = self.clock() + parse_time_value(
                params["keep_alive"], "keep_alive")
        if "wait_for_completion_timeout" in params:
            search.done.wait(timeout=parse_time_value(
                params["wait_for_completion_timeout"],
                "wait_for_completion_timeout"))
        return self._render(search)

    def delete(self, search_id: str) -> None:
        search = self._lookup(search_id)
        if search.task is not None and not search.done.is_set():
            self.task_manager.cancel(search.task, "async search deleted")
        with self._lock:
            self._searches.pop(search_id, None)

    def _lookup(self, search_id: str) -> _AsyncSearch:
        with self._lock:
            self._reap_locked()
            search = self._searches.get(search_id)
        if search is None:
            raise ResourceNotFoundException(search_id)
        return search

    def _reap_locked(self):
        """Caller holds the lock. Expired entries are removed; any whose
        search is still running is cancelled so it cannot burn CPU as an
        unaddressable orphan."""
        now = self.clock()
        expired = [a for a in self._searches.values()
                   if a.expires_at < now]
        for a in expired:
            del self._searches[a.id]
        for a in expired:
            if a.task is not None and not a.done.is_set():
                self.task_manager.cancel(a.task, "async search expired")

    # ------------------------------------------------------------- render
    def _render(self, search: _AsyncSearch) -> Dict[str, Any]:
        running = not search.done.is_set()
        out: Dict[str, Any] = {
            "id": search.id,
            "is_partial": running or search.error is not None,
            "is_running": running,
            "start_time_in_millis": search.start_ms,
            "expiration_time_in_millis": int(search.expires_at * 1000),
        }
        if search.error is not None:
            out["error"] = search.error
            # REST handlers surface the stored failure status (ES returns
            # the failure's own status, not 200)
            out["_http_status"] = search.error_status
        elif search.response is not None:
            out["response"] = search.response
            out["completion_time_in_millis"] = (
                search.completed_ms or int(self.clock() * 1000))
        else:
            # still running: the skeleton partial response
            out["response"] = {
                "took": int(self.clock() * 1000) - search.start_ms,
                "timed_out": False,
                "hits": {"total": {"value": 0, "relation": "gte"},
                         "max_score": None, "hits": []},
            }
        return out
