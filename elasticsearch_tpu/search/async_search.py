"""Async search: submit, poll partial results, cancel.

ref: x-pack/plugin/async-search (AsyncSearchTask.java,
MutableSearchResponse.java, built on SearchProgressActionListener):
``POST /{index}/_async_search`` starts the search on a background thread
as a cancellable task; ``GET /_async_search/{id}`` polls; responses carry
``is_running`` / ``is_partial``. ``wait_for_completion_timeout`` (default
1s) lets fast searches complete synchronously — slow ones return an id.

TPU note: with scoring as single dense kernel launches, per-shard partial
results arrive at kernel-completion granularity; the mutable response here
exposes the same shape (total/completed shards) the reference streams.
"""

from __future__ import annotations

import base64
import threading
import time
import uuid
from typing import Any, Callable, Dict, Optional

from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuException,
    ResourceNotFoundException,
)
from elasticsearch_tpu.common.settings import parse_time_value
from elasticsearch_tpu.transport.tasks import TaskCancelledException

DEFAULT_KEEP_ALIVE = 5 * 24 * 3600.0  # 5d, ref: async-search default


class _AsyncSearch:
    def __init__(self, search_id: str, index_expression: str,
                 body: Dict[str, Any], keep_alive: float,
                 clock: Optional[Callable[[], float]] = None,
                 tenant: Optional[str] = None,
                 wclass: Optional[str] = None):
        self.clock = clock or time.time
        self.id = search_id
        self.index_expression = index_expression
        self.body = body
        # the submitter's attribution, re-entered by the background run
        # and stamped on every status render — long-running work stays
        # attributable after the submitting request returns
        self.tenant = tenant
        self.wclass = wclass
        self.start_ms = int(self.clock() * 1000)
        self.expires_at = self.clock() + keep_alive
        self.done = threading.Event()
        self.response: Optional[Dict[str, Any]] = None
        self.error: Optional[Dict[str, Any]] = None
        self.error_status = 500
        self.completed_ms: Optional[int] = None
        self.task = None  # CancellableTask once started


class AsyncSearchService:
    def __init__(self, search_service, task_manager,
                 clock: Optional[Callable[[], float]] = None):
        self.search_service = search_service
        self.task_manager = task_manager
        # injectable wall-clock seam (expiry/display epochs) so the
        # deterministic harness can drive keep-alive reaping
        self.clock = clock or time.time
        self._searches: Dict[str, _AsyncSearch] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------- submit
    def submit(self, index_expression: str, body: Dict[str, Any],
               params: Dict[str, str]) -> Dict[str, Any]:
        wait = parse_time_value(
            params.get("wait_for_completion_timeout", "1s"),
            "wait_for_completion_timeout")
        keep_alive = parse_time_value(params.get("keep_alive", "5d"),
                                      "keep_alive")
        search_id = base64.urlsafe_b64encode(
            uuid.uuid4().bytes).decode().rstrip("=")
        from elasticsearch_tpu.telemetry import context as _telectx
        search = _AsyncSearch(
            search_id, index_expression, body or {}, keep_alive,
            clock=self.clock,
            # capture BEFORE the thread boundary: TLS does not cross it
            tenant=_telectx.current_tenant(),
            wclass=_telectx.current_workload_class() or "async")
        task = self.task_manager.register(
            "transport", "indices:data/read/async_search/submit",
            description=f"async_search indices[{index_expression}]",
            cancellable=True)
        search.task = task
        with self._lock:
            self._reap_locked()
            self._searches[search_id] = search

        def run():
            try:
                # re-enter the submitter's attribution on the worker
                # thread (fresh TLS)
                with _telectx.activate_tenant(search.tenant), \
                        _telectx.activate_workload_class(search.wclass):
                    search.response = self.search_service.search(
                        index_expression, search.body, task=task)
            except TaskCancelledException:
                search.error = {"type": "task_cancelled_exception",
                                "reason": "async search was cancelled"}
                search.error_status = 400
            except ElasticsearchTpuException as e:
                search.error = e.to_xcontent()
                search.error_status = e.status
            except Exception as e:  # pragma: no cover - defensive
                search.error = {"type": "exception", "reason": str(e)}
            finally:
                search.completed_ms = int(self.clock() * 1000)
                self.task_manager.unregister(task)
                search.done.set()

        threading.Thread(target=run, daemon=True,
                         name=f"async_search-{search_id[:8]}").start()
        search.done.wait(timeout=wait)
        return self._render(search)

    # ---------------------------------------------------------------- get
    def get(self, search_id: str,
            params: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
        params = params or {}
        search = self._lookup(search_id)
        if "keep_alive" in params:
            search.expires_at = self.clock() + parse_time_value(
                params["keep_alive"], "keep_alive")
        if "wait_for_completion_timeout" in params:
            search.done.wait(timeout=parse_time_value(
                params["wait_for_completion_timeout"],
                "wait_for_completion_timeout"))
        return self._render(search)

    def delete(self, search_id: str) -> None:
        search = self._lookup(search_id)
        if search.task is not None and not search.done.is_set():
            self.task_manager.cancel(search.task, "async search deleted")
        with self._lock:
            self._searches.pop(search_id, None)

    def _lookup(self, search_id: str) -> _AsyncSearch:
        with self._lock:
            self._reap_locked()
            search = self._searches.get(search_id)
        if search is None:
            raise ResourceNotFoundException(search_id)
        return search

    def _reap_locked(self):
        """Caller holds the lock. Expired entries are removed; any whose
        search is still running is cancelled so it cannot burn CPU as an
        unaddressable orphan."""
        now = self.clock()
        expired = [a for a in self._searches.values()
                   if a.expires_at < now]
        for a in expired:
            del self._searches[a.id]
        for a in expired:
            if a.task is not None and not a.done.is_set():
                self.task_manager.cancel(a.task, "async search expired")

    # ------------------------------------------------------------- render
    def _render(self, search: _AsyncSearch) -> Dict[str, Any]:
        running = not search.done.is_set()
        out: Dict[str, Any] = {
            "id": search.id,
            "is_partial": running or search.error is not None,
            "is_running": running,
            "start_time_in_millis": search.start_ms,
            "expiration_time_in_millis": int(search.expires_at * 1000),
        }
        if search.tenant is not None:
            out["tenant"] = search.tenant
        if search.wclass is not None:
            out["search.class"] = search.wclass
        if search.error is not None:
            out["error"] = search.error
            # REST handlers surface the stored failure status (ES returns
            # the failure's own status, not 200)
            out["_http_status"] = search.error_status
        elif search.response is not None:
            out["response"] = search.response
            out["completion_time_in_millis"] = (
                search.completed_ms or int(self.clock() * 1000))
        else:
            # still running: the skeleton partial response
            out["response"] = {
                "took": int(self.clock() * 1000) - search.start_ms,
                "timed_out": False,
                "hits": {"total": {"value": 0, "relation": "gte"},
                         "max_score": None, "hits": []},
            }
        return out


# --------------------------------------------------------------- cluster

ASYNC_SUBMIT_ACTION = "indices:data/read/async_search/submit"
ASYNC_GET_ACTION = "indices:data/read/async_search[get]"
ASYNC_DELETE_ACTION = "indices:data/read/async_search[delete]"


class ClusterAsyncSearchService:
    """Cluster-aware async search (ref: x-pack async-search +
    AsyncExecutionId): the search id ENCODES the submitting node, so
    get/status/delete issued against ANY node route to the owner over
    the transport. The submit runs the distributed search fan-out as a
    PR-5 cancellable parent task (`GET /_tasks`-visible; a cancel from
    any node reaches it by task id and bans its per-shard children),
    and a mid-flight copy failure folds into the PR-1 typed
    partial-results protocol instead of killing the search.

    Everything runs on the SCHEDULER clock and callback style — no
    threads, no wall time — so seeded chaos runs replay byte-identical.
    """

    def __init__(self, transport, scheduler, task_manager,
                 search_fn, state_fn,
                 cancel_local: Optional[Callable] = None,
                 on_cancelled_parent_done: Optional[Callable] = None):
        from elasticsearch_tpu.transport.transport import ResponseHandler
        self.transport = transport
        self.scheduler = scheduler
        self.task_manager = task_manager
        # search_fn(index, body, on_done, task=) → the distributed
        # coordinator under the caller-owned task
        self.search_fn = search_fn
        self.state_fn = state_fn
        # ClusterNode._cancel_local: ban-broadcast-then-cancel, so a
        # delete kills the fan-out's children on every node
        self.cancel_local = cancel_local
        self.on_cancelled_parent_done = on_cancelled_parent_done
        self._rh = ResponseHandler
        self._searches: Dict[str, Dict[str, Any]] = {}
        self._seq = 0
        transport.register_request_handler(ASYNC_GET_ACTION,
                                           self._on_get)
        transport.register_request_handler(ASYNC_DELETE_ACTION,
                                           self._on_delete)

    # ------------------------------------------------------------- submit

    def submit(self, index_expression: str, body: Dict[str, Any],
               params: Optional[Dict[str, str]],
               on_done: Callable) -> None:
        from elasticsearch_tpu.transport.tasks import (
            TaskId, encode_node_scoped_id)
        params = params or {}
        try:
            wait = parse_time_value(
                params.get("wait_for_completion_timeout", "1s"),
                "wait_for_completion_timeout")
            keep_alive = parse_time_value(
                params.get("keep_alive", "5d"), "keep_alive")
        except Exception as e:  # noqa: BLE001 — typed parse error
            on_done(None, e)
            return
        self._reap()
        self._seq += 1
        node_id = self.transport.local_node.node_id
        search_id = encode_node_scoped_id(node_id, self._seq)
        now = self.scheduler.now()
        task = self.task_manager.register(
            "transport", ASYNC_SUBMIT_ACTION,
            description=f"async_search indices[{index_expression}]",
            cancellable=True)
        from elasticsearch_tpu.telemetry import context as _telectx
        rec: Dict[str, Any] = {
            "id": search_id, "index": index_expression,
            "start": now, "keep_alive": keep_alive,
            "expires_at": now + keep_alive,
            "running": True, "response": None,
            "error": None, "error_status": 500,
            "completed_at": None, "task": task,
            "waiters": [],
            # submitter attribution: stamped on every status render and
            # re-entered by the fan-out below
            "tenant": _telectx.current_tenant(),
            "wclass": _telectx.current_workload_class() or "async",
        }
        self._searches[search_id] = rec
        responded = {"done": False}

        def respond():
            if responded["done"]:
                return
            responded["done"] = True
            on_done(self._render(rec), None)

        def search_done(resp, err):
            rec["running"] = False
            rec["completed_at"] = self.scheduler.now()
            if err is not None:
                rec["error"] = (
                    err.to_xcontent()
                    if isinstance(err, ElasticsearchTpuException)
                    else {"type": "exception", "reason": str(err)})
                rec["error_status"] = getattr(err, "status", 500)
            else:
                rec["response"] = resp
            was_cancelled = getattr(task, "is_cancelled",
                                    lambda: False)()
            self.task_manager.unregister(task)
            rec["task"] = None
            if was_cancelled and \
                    self.on_cancelled_parent_done is not None:
                # sweep the cancel's ban markers off the cluster one
                # beat later (same deferral as the search coordinator)
                tid = TaskId(node_id, task.id)
                self.scheduler.schedule(
                    1.0, lambda: self.on_cancelled_parent_done(tid),
                    f"sweep task bans [{tid}]")
            respond()
            for w in rec.pop("waiters", []):
                w()
            rec["waiters"] = []

        self.scheduler.schedule(max(wait, 0.0), respond,
                                f"async_search wait [{search_id}]")
        with _telectx.activate_tenant(rec["tenant"]), \
                _telectx.activate_workload_class(rec["wclass"]):
            self.search_fn(index_expression, body or {}, search_done,
                           task=task)

    # ---------------------------------------------------------- get/delete

    def get(self, search_id: str, params: Optional[Dict[str, str]],
            on_done: Callable) -> None:
        self._route(search_id, ASYNC_GET_ACTION,
                    {"id": search_id, "params": params or {}},
                    lambda: self._get_local(search_id, params, on_done),
                    on_done)

    def delete(self, search_id: str, on_done: Callable) -> None:
        self._route(search_id, ASYNC_DELETE_ACTION, {"id": search_id},
                    lambda: self._delete_local(search_id, on_done),
                    on_done)

    def _route(self, search_id: str, action: str, payload: Dict,
               local: Callable, on_done: Callable) -> None:
        """Resolve the owner from the id: serve locally or forward."""
        from elasticsearch_tpu.transport.tasks import (
            decode_node_scoped_id)
        try:
            owner_id = decode_node_scoped_id(search_id).node_id
        except ResourceNotFoundException as e:
            on_done(None, e)
            return
        if owner_id == self.transport.local_node.node_id:
            local()
            return
        owner = self.state_fn().nodes.get(owner_id)
        if owner is None:
            on_done(None, ResourceNotFoundException(search_id))
            return
        self.transport.send_request(
            owner, action, payload,
            self._rh(lambda r: on_done(r, None),
                     lambda e: on_done(None, e)),
            timeout=30.0)

    def _on_get(self, req, channel, src) -> None:
        self._get_local(req["id"], req.get("params"),
                        self._channel_done(channel))

    def _on_delete(self, req, channel, src) -> None:
        self._delete_local(req["id"], self._channel_done(channel))

    @staticmethod
    def _channel_done(channel):
        def done(resp, err):
            if err is not None:
                channel.send_exception(
                    err if isinstance(err, BaseException)
                    else RuntimeError(str(err)))
            else:
                channel.send_response(resp)
        return done

    def _get_local(self, search_id: str,
                   params: Optional[Dict[str, str]],
                   on_done: Callable) -> None:
        params = params or {}
        self._reap()
        rec = self._searches.get(search_id)
        if rec is None:
            on_done(None, ResourceNotFoundException(search_id))
            return
        try:
            if "keep_alive" in params:
                rec["keep_alive"] = parse_time_value(
                    params["keep_alive"], "keep_alive")
                rec["expires_at"] = (self.scheduler.now()
                                     + rec["keep_alive"])
            wait = (parse_time_value(
                params["wait_for_completion_timeout"],
                "wait_for_completion_timeout")
                if "wait_for_completion_timeout" in params else None)
        except Exception as e:  # noqa: BLE001 — typed parse error
            on_done(None, e)
            return
        if not rec["running"] or wait is None:
            on_done(self._render(rec), None)
            return
        responded = {"done": False}

        def respond():
            if responded["done"]:
                return
            responded["done"] = True
            on_done(self._render(rec), None)

        rec["waiters"].append(respond)
        self.scheduler.schedule(max(wait, 0.0), respond,
                                f"async_search get wait [{search_id}]")

    def _delete_local(self, search_id: str, on_done: Callable) -> None:
        from elasticsearch_tpu.transport.tasks import TaskId
        self._reap()
        rec = self._searches.pop(search_id, None)
        if rec is None:
            on_done(None, ResourceNotFoundException(search_id))
            return
        task = rec.get("task")
        if rec["running"] and task is not None \
                and self.cancel_local is not None:
            # ban-broadcast-then-cancel: the fan-out's children on every
            # node die with the parent (visible in `GET /_tasks` until
            # then); the search completes typed-cancelled and releases
            # its own resources through its normal completion seam
            self.cancel_local(
                TaskId(self.transport.local_node.node_id, task.id),
                "async search deleted",
                lambda r, e: on_done({"acknowledged": True}, None))
            return
        on_done({"acknowledged": True}, None)

    # ----------------------------------------------------------- internals

    def _reap(self) -> None:
        """Lazy keep-alive expiry on the scheduler clock (no periodic
        task — seeded interleavings stay undisturbed); a still-running
        expired search is cancelled, never orphaned."""
        from elasticsearch_tpu.transport.tasks import TaskId
        now = self.scheduler.now()
        expired = [sid for sid, r in self._searches.items()
                   if r["expires_at"] <= now]
        for sid in expired:
            rec = self._searches.pop(sid)
            task = rec.get("task")
            if rec["running"] and task is not None \
                    and self.cancel_local is not None:
                self.cancel_local(
                    TaskId(self.transport.local_node.node_id, task.id),
                    "async search expired", lambda r, e: None)

    def open_async_search_count(self) -> int:
        return len(self._searches)

    def _render(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        from elasticsearch_tpu.transport.tasks import TaskId
        running = rec["running"]
        now_ms = int(self.scheduler.now() * 1000)
        out: Dict[str, Any] = {
            "id": rec["id"],
            "is_partial": running or rec["error"] is not None,
            "is_running": running,
            "start_time_in_millis": int(rec["start"] * 1000),
            "expiration_time_in_millis": int(rec["expires_at"] * 1000),
        }
        if rec.get("tenant") is not None:
            out["tenant"] = rec["tenant"]
        if rec.get("wclass") is not None:
            out["search.class"] = rec["wclass"]
        if running and rec.get("task") is not None:
            # the `GET /_tasks`-addressable handle for the fan-out
            out["task"] = str(TaskId(
                self.transport.local_node.node_id, rec["task"].id))
        if rec["error"] is not None:
            out["error"] = rec["error"]
            out["_http_status"] = rec["error_status"]
        elif rec["response"] is not None:
            out["response"] = rec["response"]
            if rec["response"].get("_shards", {}).get("failed", 0):
                # copy failures folded into typed partial results
                out["is_partial"] = True
            out["completion_time_in_millis"] = int(
                (rec["completed_at"] or self.scheduler.now()) * 1000)
        else:
            out["response"] = {
                "took": now_ms - int(rec["start"] * 1000),
                "timed_out": False,
                "hits": {"total": {"value": 0, "relation": "gte"},
                         "max_score": None, "hits": []},
            }
        return out
