"""Percolator: reverse search (ref: modules/percolator —
PercolateQueryBuilder / PercolatorFieldMapper). Queries are indexed as
documents (a ``percolator``-typed field holds the query DSL in _source);
the ``percolate`` query takes candidate document(s), builds an in-memory
one-segment index of them (the MemoryIndex analogue), and matches each
stored query against it.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    QueryShardException,
)
from elasticsearch_tpu.index.mapper import PercolatorFieldType
from elasticsearch_tpu.index.segment import SegmentWriter
from elasticsearch_tpu.search.queries import QueryBuilder, parse_query


class _SandboxMapperService:
    """Parse-only MapperService view with its own fields dict: dynamic
    mappings introduced by candidate docs stay here, never touching the
    live index mapping."""

    def __init__(self, base):
        import copy
        self.analysis = base.analysis
        self.mapper = copy.copy(base.mapper)
        self.mapper.fields = dict(base.mapper.fields)

    def field_type(self, name):
        return self.mapper.fields.get(name)

    def field_names(self):
        return sorted(self.mapper.fields)

    def parse(self, doc_id, source):
        return self.mapper.parse(doc_id, source)


class PercolateQuery(QueryBuilder):
    """ref: PercolateQueryBuilder — `field` names the percolator field;
    `document`/`documents` inline the candidate docs (doc references by
    index/id are resolved by the search service before parsing)."""

    name = "percolate"

    def __init__(self, field: str,
                 documents: Optional[List[Dict[str, Any]]] = None):
        super().__init__()
        self.field = field
        self.documents = documents or []
        # _id of matched stored-query doc -> list of matched doc slots
        self.matched_slots: Dict[str, List[int]] = {}
        self._mini = None

    def rewrite(self, searcher) -> "PercolateQuery":
        if not self.documents:
            raise IllegalArgumentException(
                "[percolate] query requires [document] or [documents]")
        if self._mini is not None:
            return self  # candidates don't change within a request
        # the candidate docs are parsed with a SANDBOXED copy of the
        # percolator index's mappings (ref: percolator parses candidates
        # against the index mappings via a throwaway MemoryIndex) — a
        # search must never mutate the live index mapping via dynamic
        # field introduction
        from elasticsearch_tpu.search.searcher import ShardSearcher
        sandbox = _SandboxMapperService(searcher.mapper)
        writer = SegmentWriter()
        for slot, doc in enumerate(self.documents):
            writer.add(sandbox.parse(f"_slot_{slot}", doc))
        seg = writer.build("_percolate_candidates")
        self._mini = ShardSearcher([seg], sandbox)
        return self

    def do_execute(self, ctx):
        if self._mini is None:
            raise QueryShardException("[percolate] query was not rewritten")
        seg = ctx.segment
        m = np.zeros(ctx.n_docs_padded, bool)
        n_slots = len(self.documents)
        for docid in range(seg.n_docs):
            if not seg.live[docid]:
                continue
            source = json.loads(seg.stored.source(docid))
            spec = _field_path(source, self.field)
            if not isinstance(spec, dict):
                continue
            try:
                stored_q = parse_query(spec)
            except Exception:
                continue
            result = self._mini.query_phase(stored_q, n_slots,
                                            track_total_hits=True)
            if result.total_hits > 0:
                m[docid] = True
                slots = sorted(int(d.docid) for d in result.docs)
                self.matched_slots[seg.stored.ids[docid]] = slots
        mask = jnp.asarray(m)
        return mask.astype(jnp.float32), mask

    # hit decoration: _percolator_document_slot (ref: PercolateQuery adds
    # the slot field to each matched query hit)
    def add_hit_fields(self, hit: Dict[str, Any]) -> None:
        slots = self.matched_slots.get(hit.get("_id"))
        if slots is not None:
            hit.setdefault("fields", {})["_percolator_document_slot"] = slots


def resolve_percolate_refs(query_spec: Any, indices_service) -> Any:
    """Replace {"percolate": {..., "index": i, "id": d}} document
    references with the fetched _source (ref: PercolateQueryBuilder's
    coordinator rewrite fetches the doc via GetRequest)."""
    if isinstance(query_spec, list):
        return [resolve_percolate_refs(x, indices_service) for x in query_spec]
    if not isinstance(query_spec, dict):
        return query_spec
    out = {}
    for k, v in query_spec.items():
        if k == "percolate" and isinstance(v, dict) and "index" in v and "id" in v:
            idx = indices_service.get(v["index"])
            got = idx.get_doc(str(v["id"]), routing=v.get("routing"))
            if not got.found:
                raise IllegalArgumentException(
                    f"percolate document [{v['index']}/{v['id']}] not found")
            v = {key: val for key, val in v.items()
                 if key not in ("index", "id", "routing", "preference")}
            v["document"] = got.source
            out[k] = v
        else:
            out[k] = resolve_percolate_refs(v, indices_service)
    return out


def _field_path(source: Dict[str, Any], path: str) -> Any:
    cur: Any = source
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def parse_percolate(spec: Dict[str, Any]) -> PercolateQuery:
    field = spec.get("field")
    if not field:
        raise IllegalArgumentException("[percolate] requires [field]")
    docs = spec.get("documents")
    if docs is None and spec.get("document") is not None:
        docs = [spec["document"]]
    return PercolateQuery(field, docs)
