"""Query profiling: per-stage timing for `profile: true`.

The reference profiles per-Weight/Scorer timing types through
QueryProfiler trees (ref: search/profile/query/QueryProfiler.java:38,
QueryProfileBreakdown). This engine's execution shape is different —
one fused device launch instead of per-doc scorer calls — so the
breakdown reports the stages that actually exist here, split into HOST
and DEVICE time:

  rewrite   — query tree rewriting (host)
  compile   — logical-plan compilation / cache lookup (host)
  bind      — selection building + bucket padding (host)
  launch    — kernel dispatch + device execution wait (device)
  readback  — device→host transfer of the top-k rows (device↔host)
  score     — dense-path column scoring (device, fallback path)
  topk      — dense-path masked top-k (device, fallback path)
  merge     — cross-segment merge (host)

A threadlocal recorder keeps instrumentation out of every call
signature; it is active only under `profiling()`, so the serving hot
path pays one `is-None` check per stage.

Two consumers share the recorder seam:

- ``profiling()`` (the per-request ``profile: true`` dict), and
- ``stage_sink(fn)`` — a persistent sink the telemetry subsystem
  installs so stage timings accumulate into node-level histograms
  (``search.stage.launch`` etc.) on EVERY search, not only profiled
  ones (telemetry/__init__.py ``Telemetry.stage_sink``).

Both are temporal thread-local contexts; telemetry/context.py
``bind()`` carries them (plus the trace context) across scheduler task
boundaries so a multi-node search keeps its shard-side stages.

The stage seam doubles as the engine's cancellation poll point: a
caller that owns a CancellableTask installs its ``ensure_not_cancelled``
via ``cancellable()``, and every ``span(stage)`` entry — i.e. every
device-launch boundary of a multi-segment scan — polls it. A cancelled
search aborts between launches instead of after the full scan, without
the kernels themselves knowing tasks exist.

Per-request profiling (``profile: true``) extends the flat stage dict
with three structured channels, all allocated ONLY while a recorder is
installed (the profile-off hot path still pays one is-None check):

- ``record_device(attrs)`` — one attribution record per device launch
  (kernel name, lane×nb bucket, cohort width, batcher wait, padding
  waste, readback bytes/ms — stamped by search/batching.py and the
  searcher launch sites);
- ``note_kernel(kernel, kind, ms)`` — stamped by ``tracked_jit``
  (telemetry/engine.py) on every tracked entry-point call under the
  recorder: ``kind`` classifies the launch as ``compile`` (cold XLA
  compile), ``cache_hit`` (warm load via the persistent compile
  cache), or ``cached`` (jit-cache reuse);
- dotted stage names (``aggs.collect`` …) — structured child scopes
  that ``shard_profile_tree`` groups under their parent stage.

The recorder's clock is injectable (``profiling(clock=...)``): the
distributed data-node handler passes the scheduler clock, so a
chaos-seeded run under DeterministicTaskQueue reports replay-identical
profile trees (virtual time), while production reads monotonic nanos.

``stage_hook(cb)`` installs a per-span callback (``cb(stage)``) that
the task layer uses to publish a task's CURRENT profile stage
(``GET /_tasks?detailed=true``, hot_threads) — same one-getattr cost
model as the cancellation hook.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

_tls = threading.local()


def active() -> bool:
    return getattr(_tls, "rec", None) is not None \
        or getattr(_tls, "sink", None) is not None


def recording() -> bool:
    """True only under ``profiling()`` — the guard for per-request
    attribution records (device records / kernel notes), which are
    never allocated for sink-only (metrics histogram) collection."""
    return getattr(_tls, "rec", None) is not None


def now_ns() -> int:
    """Nanos on the recorder's clock (injectable for replay-identical
    trees under the deterministic harness; monotonic otherwise)."""
    clk = getattr(_tls, "clock", None)
    return clk() if clk is not None else time.monotonic_ns()


@contextmanager
def profiling(clock=None):
    """Activate collection; yields the stage dict (stage → nanos).

    ``clock`` (optional zero-arg → nanos) pins span timing to an
    injectable clock — the distributed path passes virtual scheduler
    time so seeded runs produce identical trees."""
    rec: Dict[str, Any] = {}
    prev = getattr(_tls, "rec", None)
    prev_clock = getattr(_tls, "clock", None)
    _tls.rec = rec
    if clock is not None:
        _tls.clock = clock
    try:
        yield rec
    finally:
        _tls.rec = prev
        _tls.clock = prev_clock


@contextmanager
def stage_sink(fn):
    """Install a stage sink ``fn(stage, nanos)`` for the duration;
    stacks with (and is independent of) an active ``profiling()``."""
    prev = getattr(_tls, "sink", None)
    _tls.sink = fn
    try:
        yield
    finally:
        _tls.sink = prev


def record(stage: str, nanos: int) -> None:
    rec = getattr(_tls, "rec", None)
    if rec is not None:
        rec[stage] = rec.get(stage, 0) + nanos
    sink = getattr(_tls, "sink", None)
    if sink is not None:
        sink(stage, nanos)


def note(key: str, value) -> None:
    """Non-timing annotation (e.g. collector name)."""
    rec = getattr(_tls, "rec", None)
    if rec is not None:
        rec.setdefault("_notes", {})[key] = value   # type: ignore


def add(key: str, n: float) -> None:
    """Accumulate a numeric counter (e.g. readback bytes) into the
    per-request record; no-op (no allocation) when not recording."""
    rec = getattr(_tls, "rec", None)
    if rec is not None:
        counters = rec.setdefault("_counters", {})   # type: ignore
        counters[key] = counters.get(key, 0) + n


def record_readback(t0_ns: int, *arrays) -> None:
    """Attribute one device→host readback to the active recorder:
    bytes of the materialized arrays + elapsed ms since ``t0_ns`` (a
    ``now_ns()`` stamp taken before the transfer). The one helper both
    searcher readback sites share."""
    add("readback_bytes", sum(a.nbytes for a in arrays))
    add("readback_ms", round((now_ns() - t0_ns) / 1e6, 3))


def record_device(attrs: Dict[str, Any]) -> None:
    """Append one device-launch attribution record (kernel name, lane/
    nb bucket, cohort width, batch wait, padding waste, readback
    bytes/ms, cache-hit flag); no-op when not recording."""
    rec = getattr(_tls, "rec", None)
    if rec is not None:
        rec.setdefault("_device", []).append(attrs)   # type: ignore


def note_kernel(kernel: str, kind: str, ms: float) -> None:
    """Record one tracked-jit entry-point call under the active
    recorder: ``kind`` is ``compile`` / ``cache_hit`` (persistent-cache
    warm load) / ``cached`` (jit-cache reuse). Called by
    telemetry/engine.py's ``tracked_jit`` wrapper — the seam that gives
    every profiled request its kernel-name attribution.

    Aggregated by (kernel, kind): a query scanning many segments makes
    the same warm call per segment, and per-call rows would grow the
    profile linearly with segment count for zero extra information —
    the tree renders one row per (kernel, kind) with a call count and
    summed ms."""
    rec = getattr(_tls, "rec", None)
    if rec is not None:
        kernels = rec.setdefault("_kernels", {})   # type: ignore
        slot = kernels.get((kernel, kind))
        if slot is None:
            kernels[(kernel, kind)] = [1, float(ms)]
        else:
            slot[0] += 1
            slot[1] += float(ms)


@contextmanager
def cancellable(check):
    """Install a cancellation poll ``check()`` (typically a task's
    ``ensure_not_cancelled``) for the duration; ``span()`` entries —
    the device-launch boundaries — call it. telemetry/context.bind()
    carries it across scheduler task boundaries."""
    prev = getattr(_tls, "cancel", None)
    _tls.cancel = check
    try:
        yield
    finally:
        _tls.cancel = prev


def check_cancelled() -> None:
    """Poll the installed cancellation hook (raises TaskCancelledException
    through the task's ``ensure_not_cancelled``); no-op when none is
    installed — one getattr on the hot path."""
    cb = getattr(_tls, "cancel", None)
    if cb is not None:
        cb()


@contextmanager
def stage_hook(cb):
    """Install a per-span stage callback ``cb(stage)`` — the task layer
    publishes the task's current profile stage through it so
    ``GET /_tasks?detailed=true`` and hot_threads show WHERE a
    long-running search is, not just how long it has run."""
    prev = getattr(_tls, "stage_cb", None)
    _tls.stage_cb = cb
    try:
        yield
    finally:
        _tls.stage_cb = prev


@contextmanager
def span(stage: str):
    check_cancelled()
    cb = getattr(_tls, "stage_cb", None)
    if cb is not None:
        cb(stage)
    if not active():
        yield
        return
    clk = getattr(_tls, "clock", None)
    if clk is None:
        clk = time.monotonic_ns
    t0 = clk()
    try:
        yield
    finally:
        record(stage, clk() - t0)


DEVICE_STAGES = ("launch", "readback", "score", "topk")
HOST_STAGES = ("rewrite", "compile", "bind", "merge")

# ---------------------------------------------------------------------------
# Kernel → profile attribution registry.
#
# Every `tracked_jit` entry point in ops/ MUST have a row here — the
# KEY SET is the wiring contract: a kernel added without a row fails
# the static analyzer (ESTPU-JIT03, elasticsearch_tpu/lint) on every
# tier-1 run, forcing the author to decide (and document) which
# profile stage its launches are timed under. The VALUE documents that
# stage — it must name a real stage (tests/test_profile_api.py
# validates it) but is not consulted at run time; the actual timing
# comes from the `span()` call site wrapping the launch.
# ---------------------------------------------------------------------------

KERNEL_ATTRIBUTION: Dict[str, str] = {
    # ops/plan.py — the fused plan executor family
    "plan_topk": "launch",
    "plan_topk_packed": "launch",
    "plan_topk_batch": "launch",
    "plan_topk_mesh": "launch",
    "bm25_dense_scores_sorted": "launch",
    "match_count_sorted": "score",
    "match_mask_sorted": "score",
    # ops/topk.py
    "topk": "topk",
    "approx_topk": "topk",
    "masked_topk": "topk",
    "merge_topk": "merge",
    # ops/aggs.py
    "terms_counts": "aggs.collect",
    "agg_metric_stats": "aggs.collect",
    "agg_bucket_counts": "aggs.collect",
    "agg_bucket_metrics": "aggs.collect",
    # ops/fastpath.py — the native serving front's batched kernels
    "bm25_topk_total_batch": "launch",
    "bm25_essential_topk_batch": "launch",
    "bm25_essential_dense_topk_batch": "launch",
    "bm25_topk_total_merge_batch": "launch",
    "bm25_candidates_rerank_batch": "launch",
    # ops/vector.py
    "dot_scores": "score",
    "cosine_scores": "score",
    "l2_scores": "score",
    "knn_nominate_batch": "launch",
    # ops/pallas_bm25.py
    "bm25_contrib_pallas": "launch",
    # parallel/mesh_executor.py — mesh kNN SPMD programs
    "mesh_knn_nominate": "launch",
    "mesh_knn_step": "launch",
}


# ---------------------------------------------------------------------------
# ES-shaped shard profile tree — ONE builder shared by the single-node
# SearchService and the distributed data-node handler, so the per-shard
# response shape cannot drift between the two paths (ref:
# search/profile/SearchProfileResults — per-shard query/collector/
# aggregation breakdowns merged at the coordinator).
# ---------------------------------------------------------------------------

def shard_profile_tree(shard_id: str, body: Optional[Dict[str, Any]],
                       rec: Dict[str, Any], total_ns: int,
                       collected_ns: Optional[int] = None
                       ) -> Dict[str, Any]:
    """Build one shard's ES-shaped profile entry from a finished
    recorder dict.

    ``rec`` is consumed: structured channels (`_notes`, `_device`,
    `_kernels`, `_counters`) pop out of the flat stage dict. Dotted
    stages (``aggs.collect``) render as child breakdowns under their
    parent scope. The per-shard invariant pinned by tests:
    ``device_time_in_nanos + host_time_in_nanos == time_in_nanos`` and
    every breakdown stage ≤ ``time_in_nanos``."""
    notes = rec.pop("_notes", {})
    device_records: List[Dict[str, Any]] = rec.pop("_device", [])
    kernel_notes = [
        {"kernel": kernel, "kind": kind, "calls": slot[0],
         "ms": round(slot[1], 3)}
        for (kernel, kind), slot in sorted(rec.pop("_kernels",
                                                   {}).items())]
    counters: Dict[str, float] = rec.pop("_counters", {})
    stages = {k: v for k, v in rec.items()}

    # structured child scopes: dotted stages group under their parent
    children: Dict[str, Dict[str, int]] = {}
    flat: Dict[str, int] = {}
    for k, v in stages.items():
        if "." in k:
            parent, _, child = k.partition(".")
            children.setdefault(parent, {})[child] = v
        else:
            flat[k] = v

    device_ns = sum(flat.get(k, 0) for k in DEVICE_STAGES)
    host_ns = sum(flat.get(k, 0) for k in HOST_STAGES) \
        + sum(sum(c.values()) for c in children.values())
    total_ns = max(int(total_ns), device_ns + host_ns)

    breakdown: Dict[str, Any] = dict(flat)
    breakdown["device_time_in_nanos"] = device_ns
    breakdown["host_time_in_nanos"] = total_ns - device_ns

    qtype = next(iter((body or {}).get("query") or {"match_all": {}}))
    collector_name = notes.get("collector", "FusedPlanTopDocsCollector")
    entry: Dict[str, Any] = {
        "id": shard_id,
        "searches": [{
            "query": [{
                "type": qtype,
                "description": str((body or {}).get("query", {})),
                "time_in_nanos": total_ns,
                # the TPU execution stages (compile/bind are host;
                # launch/readback are device — ref: QueryProfiler.java
                # breaks down per-Scorer timing types; here the stages
                # ARE the execution model)
                "breakdown": breakdown,
            }],
            "rewrite_time": flat.get("rewrite", 0),
            "collector": [{
                "name": collector_name,
                "reason": "search_top_hits",
                "time_in_nanos": (
                    collected_ns if collected_ns is not None
                    else flat.get("launch", 0) + flat.get("topk", 0)
                    + flat.get("score", 0)),
            }],
        }],
        "aggregations": [],
    }
    for parent in sorted(children):
        child_stages = children[parent]
        node = {
            "type": parent,
            "time_in_nanos": sum(child_stages.values()),
            "breakdown": dict(child_stages),
        }
        if parent in ("aggs", "aggregations"):
            node["type"] = "aggregations"
            spec = (body or {}).get("aggs",
                                    (body or {}).get("aggregations"))
            node["description"] = ",".join(sorted(spec)) \
                if isinstance(spec, dict) else ""
            entry["aggregations"].append(node)
        else:
            entry["searches"][0].setdefault("children", []).append(node)
    device_section: Dict[str, Any] = {}
    if device_records:
        device_section["launches"] = device_records
    if kernel_notes:
        device_section["kernels"] = kernel_notes
    if counters:
        device_section.update(
            {k: (int(v) if float(v).is_integer() else round(v, 3))
             for k, v in counters.items()})
    if device_section:
        # the attribution block the reference has no analogue for: WHY
        # the device time was what it was (cohorts, padding, compile
        # vs cache, HBM churn, readback volume)
        entry["device"] = device_section
    # tenant stamp: the ambient X-Tenant-Id rides every shard entry so
    # a profiled tree is attributable without joining against tasks
    # (lazy import — telemetry/context.py imports this module)
    from elasticsearch_tpu.telemetry import context as _telectx
    tenant = _telectx.current_tenant()
    if tenant is not None:
        entry["tenant"] = tenant
    wclass = _telectx.current_workload_class()
    if wclass is not None:
        entry["search.class"] = wclass
    return entry
