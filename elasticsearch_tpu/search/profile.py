"""Query profiling: per-stage timing for `profile: true`.

The reference profiles per-Weight/Scorer timing types through
QueryProfiler trees (ref: search/profile/query/QueryProfiler.java:38,
QueryProfileBreakdown). This engine's execution shape is different —
one fused device launch instead of per-doc scorer calls — so the
breakdown reports the stages that actually exist here, split into HOST
and DEVICE time:

  rewrite   — query tree rewriting (host)
  compile   — logical-plan compilation / cache lookup (host)
  bind      — selection building + bucket padding (host)
  launch    — kernel dispatch + device execution wait (device)
  readback  — device→host transfer of the top-k rows (device↔host)
  score     — dense-path column scoring (device, fallback path)
  topk      — dense-path masked top-k (device, fallback path)
  merge     — cross-segment merge (host)

A threadlocal recorder keeps instrumentation out of every call
signature; it is active only under `profiling()`, so the serving hot
path pays one `is-None` check per stage.

Two consumers share the recorder seam:

- ``profiling()`` (the per-request ``profile: true`` dict), and
- ``stage_sink(fn)`` — a persistent sink the telemetry subsystem
  installs so stage timings accumulate into node-level histograms
  (``search.stage.launch`` etc.) on EVERY search, not only profiled
  ones (telemetry/__init__.py ``Telemetry.stage_sink``).

Both are temporal thread-local contexts; telemetry/context.py
``bind()`` carries them (plus the trace context) across scheduler task
boundaries so a multi-node search keeps its shard-side stages.

The stage seam doubles as the engine's cancellation poll point: a
caller that owns a CancellableTask installs its ``ensure_not_cancelled``
via ``cancellable()``, and every ``span(stage)`` entry — i.e. every
device-launch boundary of a multi-segment scan — polls it. A cancelled
search aborts between launches instead of after the full scan, without
the kernels themselves knowing tasks exist.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

_tls = threading.local()


def active() -> bool:
    return getattr(_tls, "rec", None) is not None \
        or getattr(_tls, "sink", None) is not None


@contextmanager
def profiling():
    """Activate collection; yields the stage dict (stage → nanos)."""
    rec: Dict[str, int] = {}
    prev = getattr(_tls, "rec", None)
    _tls.rec = rec
    try:
        yield rec
    finally:
        _tls.rec = prev


@contextmanager
def stage_sink(fn):
    """Install a stage sink ``fn(stage, nanos)`` for the duration;
    stacks with (and is independent of) an active ``profiling()``."""
    prev = getattr(_tls, "sink", None)
    _tls.sink = fn
    try:
        yield
    finally:
        _tls.sink = prev


def record(stage: str, nanos: int) -> None:
    rec = getattr(_tls, "rec", None)
    if rec is not None:
        rec[stage] = rec.get(stage, 0) + nanos
    sink = getattr(_tls, "sink", None)
    if sink is not None:
        sink(stage, nanos)


def note(key: str, value) -> None:
    """Non-timing annotation (e.g. collector name)."""
    rec = getattr(_tls, "rec", None)
    if rec is not None:
        rec.setdefault("_notes", {})[key] = value   # type: ignore


@contextmanager
def cancellable(check):
    """Install a cancellation poll ``check()`` (typically a task's
    ``ensure_not_cancelled``) for the duration; ``span()`` entries —
    the device-launch boundaries — call it. telemetry/context.bind()
    carries it across scheduler task boundaries."""
    prev = getattr(_tls, "cancel", None)
    _tls.cancel = check
    try:
        yield
    finally:
        _tls.cancel = prev


def check_cancelled() -> None:
    """Poll the installed cancellation hook (raises TaskCancelledException
    through the task's ``ensure_not_cancelled``); no-op when none is
    installed — one getattr on the hot path."""
    cb = getattr(_tls, "cancel", None)
    if cb is not None:
        cb()


@contextmanager
def span(stage: str):
    check_cancelled()
    if not active():
        yield
        return
    t0 = time.monotonic_ns()
    try:
        yield
    finally:
        record(stage, time.monotonic_ns() - t0)


DEVICE_STAGES = ("launch", "readback", "score", "topk")
HOST_STAGES = ("rewrite", "compile", "bind", "merge")
