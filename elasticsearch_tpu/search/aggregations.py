"""Aggregations: bucket/metric/pipeline tree beside the top-k collector.

Mirrors the reference's aggregation framework (ref: search/aggregations/ —
AggregatorBase leaf collectors per segment, InternalAggregation tree-reduce
on the coordinator, SURVEY.md §2.1 "Aggregations"). Re-design for this
engine: the query phase produces a dense match mask per segment; every
bucket is a boolean mask refinement, and every metric is a vectorized
reduction over masked columnar doc values. No per-doc collect() calls —
buckets are mask algebra, metrics are numpy/jnp reductions, sub-aggs
recurse over refined masks.

Implemented aggs:
- metrics: avg, sum, min, max, value_count, stats, extended_stats,
  cardinality, percentiles, percentile_ranks, top_hits, weighted_avg
- buckets: terms, histogram, date_histogram, range, filter, filters,
  missing, global
- pipeline (coordinator-side): avg_bucket, sum_bucket, min_bucket,
  max_bucket, stats_bucket, bucket_sort, cumulative_sum, derivative
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ParsingException,
)
from elasticsearch_tpu.search.sketches import DEFAULT_COMPRESSION, TDigest

# A collect context: (segment, mask np.ndarray[bool n_docs], mapper)
# triples covering every shard's segments — each segment carries ITS
# index's mapper so multi-index aggs analyze with the right chains.
CollectCtx = List[Tuple[Any, np.ndarray, Any]]

METRIC_AGGS = {"avg", "sum", "min", "max", "value_count", "stats",
               "extended_stats", "cardinality", "percentiles",
               "percentile_ranks", "top_hits", "weighted_avg",
               "geo_bounds", "geo_centroid", "scripted_metric",
               # x-pack analytics + aggs-matrix-stats parity
               "boxplot", "top_metrics", "string_stats", "matrix_stats",
               "median_absolute_deviation", "t_test"}
BUCKET_AGGS = {"terms", "histogram", "date_histogram", "range",
               "date_range", "filter",
               "filters", "missing", "global", "composite", "nested",
               "significant_terms", "significant_text", "sampler",
               "diversified_sampler", "rare_terms", "multi_terms",
               "adjacency_matrix", "auto_date_histogram", "ip_range",
               "variable_width_histogram", "children", "parent",
               "geo_distance", "geohash_grid", "geotile_grid"}
PIPELINE_AGGS = {"avg_bucket", "sum_bucket", "min_bucket", "max_bucket",
                 "stats_bucket", "extended_stats_bucket",
                 "percentiles_bucket", "cumulative_sum", "derivative",
                 "bucket_sort", "cumulative_cardinality"}


def _scripted_metric_scripts(body: Dict[str, Any]):
    """Compile the four scripted_metric scripts (shared by the
    in-process path and the distributed partial collector)."""
    from elasticsearch_tpu.script.interp import compile_painless

    def src(key):
        s = body.get(key)
        if isinstance(s, dict):
            s = s.get("source")
        return s

    map_src = src("map_script")
    if not map_src:
        raise ParsingException(
            "[scripted_metric] requires [map_script]")
    params = dict(body.get("params", {}))
    init_s = compile_painless(src("init_script")) \
        if src("init_script") else None
    map_s = compile_painless(map_src)
    combine_s = compile_painless(src("combine_script")) \
        if src("combine_script") else None
    reduce_s = compile_painless(src("reduce_script")) \
        if src("reduce_script") else None
    return params, init_s, map_s, combine_s, reduce_s


def scripted_metric_states(body: Dict[str, Any],
                           ctx: CollectCtx) -> List[Any]:
    """init/map per segment, combine per segment → the mergeable
    per-shard states the reduce script consumes (the reference's
    ScriptedMetricAggregator shard half). States must stay
    JSON-serializable to cross the wire on the distributed path."""
    from elasticsearch_tpu.script.contexts import ContextShim
    from elasticsearch_tpu.script.interp import PainlessError

    params, init_s, map_s, combine_s, _reduce_s = \
        _scripted_metric_scripts(body)

    class _DocShim(ContextShim):
        def __init__(self, seg, d):
            self._seg = seg
            self._d = d

        def pl_index(self, field):
            seg, d = self._seg, self._d
            nv = seg.numerics.get(field)
            if nv is not None:
                missing = bool(nv.missing[d])
                return _Col(None if missing else float(nv.values[d]))
            kv = seg.keywords.get(field)
            if kv is not None:
                ords = kv.all_ords[kv.offsets[d]: kv.offsets[d + 1]]
                return _Col(kv.terms[ords[0]] if len(ords) else None)
            return _Col(None)

    class _Col(ContextShim):
        def __init__(self, value):
            self._v = value

        def pl_get(self, name):
            if name == "value":
                if self._v is None:
                    raise PainlessError(
                        "A document doesn't have a value for a field")
                return self._v
            if name == "empty":
                return self._v is None
            raise PainlessError(f"unknown field [{name}]")

        def pl_call(self, name, args):
            if name == "size":
                return 0 if self._v is None else 1
            if name == "getValue":
                return self.pl_get("value")
            raise PainlessError(f"unknown method [{name}]")

    states = []
    for seg, mask, _m in ctx:
        state: Dict[str, Any] = {}
        base = {"state": state, "params": params}
        if init_s is not None:
            init_s.execute(base)
        for d in np.nonzero(mask[: seg.n_docs])[0]:
            map_s.execute({**base, "doc": _DocShim(seg, int(d))})
        states.append(combine_s.execute(base)
                      if combine_s is not None else state)
    return states


def scripted_metric_reduce(body: Dict[str, Any],
                           states: List[Any]) -> Dict[str, Any]:
    """The coordinator half: reduce script over all shards' states."""
    params, _i, _m, _c, reduce_s = _scripted_metric_scripts(body)
    if reduce_s is not None:
        value = reduce_s.execute({"states": states, "params": params})
    else:
        value = states
    return {"value": value}


def _scripted_metric(body: Dict[str, Any], ctx: CollectCtx):
    """ref: metrics/ScriptedMetricAggregator — init/map per shard,
    combine per shard, reduce across shards; scripts run the full
    Painless engine (script/) with `state`, `states`, `params`, and a
    per-doc `doc` binding over the segment's doc values."""
    return scripted_metric_reduce(body,
                                  scripted_metric_states(body, ctx))


def compute_aggs(spec: Dict[str, Any], ctx: CollectCtx,
                 mapper, device_cache=None) -> Dict[str, Any]:
    """Evaluate an aggs tree; returns the `aggregations` response object.

    Wrapper over _compute_aggs that strips internal carrier keys (e.g.
    cardinality's exact value set, consumed by cumulative_cardinality)
    from the finished tree. The caller's device cache is scoped to this
    computation via a contextvar — thread-safe across concurrent
    searches, and the reference is dropped on exit (a module global
    would pin a deleted index's HBM arrays and race across indices)."""
    token = _DEVICE_CACHE.set(device_cache)
    try:
        out = _compute_aggs(spec, ctx, mapper, device_cache)
    finally:
        _DEVICE_CACHE.reset(token)
    _strip_internal(out)
    return out


def _strip_internal(node) -> None:
    if isinstance(node, dict):
        # only the internal carrier (a Python set) — a user _source field
        # named "_set" is a JSON value and passes through untouched
        if isinstance(node.get("_set"), set):
            del node["_set"]
        # mergeable-sketch carrier for moving_percentiles (a TDigest
        # instance can never appear as a user JSON value); "_values"
        # covers plugin aggs still carrying the legacy raw sample
        if isinstance(node.get("_digest"), TDigest):
            del node["_digest"]
        if isinstance(node.get("_values"), np.ndarray):
            del node["_values"]
        for k, v in node.items():
            if k != "_source":
                _strip_internal(v)
    elif isinstance(node, list):
        for v in node:
            _strip_internal(v)


def _compute_aggs(spec: Dict[str, Any], ctx: CollectCtx,
                  mapper, device_cache=None) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    pipelines: List[Tuple[str, str, Dict[str, Any]]] = []
    for name, node in spec.items():
        agg_type, body, sub = _split_node(name, node)
        if agg_type in PIPELINE_AGGS:
            pipelines.append((name, agg_type, body))
            continue
        out[name] = _compute_one(agg_type, body, sub, ctx, mapper)
    for name, agg_type, body in pipelines:
        out[name] = _compute_pipeline(agg_type, body, out)
    return out


def _split_node(name, node):
    sub = node.get("aggs", node.get("aggregations", {}))
    types = [k for k in node if k not in ("aggs", "aggregations", "meta")]
    if len(types) != 1:
        raise ParsingException(
            f"Expected exactly one aggregation type under [{name}], "
            f"got {types}")
    agg_type = types[0]
    if agg_type not in METRIC_AGGS | BUCKET_AGGS | PIPELINE_AGGS \
            and agg_type not in PLUGIN_AGGS:
        raise ParsingException(f"Unknown aggregation type [{agg_type}]")
    return agg_type, node[agg_type] or {}, sub


# plugin-contributed aggregations (ref: SearchPlugin.getAggregations):
# {type: fn(body, sub_spec, ctx, mapper) -> result dict}
PLUGIN_AGGS: Dict[str, Any] = {}


def _compute_one(agg_type, body, sub, ctx, mapper):
    if agg_type in PLUGIN_AGGS:
        return PLUGIN_AGGS[agg_type](body, sub, ctx, mapper)
    if agg_type in METRIC_AGGS:
        return _metric(agg_type, body, ctx, mapper)
    return _bucket(agg_type, body, sub, ctx, mapper)


# ---------------------------------------------------------------------------
# value sources
# ---------------------------------------------------------------------------

def _numeric_values(ctx: CollectCtx, field: str) -> np.ndarray:
    """All values (multi-value aware) of `field` for masked docs.
    Vectorized ragged expansion — np.repeat of the doc mask over the
    per-doc value counts selects every value position, no per-doc
    Python (VERDICT r3 item 6)."""
    chunks = []
    for seg, mask, _m in ctx:
        nv = seg.numerics.get(field)
        if nv is None:
            continue
        m = mask[: seg.n_docs] & ~nv.missing
        if not m.any():
            continue
        sel = np.repeat(m, np.diff(nv.offsets))
        chunks.append(nv.all_values[sel])
    return np.concatenate(chunks) if chunks else np.zeros(0)


def _first_values_and_mask(seg, mask, field):
    nv = seg.numerics.get(field)
    if nv is None:
        return None, None
    m = mask[: seg.n_docs] & ~nv.missing
    return nv.values, m


# above this many docs the terms collector rides the device (ord-major
# permutation + cumsum, ops/aggs.py); below it a host bincount wins
DEVICE_AGG_MIN_DOCS = 200_000

# zero-count gap fill materializes one bucket per step — cap the span
# so one sparse value pair (0 and 1e12 at interval 1) cannot OOM the
# node outside any breaker's sight (ES: search.max_buckets /
# too_many_buckets_exception; shared with the distributed reduce in
# agg_partials.py)
MAX_HISTOGRAM_BUCKETS = 65536


def _check_bucket_cap(n: int, agg_type: str) -> None:
    if n > MAX_HISTOGRAM_BUCKETS:
        raise IllegalArgumentException(
            f"[{agg_type}] would materialize [{n}] buckets "
            f"(> [{MAX_HISTOGRAM_BUCKETS}]); narrow the range or "
            "widen the interval")

import contextvars  # noqa: E402

# the index's DeviceSegmentCache, scoped per compute_aggs call
_DEVICE_CACHE: "contextvars.ContextVar" = contextvars.ContextVar(
    "agg_device_cache", default=None)


def _masked_ord_counts(kv, mask, n_docs) -> np.ndarray:
    """Per-ord value counts [n_terms] over masked docs — vectorized
    ragged expansion + bincount, no per-doc Python."""
    m = mask[:n_docs]
    sel = np.repeat(m, np.diff(kv.offsets))
    return np.bincount(kv.all_ords[sel], minlength=len(kv.terms))


def _keyword_terms_counts(ctx: CollectCtx, field: str):
    """term -> doc count over masked docs. Batched segmented reductions
    (ref: AggregatorBase.java:180-186 per-doc LeafBucketCollector —
    recast columnar): device ord-major cumsum at scale, host bincount
    below DEVICE_AGG_MIN_DOCS."""
    counts: Dict[str, int] = {}
    dev_cache = _DEVICE_CACHE.get()
    for seg, mask, _m in ctx:
        kv = seg.keywords.get(field)
        if kv is None:
            continue
        bc = None
        if dev_cache is not None and seg.n_docs >= DEVICE_AGG_MIN_DOCS:
            try:
                dev = dev_cache.get(seg)
                om = dev.keyword_ord_major(field)
                if om is not None:
                    import jax

                    from elasticsearch_tpu.ops.aggs import (
                        terms_counts_per_term)
                    dmask = jax.device_put(
                        np.pad(mask[: seg.n_docs],
                               (0, dev.n_docs_padded - seg.n_docs)),
                        device=dev._device)
                    bc = terms_counts_per_term(om[0], om[1], dmask)
            except Exception:       # noqa: BLE001 — host fallback
                # log ONCE per process: a permanently broken device
                # path must not silently run every query at host speed
                if not getattr(_keyword_terms_counts, "_dev_warned",
                               False):
                    _keyword_terms_counts._dev_warned = True
                    import logging
                    logging.getLogger(
                        "elasticsearch_tpu.aggs").exception(
                        "device terms collector failed; using the "
                        "host path")
                bc = None
        if bc is None:
            bc = _masked_ord_counts(kv, mask, seg.n_docs)
        for o in np.nonzero(bc)[0]:
            term = kv.terms[int(o)]
            counts[term] = counts.get(term, 0) + int(bc[o])
    return counts


def _keyword_membership_mask(seg, field: str, term: str) -> np.ndarray:
    """bool [n_docs]: docs containing `term` in keyword field (multi-value
    aware)."""
    kv = seg.keywords.get(field)
    out = np.zeros(seg.n_docs, bool)
    if kv is None:
        return out
    try:
        tid = kv.terms.index(term)
    except ValueError:
        return out
    positions = np.nonzero(kv.all_ords == tid)[0]
    docs = np.searchsorted(kv.offsets, positions, side="right") - 1
    out[docs] = True
    return out


def _geo_points(ctx: CollectCtx, field: str):
    """(lats, lons) of masked docs' first point values across segments."""
    lat_chunks, lon_chunks = [], []
    for seg, mask, _m in ctx:
        nlat = seg.numerics.get(f"{field}.lat")
        nlon = seg.numerics.get(f"{field}.lon")
        if nlat is None or nlon is None:
            continue
        m = mask[: seg.n_docs] & ~nlat.missing
        lat_chunks.append(nlat.values[m])
        lon_chunks.append(nlon.values[m])
    if not lat_chunks:
        return np.zeros(0), np.zeros(0)
    return np.concatenate(lat_chunks), np.concatenate(lon_chunks)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def _metric(agg_type, body, ctx, mapper):
    field = body.get("field")
    missing_val = body.get("missing")

    if agg_type == "geo_bounds":
        # ref: metrics/GeoBoundsAggregator — envelope of all points
        lats, lons = _geo_points(ctx, field)
        if len(lats) == 0:
            return {}
        return {"bounds": {
            "top_left": {"lat": float(lats.max()), "lon": float(lons.min())},
            "bottom_right": {"lat": float(lats.min()), "lon": float(lons.max())},
        }}

    if agg_type == "geo_centroid":
        # ref: metrics/GeoCentroidAggregator — arithmetic mean of points
        lats, lons = _geo_points(ctx, field)
        if len(lats) == 0:
            return {"count": 0}
        return {"location": {"lat": float(lats.mean()),
                             "lon": float(lons.mean())},
                "count": int(len(lats))}

    if agg_type == "scripted_metric":
        return _scripted_metric(body, ctx)

    if agg_type == "top_hits":
        import json as _json
        size = int(body.get("size", 3))
        sort_spec = body.get("sort")
        total = int(sum(int(mask[: seg.n_docs].sum())
                        for seg, mask, _m in ctx))
        if sort_spec:
            # primary sort key over numeric doc values (the same
            # primary-key discipline as the searcher's sort path);
            # missing values sort last in either direction
            spec = (sort_spec[0] if isinstance(sort_spec, list)
                    else sort_spec)
            # ES accepts `"sort": "price"` and `"sort": ["price"]` —
            # normalize string specs to {field: {"order": ...}} before
            # unpacking (default order asc, as the reference's
            # FieldSortBuilder does for bare field names)
            if isinstance(spec, str):
                spec = {spec: {"order": "asc"}}
            (sfield, sdir), = spec.items()
            order = (sdir.get("order", "asc")
                     if isinstance(sdir, dict) else str(sdir))
            desc = order == "desc"
            cand = []
            for seg, mask, _m in ctx:
                nv = seg.numerics.get(sfield)
                idxs = np.nonzero(mask[: seg.n_docs])[0]
                for d in idxs:
                    d = int(d)
                    if nv is not None and not nv.missing[d]:
                        key = float(nv.values[d])
                        missing_rank = 0
                    else:
                        key = 0.0
                        missing_rank = 1
                    cand.append((missing_rank,
                                 -key if desc else key, seg, d))
            cand.sort(key=lambda e: (e[0], e[1], e[3]))
            hits = [{"_id": seg.stored.ids[d],
                     "_source": _json.loads(seg.stored.source(d)),
                     "sort": [(-k if desc else k) if mr == 0 else None]}
                    for mr, k, seg, d in cand[:size]]
        else:
            hits = []
            for seg, mask, _m in ctx:
                for d in np.nonzero(mask[: seg.n_docs])[0][:size]:
                    hits.append({
                        "_id": seg.stored.ids[int(d)],
                        "_source": _json.loads(
                            seg.stored.source(int(d)))})
            hits = hits[:size]
        return {"hits": {"total": {"value": total, "relation": "eq"},
                         "hits": hits}}

    if agg_type == "cardinality":
        # keyword or numeric distinct count (exact; the reference uses
        # HLL++ — approximation is a later optimization)
        distinct = set()
        for seg, mask, _m in ctx:
            kv = seg.keywords.get(field)
            if kv is not None:
                bc = _masked_ord_counts(kv, mask, seg.n_docs)
                distinct.update(kv.terms[int(o)]
                                for o in np.nonzero(bc)[0])
                continue
            nv = seg.numerics.get(field)
            if nv is not None:
                m = mask[: seg.n_docs] & ~nv.missing
                distinct.update(np.unique(nv.values[m]).tolist())
        # the exact distinct set travels internally for
        # cumulative_cardinality (stripped from the response)
        return {"value": len(distinct), "_set": distinct}

    if agg_type == "t_test":
        # ref: x-pack analytics TTestAggregator — paired /
        # homoscedastic / heteroscedastic (Welch, the default) two-
        # sided p-value over two numeric value sources, each with an
        # optional per-source filter (the A/B-test shape)
        ttype = str(body.get("type", "heteroscedastic"))
        if ttype not in ("paired", "homoscedastic", "heteroscedastic"):
            raise ParsingException(
                f"unsupported t_test type [{ttype}]; expected one of "
                "[paired, homoscedastic, heteroscedastic]")
        a_spec, b_spec = body.get("a") or {}, body.get("b") or {}

        def _source_ctx(spec):
            if spec.get("filter") is None:
                return ctx
            from elasticsearch_tpu.search.queries import parse_query
            q = parse_query(spec["filter"])
            return _refine(ctx, _query_masks(q, ctx, mapper))

        from scipy import stats as _st
        if ttype == "paired":
            if (a_spec.get("filter") is not None
                    or b_spec.get("filter") is not None):
                raise ParsingException(
                    "paired t_test does not support filters")
            # pairs are WITHIN a document: both fields present
            xa_parts, xb_parts = [], []
            for seg, mask, _m in ctx:
                va, ma = _first_values_and_mask(seg, mask,
                                                a_spec.get("field"))
                vb, mb = _first_values_and_mask(seg, mask,
                                                b_spec.get("field"))
                if va is None or vb is None:
                    continue
                both = ma & mb
                xa_parts.append(va[both])
                xb_parts.append(vb[both])
            xa = np.concatenate(xa_parts) if xa_parts else np.zeros(0)
            xb = np.concatenate(xb_parts) if xb_parts else np.zeros(0)
            if len(xa) < 2:
                return {"value": None}
            res = _st.ttest_rel(xa, xb)
        else:
            xa = _numeric_values(_source_ctx(a_spec),
                                 a_spec.get("field"))
            xb = _numeric_values(_source_ctx(b_spec),
                                 b_spec.get("field"))
            if len(xa) < 2 or len(xb) < 2:
                return {"value": None}
            res = _st.ttest_ind(xa, xb,
                                equal_var=(ttype == "homoscedastic"))
        p = float(res.pvalue)
        return {"value": None if np.isnan(p) else p}

    if agg_type == "median_absolute_deviation":
        # ref: x-pack/plugin/analytics MedianAbsoluteDeviationAggregator
        # — reduced from a bounded-memory digest (exact while the sample
        # fits the centroid budget, same as the reference's TDigest path)
        digest = TDigest.from_values(_numeric_values(ctx, field),
                                     _digest_compression(body))
        return {"value": digest.mad()}

    if agg_type == "boxplot":
        # ref: x-pack/plugin/analytics BoxplotAggregator — five-number
        # summary + 1.5·IQR whiskers clamped to data points (the digest's
        # representative points; exact below the centroid budget)
        return shape_boxplot(TDigest.from_values(
            _numeric_values(ctx, field), _digest_compression(body)))

    if agg_type == "top_metrics":
        # ref: x-pack/plugin/analytics TopMetricsAggregator — the metric
        # values of the top-N docs by a sort field
        metrics = body.get("metrics", [])
        if isinstance(metrics, dict):
            metrics = [metrics]
        sort_spec = body.get("sort", [])
        if isinstance(sort_spec, (str, dict)):
            sort_spec = [sort_spec]
        if not sort_spec:
            raise IllegalArgumentException("top_metrics requires [sort]")
        entry = sort_spec[0]
        if isinstance(entry, str):
            sfield, order = entry, "asc"
        else:
            (sfield, spec), = entry.items()
            order = spec if isinstance(spec, str) else spec.get("order", "asc")
        size = int(body.get("size", 1))
        rows = []          # (sort_value, {metric: value})
        for seg, mask, _m in ctx:
            sv, sm = _first_values_and_mask(seg, mask, sfield)
            if sv is None:
                continue
            docs = np.nonzero(sm)[0]
            if len(docs) == 0:
                continue
            # top-N by sort value FIRST (vectorized partial sort), then
            # metric columns only for those N docs
            svals = sv[docs]
            if len(docs) > size:
                part = (np.argpartition(-svals, size - 1)[:size]
                        if order == "desc"
                        else np.argpartition(svals, size - 1)[:size])
                docs, svals = docs[part], svals[part]
            for d, sval in zip(docs, svals):
                mvals = {}
                for mspec in metrics:
                    mf = mspec.get("field")
                    nv = seg.numerics.get(mf)
                    mvals[mf] = (float(nv.values[d])
                                 if nv is not None and not nv.missing[d]
                                 else None)
                rows.append((float(sval), mvals))
        rows.sort(key=lambda r: r[0], reverse=(order == "desc"))
        return {"top": [{"sort": [s], "metrics": mv}
                        for s, mv in rows[:size]]}

    if agg_type == "string_stats":
        # ref: x-pack/plugin/analytics StringStatsAggregator — length
        # stats + Shannon entropy over the character distribution
        count = 0
        min_len = None
        max_len = None
        total_len = 0
        char_counts: Dict[str, int] = {}
        for seg, mask, _m in ctx:
            kv = seg.keywords.get(field)
            if kv is None:
                continue
            # per-ord counts once (vectorized); character work runs per
            # DISTINCT term, weighted by its count — never per doc
            bc = _masked_ord_counts(kv, mask, seg.n_docs)
            for o in np.nonzero(bc)[0]:
                term = kv.terms[int(o)]
                c = int(bc[o])
                count += c
                ln = len(term)
                total_len += ln * c
                min_len = ln if min_len is None else min(min_len, ln)
                max_len = ln if max_len is None else max(max_len, ln)
                for ch in term:
                    char_counts[ch] = char_counts.get(ch, 0) + c
        if count == 0:
            return {"count": 0, "min_length": None, "max_length": None,
                    "avg_length": None, "entropy": 0.0}
        total_chars = sum(char_counts.values())
        entropy = -sum((c / total_chars) * math.log2(c / total_chars)
                       for c in char_counts.values()) if total_chars else 0.0
        out = {"count": count, "min_length": min_len,
               "max_length": max_len, "avg_length": total_len / count,
               "entropy": entropy}
        if body.get("show_distribution"):
            out["distribution"] = {
                ch: c / total_chars
                for ch, c in sorted(char_counts.items(),
                                    key=lambda kv_: -kv_[1])}
        return out

    if agg_type == "matrix_stats":
        # ref: modules/aggs-matrix-stats MatrixStatsAggregator — per-field
        # moments + covariance/correlation over docs that carry EVERY
        # field (pairwise-complete rows)
        fields = body.get("fields", [])
        cols = {f: [] for f in fields}
        for seg, mask, _m in ctx:
            nvs = [seg.numerics.get(f) for f in fields]
            if any(nv is None for nv in nvs):
                continue
            m = mask[: seg.n_docs].copy()
            for nv in nvs:
                m &= ~nv.missing
            for f, nv in zip(fields, nvs):
                cols[f].append(nv.values[m])
        arrs = {f: (np.concatenate(v) if v else np.zeros(0))
                for f, v in cols.items()}
        n = min((len(a) for a in arrs.values()), default=0)
        if n == 0:
            return {"doc_count": 0, "fields": []}
        mat = np.stack([arrs[f][:n] for f in fields])     # [F, n]
        mean = mat.mean(axis=1)
        centered = mat - mean[:, None]
        cov = (centered @ centered.T) / (n - 1) if n > 1 else (
            np.zeros((len(fields), len(fields))))
        std = np.sqrt(np.diag(cov))
        out_fields = []
        for i, f in enumerate(fields):
            v = mat[i]
            var = float(cov[i, i])
            sd = math.sqrt(var) if var > 0 else 0.0
            skew = (float(np.mean((v - mean[i]) ** 3)) / sd ** 3
                    if sd else 0.0)
            kurt = (float(np.mean((v - mean[i]) ** 4)) / sd ** 4
                    if sd else 0.0)
            corr = {}
            for j, g in enumerate(fields):
                denom = std[i] * std[j]
                corr[g] = float(cov[i, j] / denom) if denom else 0.0
            out_fields.append({
                "name": f, "count": n, "mean": float(mean[i]),
                "variance": var, "skewness": skew, "kurtosis": kurt,
                "covariance": {g: float(cov[i, j])
                               for j, g in enumerate(fields)},
                "correlation": corr,
            })
        return {"doc_count": n, "fields": out_fields}

    if agg_type == "weighted_avg":
        vfield = body.get("value", {}).get("field")
        wfield = body.get("weight", {}).get("field")
        num = 0.0
        den = 0.0
        for seg, mask, _m in ctx:
            vv, vm = _first_values_and_mask(seg, mask, vfield)
            wv, wm = _first_values_and_mask(seg, mask, wfield)
            if vv is None or wv is None:
                continue
            m = vm & wm
            num += float((vv[m] * wv[m]).sum())
            den += float(wv[m].sum())
        return {"value": num / den if den else None}

    if missing_val is None and agg_type in (
            "sum", "min", "max", "avg", "value_count", "stats"):
        # device-side batched reduction: one fused launch per resident
        # segment column (ops/aggs.py masked_metric_stats) when every
        # contributing segment clears DEVICE_AGG_MIN_DOCS; None falls
        # through to the exact host path unchanged. extended_stats is
        # deliberately ABSENT: its variance = ss/n − avg² cancels
        # catastrophically in the f32 sum-of-squares accumulation
        # (values ~1e7 over 1M docs give std errors in the thousands
        # where host f64 is exact) — it stays host-side
        dev = _device_metric_stats(ctx, field)
        if dev is not None:
            return _shape_metric_from_stats(agg_type, dev)

    values = _numeric_values(ctx, field)
    if missing_val is not None:
        # count docs matched but missing the field as `missing` value
        n_missing = 0
        for seg, mask, _m in ctx:
            nv = seg.numerics.get(field)
            miss = nv.missing if nv is not None else np.ones(seg.n_docs, bool)
            n_missing += int((mask[: seg.n_docs] & miss).sum())
        values = np.concatenate([values, np.full(n_missing, float(missing_val))])

    n = len(values)
    if agg_type == "value_count":
        return {"value": int(n)}
    if n == 0:
        if agg_type == "stats":
            return {"count": 0, "min": None, "max": None, "avg": None, "sum": 0.0}
        if agg_type == "extended_stats":
            return {"count": 0, "min": None, "max": None, "avg": None,
                    "sum": 0.0, "sum_of_squares": None, "variance": None,
                    "std_deviation": None}
        if agg_type in ("percentiles", "percentile_ranks"):
            return {"values": {}}
        return {"value": None}
    if agg_type == "avg":
        return {"value": float(values.mean())}
    if agg_type == "sum":
        return {"value": float(values.sum())}
    if agg_type == "min":
        return {"value": float(values.min())}
    if agg_type == "max":
        return {"value": float(values.max())}
    if agg_type == "stats":
        return {"count": n, "min": float(values.min()),
                "max": float(values.max()), "avg": float(values.mean()),
                "sum": float(values.sum())}
    if agg_type == "extended_stats":
        var = float(values.var())
        return {"count": n, "min": float(values.min()),
                "max": float(values.max()), "avg": float(values.mean()),
                "sum": float(values.sum()),
                "sum_of_squares": float((values ** 2).sum()),
                "variance": var, "std_deviation": math.sqrt(var)}
    if agg_type == "percentiles":
        percents = body.get("percents", [1, 5, 25, 50, 75, 95, 99])
        # "_digest" carries the mergeable sketch for moving_percentiles'
        # window merge (the reference merges TDigest states; below the
        # centroid budget the digest IS the exact sample, so quantiles
        # are numpy's linear interpolation) — stripped before the
        # response leaves the agg layer (_strip_internal)
        digest = TDigest.from_values(values, _digest_compression(body))
        return {"values": {str(float(p)): digest.quantile(float(p))
                           for p in percents},
                "_digest": digest}
    if agg_type == "percentile_ranks":
        targets = body.get("values", [])
        digest = TDigest.from_values(values, _digest_compression(body))
        return {"values": {str(float(t)): digest.cdf(float(t)) * 100.0
                           for t in targets}}
    raise IllegalArgumentException(f"unhandled metric [{agg_type}]")


def shape_boxplot(digest: TDigest) -> Dict[str, Any]:
    """Boxplot response from a digest — ONE shaping for the in-process
    metric and the distributed finalize (agg_partials.py), so the two
    paths cannot drift."""
    if digest.is_empty():
        return {"min": None, "max": None, "q1": None, "q2": None,
                "q3": None}
    q1, q2, q3 = (digest.quantile(p) for p in (25, 50, 75))
    iqr = q3 - q1
    lo, hi = q1 - 1.5 * iqr, q3 + 1.5 * iqr
    pts = digest.data_points()
    within = pts[(pts >= lo) & (pts <= hi)]
    return {"min": float(digest.min), "max": float(digest.max),
            "q1": q1, "q2": q2, "q3": q3,
            "lower": float(within.min()) if len(within) else q1,
            "upper": float(within.max()) if len(within) else q3}


def _digest_compression(body) -> int:
    """Centroid budget for the percentile family (ES body shape:
    ``{"tdigest": {"compression": N}}``)."""
    td = body.get("tdigest") or {}
    try:
        return max(16, int(td.get("compression", DEFAULT_COMPRESSION)))
    except (TypeError, ValueError):
        raise ParsingException(
            f"invalid tdigest compression [{td.get('compression')!r}]")


def _shape_metric_from_stats(agg_type, stats):
    """The response object of a simple numeric metric from its
    (count, sum, min, max, sum_sq) moments — mirrors the host branch
    shapes exactly (including the empty shapes)."""
    n, s, mn, mx, ss = stats
    if agg_type == "value_count":
        return {"value": int(n)}
    if n == 0:
        if agg_type == "stats":
            return {"count": 0, "min": None, "max": None, "avg": None,
                    "sum": 0.0}
        if agg_type == "extended_stats":
            return {"count": 0, "min": None, "max": None, "avg": None,
                    "sum": 0.0, "sum_of_squares": None, "variance": None,
                    "std_deviation": None}
        return {"value": None}
    avg = s / n
    if agg_type == "avg":
        return {"value": avg}
    if agg_type == "sum":
        return {"value": s}
    if agg_type == "min":
        return {"value": mn}
    if agg_type == "max":
        return {"value": mx}
    if agg_type == "stats":
        return {"count": n, "min": mn, "max": mx, "avg": avg, "sum": s}
    var = max(ss / n - avg * avg, 0.0)
    return {"count": n, "min": mn, "max": mx, "avg": avg, "sum": s,
            "sum_of_squares": ss, "variance": var,
            "std_deviation": math.sqrt(var)}


def _warn_device_once(which: str) -> None:
    """Log a broken device agg path ONCE per process per site — it must
    not silently run every query at host speed."""
    flag = f"_dev_warned_{which}"
    if not getattr(_warn_device_once, flag, False):
        setattr(_warn_device_once, flag, True)
        import logging
        logging.getLogger("elasticsearch_tpu.aggs").exception(
            "device %s reduction failed; using the host path", which)


def _single_valued(nv, n_docs: int) -> bool:
    """Whether a numeric doc-values column holds at most one value per
    doc (the device columns carry FIRST values only). Cached on the
    immutable column."""
    cached = getattr(nv, "_single_valued", None)
    if cached is None:
        cached = bool(np.all(np.diff(nv.offsets) <= 1))
        try:
            nv._single_valued = cached
        except Exception:  # noqa: BLE001 — slots/frozen columns
            pass
    return cached


# device columns are f32: past 2^24 the mantissa can no longer hold
# integers exactly, so sums over large-magnitude fields (epoch-ms
# dates at ~1.7e12 are the canonical case) would silently drift by
# minutes where the host f64 path is exact — such columns stay host
F32_EXACT_MAX = float(2 ** 24)


def _f32_exact(nv) -> bool:
    """Whether a column's values survive the f32 device representation
    (|v| ≤ 2^24). Cached on the immutable column."""
    cached = getattr(nv, "_f32_exact", None)
    if cached is None:
        finite = nv.values[np.isfinite(nv.values)]
        cached = bool(finite.size == 0
                      or float(np.abs(finite).max()) <= F32_EXACT_MAX)
        try:
            nv._f32_exact = cached
        except Exception:  # noqa: BLE001 — slots/frozen columns
            pass
    return cached


def _device_metric_stats(ctx, field):
    """Combined (count, sum, min, max, sum_sq) via one fused device
    launch per segment — or None (host path) when the device shouldn't
    or can't take it: no cache, a contributing segment below
    DEVICE_AGG_MIN_DOCS, a multi-valued column (device columns are
    first-value-only), or any device error."""
    dev_cache = _DEVICE_CACHE.get()
    if dev_cache is None or field is None:
        return None
    parts = []
    try:
        import jax

        from elasticsearch_tpu.ops.aggs import masked_metric_stats
        for seg, mask, _m in ctx:
            nv = seg.numerics.get(field)
            if nv is None:
                continue
            if seg.n_docs < DEVICE_AGG_MIN_DOCS \
                    or not _single_valued(nv, seg.n_docs) \
                    or not _f32_exact(nv):
                return None
            dev = dev_cache.get(seg)
            dval = dev.numerics.get(field)
            if dval is None:
                return None
            dmask = jax.device_put(
                np.pad(mask[: seg.n_docs],
                       (0, dev.n_docs_padded - seg.n_docs)),
                device=dev._device)
            parts.append(masked_metric_stats(
                dval, dev.numeric_missing[field], dmask))
    except Exception:  # noqa: BLE001 — host fallback
        _warn_device_once("metric")
        return None
    if not parts:
        return None
    n = sum(p[0] for p in parts)
    s = sum(p[1] for p in parts)
    ss = sum(p[4] for p in parts)
    mns = [p[2] for p in parts if p[2] is not None]
    mxs = [p[3] for p in parts if p[3] is not None]
    return (n, s, min(mns) if mns else None,
            max(mxs) if mxs else None, ss)


# sub-agg types the fused per-bucket device columns can serve —
# extended_stats excluded (f32 sum-of-squares cancellation, see the
# device metric dispatch note in _metric)
DEVICE_METRIC_SUBAGGS = {"sum", "min", "max", "avg", "value_count",
                         "stats"}


def _device_histogram_submetrics(regular_sub):
    """[(name, agg_type, field)] when EVERY sub-agg is a simple numeric
    metric the fused per-bucket columns can serve; None otherwise."""
    sub_metrics = []
    for name, node in (regular_sub or {}).items():
        types = [k for k in node
                 if k not in ("aggs", "aggregations", "meta")]
        if len(types) != 1:
            return None
        t = types[0]
        b = node[t] or {}
        if t not in DEVICE_METRIC_SUBAGGS \
                or node.get("aggs") or node.get("aggregations") \
                or b.get("missing") is not None or b.get("script"):
            return None
        sub_metrics.append((name, t, b.get("field")))
    return sub_metrics


def _device_histogram_buckets(ctx, field, interval, min_doc_count,
                              gap_fill, key_of, is_date, regular_sub):
    """Fixed-interval histogram via device scatter-add: per-bucket doc
    counts plus every simple numeric metric sub-agg as fused per-bucket
    columns — one launch per (segment, column) instead of one host
    numpy pass per bucket. Returns the finished bucket list, or None
    (exact host path) when ineligible: no device cache, a segment below
    DEVICE_AGG_MIN_DOCS, a multi-valued column, a non-metric sub-agg,
    a bucket span past AGG_BUCKET_CAP, or any device error."""
    sub_metrics = _device_histogram_submetrics(regular_sub)
    if sub_metrics is None:
        return None
    moments = _device_histogram_moments(ctx, field, interval,
                                        sub_metrics)
    if moments is None:
        return None
    lo, counts, mcols = moments
    nb = len(counts)
    buckets = []
    for i in range(nb):
        count = int(counts[i])
        if (count == 0 and not gap_fill) or count < min_doc_count:
            continue
        key = key_of(lo + i)
        b = {"key": key}
        if is_date:
            b["key_as_string"] = _ms_to_iso(key)
        b["doc_count"] = count
        for name, t, _f in sub_metrics:
            acc = mcols[name]
            c = int(acc[0][i])
            b[name] = _shape_metric_from_stats(t, (
                c, float(acc[1][i]),
                float(acc[2][i]) if c else None,
                float(acc[3][i]) if c else None,
                float(acc[4][i])))
        buckets.append(b)
    return buckets


def _device_histogram_moments(ctx, field, interval, sub_metrics):
    """(lo_step, counts[nb], {name: [cnt, sum, min, max, sum_sq]
    arrays}) via device scatter-add — or None for the host path."""
    dev_cache = _DEVICE_CACHE.get()
    if dev_cache is None or field is None:
        return None
    try:
        import jax

        from elasticsearch_tpu.ops.aggs import (
            bucket_counts,
            bucket_metric_columns,
            pow2_buckets,
        )
        seg_rows = []
        lo = hi = None
        for seg, mask, _m in ctx:
            nv = seg.numerics.get(field)
            if nv is None:
                continue
            if seg.n_docs < DEVICE_AGG_MIN_DOCS:
                return None
            for _n, _t, mf in sub_metrics:
                mnv = seg.numerics.get(mf)
                if mnv is not None \
                        and (not _single_valued(mnv, seg.n_docs)
                             or not _f32_exact(mnv)):
                    return None
            m = mask[: seg.n_docs] & ~nv.missing
            steps = np.floor(
                np.nan_to_num(nv.values) / interval).astype(np.int64)
            if m.any():
                smin, smax = int(steps[m].min()), int(steps[m].max())
                lo = smin if lo is None else min(lo, smin)
                hi = smax if hi is None else max(hi, smax)
            seg_rows.append((seg, m, steps))
        if lo is None:
            return None
        nb = hi - lo + 1
        if pow2_buckets(nb) == 0:
            return None
        counts = np.zeros(nb, np.int64)
        mcols = {name: [np.zeros(nb, np.int64), np.zeros(nb),
                        np.full(nb, np.inf), np.full(nb, -np.inf),
                        np.zeros(nb)]
                 for name, _t, _f in sub_metrics}
        for seg, m, steps in seg_rows:
            dev = dev_cache.get(seg)
            pad = dev.n_docs_padded - seg.n_docs
            dmask = jax.device_put(np.pad(m, (0, pad)),
                                   device=dev._device)
            ids = np.clip(steps - lo, 0, nb - 1).astype(np.int32)
            dids = jax.device_put(np.pad(ids, (0, pad)),
                                  device=dev._device)
            counts += bucket_counts(dids, dmask, nb)
            for name, _t, mf in sub_metrics:
                dval = dev.numerics.get(mf)
                if dval is None:
                    continue
                cnt, s, mn, mx, ss = bucket_metric_columns(
                    dids, dmask, dval, dev.numeric_missing[mf], nb)
                acc = mcols[name]
                acc[0] += cnt
                acc[1] += s
                acc[2] = np.minimum(acc[2],
                                    np.where(cnt > 0, mn, np.inf))
                acc[3] = np.maximum(acc[3],
                                    np.where(cnt > 0, mx, -np.inf))
                acc[4] += ss
    except Exception:  # noqa: BLE001 — host fallback
        _warn_device_once("histogram")
        return None
    return lo, counts, mcols


# ---------------------------------------------------------------------------
# buckets
# ---------------------------------------------------------------------------

def _refine(ctx: CollectCtx, submasks: List[np.ndarray]) -> CollectCtx:
    return [(seg, mask & sub, m) for (seg, mask, m), sub in zip(ctx, submasks)]


PARENT_PIPELINES = {"cumulative_sum", "derivative",
                    "cumulative_cardinality", "bucket_sort",
                    "moving_fn", "moving_avg", "serial_diff",
                    "bucket_script", "bucket_selector",
                    "moving_percentiles", "normalize"}


def _split_parent_pipelines(sub: Dict[str, Any]):
    """(regular sub-aggs, parent pipelines) — parent pipelines are
    declared INSIDE a multi-bucket agg and run across its buckets (the
    reference's shape; the engine also accepts the sibling form with
    "agg>metric" paths for backward compatibility)."""
    regular, parents = {}, {}
    for name, node in (sub or {}).items():
        types = [k for k in node
                 if k not in ("aggs", "aggregations", "meta")]
        if len(types) == 1 and types[0] in PARENT_PIPELINES:
            parents[name] = (types[0], node[types[0]] or {})
        else:
            regular[name] = node
    return regular, parents


def _bucket_metric_value(bucket: Dict[str, Any], path: str):
    if path in ("_count", ""):
        return bucket.get("doc_count")
    name, _, leaf = path.partition(".")
    v = bucket.get(name)
    if isinstance(v, dict):
        return v.get(leaf or "value")
    return None


def _apply_parent_pipelines(parents, buckets: List[Dict[str, Any]]):
    """Run parent pipelines across a finished bucket list, writing their
    per-bucket results under the declared names (ref: the pipeline
    aggregator tree reduced on the coordinator)."""
    for name, (ptype, body) in parents.items():
        path = body.get("buckets_path", "_count")
        if ptype == "cumulative_sum":
            cum = 0.0
            for b in buckets:
                v = _bucket_metric_value(b, path)
                cum += v or 0.0
                b[name] = {"value": cum}
        elif ptype == "derivative":
            prev = None
            for b in buckets:
                v = _bucket_metric_value(b, path)
                if prev is not None and v is not None:
                    b[name] = {"value": v - prev}
                prev = v
        elif ptype == "cumulative_cardinality":
            seen: set = set()
            metric = path.partition(".")[0]
            for b in buckets:
                s2 = b.get(metric, {}).get("_set")
                if s2 is not None:
                    seen |= s2
                b[name] = {"value": len(seen)}
        elif ptype in ("moving_fn", "moving_avg"):
            # ref: MovFnPipelineAggregator (window ends BEFORE the
            # current bucket at shift=0) vs the old MovAvg aggregator
            # (window INCLUDES the current bucket) — both semantics are
            # preserved. The closed script set covers the built-in
            # MovingFunctions (unweightedAvg default, min, max, sum).
            window = int(body.get("window", 5))
            script = str(body.get("script", ""))
            fn = (min if "min(" in script else
                  max if "max(" in script else
                  sum if "sum(" in script and "unweighted" not in script
                  else None)
            include_current = ptype == "moving_avg"
            series = [_bucket_metric_value(b, path) for b in buckets]
            for i, b in enumerate(buckets):
                end = i + 1 if include_current else i
                win = [v for v in series[max(0, end - window): end]
                       if v is not None]
                if not win:
                    b[name] = {"value": None}
                elif fn is None:
                    b[name] = {"value": sum(win) / len(win)}
                else:
                    b[name] = {"value": fn(win)}
        elif ptype == "serial_diff":
            lag = int(body.get("lag", 1))
            series = [_bucket_metric_value(b, path) for b in buckets]
            for i, b in enumerate(buckets):
                if i >= lag and series[i] is not None \
                        and series[i - lag] is not None:
                    b[name] = {"value": series[i] - series[i - lag]}
        elif ptype == "moving_percentiles":
            # ref: x-pack/plugin/analytics/.../MovingPercentilesPipeline
            # Aggregator.java:31 — slide a window over a sibling
            # percentiles metric, merging the windowed TDigest states
            # ("_digest" carrier on the percentiles result; exact below
            # the centroid budget, where the merge degenerates to
            # concatenating the samples).
            window = int(body.get("window", 5))
            shift = int(body.get("shift", 0))
            metric = path.partition(".")[0].partition(">")[0]
            digests = []
            pcts = None
            for b in buckets:
                node = b.get(metric) or {}
                digests.append(node.get("_digest"))
                if pcts is None and node.get("values"):
                    pcts = [float(p) for p in node["values"]]
            pcts = pcts or [1.0, 5.0, 25.0, 50.0, 75.0, 95.0, 99.0]
            for i, b in enumerate(buckets):
                # MovFn indexing (ref: MovingPercentiles reduce:
                # [i - window + shift, i + shift)) — the window ends
                # BEFORE the current bucket at shift=0, same as the
                # moving_fn branch above
                lo = max(0, i - window + shift)
                hi = max(lo, min(len(buckets), i + shift))
                win = [d for d in digests[lo:hi]
                       if d is not None and not d.is_empty()]
                if not win:
                    b[name] = {"values": {}}
                    continue
                merged = TDigest.merge_all(win)
                b[name] = {"values": {
                    str(p): merged.quantile(p) for p in pcts}}
        elif ptype == "normalize":
            # ref: x-pack/plugin/analytics/.../normalize/
            # NormalizePipelineAggregationBuilder — rescale a bucket
            # metric across the whole bucket list
            method = str(body.get("method", "percent_of_sum"))
            series = [_bucket_metric_value(b, path) for b in buckets]
            vals = np.asarray([v for v in series if v is not None],
                              np.float64)
            n = len(vals)
            lo = float(vals.min()) if n else 0.0
            hi = float(vals.max()) if n else 0.0
            total = float(vals.sum()) if n else 0.0
            mean = float(vals.mean()) if n else 0.0
            std = float(vals.std()) if n else 0.0
            emax = float(np.exp(vals - vals.max()).sum()) if n else 0.0

            def norm_one(v):
                if v is None:
                    return None
                if method == "rescale_0_1":
                    return 0.0 if hi == lo else (v - lo) / (hi - lo)
                if method == "rescale_0_100":
                    return 0.0 if hi == lo else \
                        100.0 * (v - lo) / (hi - lo)
                if method == "percent_of_sum":
                    return None if total == 0 else v / total
                if method == "mean":
                    return 0.0 if hi == lo else (v - mean) / (hi - lo)
                if method in ("z-score", "zscore"):
                    return None if std == 0 else (v - mean) / std
                if method == "softmax":
                    return None if emax == 0 else \
                        float(np.exp(v - hi)) / emax
                raise IllegalArgumentException(
                    f"invalid normalize method [{method}]")

            for b, v in zip(buckets, series):
                b[name] = {"value": norm_one(v)}
        elif ptype in ("bucket_script", "bucket_selector"):
            # ref: pipeline/BucketScriptPipelineAggregator (per-bucket
            # computed metric) and BucketSelectorPipelineAggregator
            # (per-bucket retention predicate); scripts run the full
            # sandboxed Painless interpreter with params bound to the
            # resolved buckets_path metrics. Runtime script errors fail
            # the request like the reference's script_exception; only
            # division by zero degrades to a null value (the Java
            # double semantics the interpreter lacks).
            from elasticsearch_tpu.common.errors import ScriptException
            from elasticsearch_tpu.script.interp import (PainlessError,
                                                         compile_painless)
            paths = body.get("buckets_path") or {}
            if isinstance(paths, str):
                paths = {"_value": paths}
            spec2 = body.get("script", "")
            src = (spec2.get("source", "") if isinstance(spec2, dict)
                   else str(spec2))
            static = (spec2.get("params", {})
                      if isinstance(spec2, dict) else {})
            try:
                script = compile_painless(src)
            except PainlessError as e:
                raise ParsingException(
                    f"[{ptype}] script compile error: {e}")
            gap = str(body.get("gap_policy", "skip"))
            selector = ptype == "bucket_selector"
            keep = []
            for b in buckets:
                vals = {k: _bucket_metric_value(b, p)
                        for k, p in paths.items()}
                missing = any(v is None for v in vals.values())
                if missing and gap != "insert_zeros":
                    # skip: bucket_script writes nothing,
                    # bucket_selector retains the bucket
                    keep.append(b)
                    continue
                if missing:
                    vals = {k: (0.0 if v is None else v)
                            for k, v in vals.items()}
                try:
                    result = script.execute(
                        {"params": {**static, **vals}})
                except ZeroDivisionError:
                    result = None
                except PainlessError as e:
                    raise ScriptException(
                        f"[{ptype}] runtime error: {e} in [{src}]")
                if selector:
                    if bool(result):
                        keep.append(b)
                else:
                    try:
                        value = (None if result is None
                                 else float(result))
                    except (TypeError, ValueError):
                        raise ScriptException(
                            f"[{ptype}] script returned a non-numeric "
                            f"value [{result!r}] in [{src}]")
                    b[name] = {"value": value}
            if selector:
                buckets[:] = keep
        elif ptype == "bucket_sort":
            sort_spec = body.get("sort", [])
            for entry in reversed(sort_spec):
                if isinstance(entry, str):
                    p, order = entry, "asc"
                else:
                    (p, spec2), = entry.items()
                    order = (spec2 if isinstance(spec2, str)
                             else spec2.get("order", "asc"))
                buckets.sort(
                    key=lambda b, _p=p: (
                        _bucket_metric_value(b, _p) is None,
                        _bucket_metric_value(b, _p) or 0),
                    reverse=(order == "desc"))
            frm = int(body.get("from", 0))
            size = body.get("size")
            del buckets[: frm]
            if size is not None:
                del buckets[int(size):]
    return buckets


def _bucket_result(sub: Dict[str, Any], bucket_ctx: CollectCtx, mapper,
                   doc_count: int, extra: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(extra)
    out["doc_count"] = doc_count
    if sub:
        regular, _parents = _split_parent_pipelines(sub)
        if regular:
            out.update(_compute_aggs(regular, bucket_ctx, mapper))
    return out


def _composite_source_values(stype, sbody, seg):
    """Per-doc (first) composite key values for one source over one segment.

    Returns (values, valid) where values is indexable by doc id and valid is
    a bool mask; (None, None) when the field is absent from the segment.
    (ref: search/aggregations/bucket/composite/SingleDimensionValuesSource
    and subclasses — recast as columnar per-segment key extraction.)
    """
    field = sbody.get("field")
    if stype == "terms":
        kv = seg.keywords.get(field)
        if kv is not None:
            vals = [kv.terms[o] if o >= 0 else None for o in kv.ords]
            return vals, kv.ords >= 0
        nv = seg.numerics.get(field)
        if nv is not None:
            return nv.values, ~nv.missing
        return None, None
    nv = seg.numerics.get(field)
    if nv is None:
        return None, None
    if stype == "histogram":
        interval = float(sbody["interval"])
        return np.floor(nv.values / interval) * interval, ~nv.missing
    if stype == "date_histogram":
        cal_unit = _calendar_unit(sbody)
        if cal_unit is not None:
            return _calendar_floor_ms(nv.values, cal_unit), ~nv.missing
        interval = _date_interval_ms(sbody)
        return np.floor(nv.values / interval) * interval, ~nv.missing
    raise ParsingException(f"Unknown composite source type [{stype}]")


def _composite_cmp(a, b, orders):
    """Compare two composite key tuples honoring per-source order; None
    (missing bucket) sorts first on asc, last on desc (ES missing_order
    default)."""
    for x, y, order in zip(a, b, orders):
        if x == y:
            continue
        if x is None:
            c = -1
        elif y is None:
            c = 1
        else:
            c = -1 if x < y else 1
        if order == "desc":
            c = -c
        return c
    return 0


def _composite(body, sub, ctx, mapper):
    """Composite agg: paginable multi-source bucket keys with after-key
    cursoring (ref: bucket/composite/CompositeAggregator.java — the
    substrate for SQL GROUP BY and transforms). Keys are extracted
    columnar per segment, grouped on the coordinator, sorted in composite
    key order, and paged via `after`."""
    import functools

    sources = body.get("sources", [])
    if not sources:
        raise ParsingException("composite requires [sources]")
    size = int(body.get("size", 10))
    after = body.get("after")
    names, orders, missing_ok = [], [], []
    for src in sources:
        (name, spec), = src.items()
        (stype, sbody), = spec.items()
        names.append(name)
        orders.append(sbody.get("order", "asc"))
        missing_ok.append(bool(sbody.get("missing_bucket", False)))
    # per segment per source value extraction
    seg_source_vals = []
    for seg, _mask, _m in ctx:
        row = []
        for src in sources:
            (name, spec), = src.items()
            (stype, sbody), = spec.items()
            row.append(_composite_source_values(stype, sbody, seg))
        seg_source_vals.append(row)
    # group masked docs by composite key
    groups: Dict[tuple, List[List[int]]] = {}
    counts: Dict[tuple, int] = {}
    for si, (seg, mask, _m) in enumerate(ctx):
        docs = np.nonzero(mask[: seg.n_docs])[0]
        for d in docs:
            key = []
            ok = True
            for j in range(len(sources)):
                vals, valid = seg_source_vals[si][j]
                if vals is None or not bool(valid[d]):
                    if missing_ok[j]:
                        key.append(None)
                    else:
                        ok = False
                        break
                else:
                    v = vals[d]
                    key.append(float(v) if isinstance(
                        v, (np.floating, np.integer)) else v)
            if not ok:
                continue
            kt = tuple(key)
            if kt not in groups:
                groups[kt] = [[] for _ in ctx]
                counts[kt] = 0
            groups[kt][si].append(int(d))
            counts[kt] += 1
    keyfn = functools.cmp_to_key(
        lambda a, b: _composite_cmp(a, b, orders))
    ordered = sorted(groups, key=keyfn)
    if after is not None:
        after_t = tuple(after.get(n) for n in names)
        ordered = [k for k in ordered
                   if _composite_cmp(k, after_t, orders) > 0]
    page = ordered[:size]
    buckets = []
    for kt in page:
        submasks = []
        for si, (seg, _mask, _m) in enumerate(ctx):
            sm = np.zeros(seg.n_docs, bool)
            if groups[kt][si]:
                sm[groups[kt][si]] = True
            submasks.append(sm)
        bucket_ctx = _refine(ctx, submasks)
        buckets.append(_bucket_result(
            sub, bucket_ctx, mapper, counts[kt],
            {"key": dict(zip(names, kt))}))
    _apply_parent_pipelines(_split_parent_pipelines(sub)[1], buckets)
    out: Dict[str, Any] = {"buckets": buckets}
    if buckets:
        out["after_key"] = buckets[-1]["key"]
    return out


def _significant_terms(body, sub, ctx, mapper):
    """ref: bucket/significant/SignificantTermsAggregator — terms whose
    foreground (query-matched) frequency is anomalously high vs the
    background (whole index), scored with JLH."""
    field = body.get("field")
    size = int(body.get("size", 10))
    min_doc_count = int(body.get("min_doc_count", 3))
    fg_counts = _keyword_terms_counts(ctx, field)
    bg_ctx = [(seg, seg.live.copy(), m) for seg, _msk, m in ctx]
    bg_counts = _keyword_terms_counts(bg_ctx, field)
    fg_total = sum(int(msk.sum()) for _, msk, _m in ctx)
    bg_total = sum(int(msk.sum()) for _, msk, _m in bg_ctx)
    scored = []
    for term, fg in fg_counts.items():
        if fg < min_doc_count:
            continue
        bg = bg_counts.get(term, fg)
        fg_rate = fg / max(fg_total, 1)
        bg_rate = bg / max(bg_total, 1)
        if fg_rate <= bg_rate:
            continue
        # JLH: (fg% - bg%) * (fg% / bg%)
        score = (fg_rate - bg_rate) * (fg_rate / max(bg_rate, 1e-12))
        scored.append((score, term, fg, bg))
    scored.sort(key=lambda t: (-t[0], t[1]))
    buckets = []
    for score, term, fg, bg in scored[:size]:
        bucket_ctx = _refine(
            ctx, [_keyword_membership_mask(seg, field, term)
                  for seg, _m2, _m3 in ctx])
        buckets.append(_bucket_result(
            sub, bucket_ctx, mapper, fg,
            {"key": term, "score": score, "bg_count": bg}))
    _apply_parent_pipelines(_split_parent_pipelines(sub)[1], buckets)
    return {"doc_count": fg_total, "bg_count": bg_total,
            "buckets": buckets}


def _rare_terms(body, sub, ctx, mapper):
    """ref: bucket/terms/rare/RareTermsAggregator — the long tail:
    terms whose doc count is at most ``max_doc_count`` (default 1),
    ordered ascending by count then key. The reference bounds memory
    with a bloom filter; the columnar ord counts here are exact."""
    field = body.get("field")
    max_dc = int(body.get("max_doc_count", 1))
    if not 1 <= max_dc <= 100:
        raise ParsingException(
            "[max_doc_count] must be in [1, 100]")
    counts = _keyword_terms_counts(ctx, field)
    rare = sorted(((c, t) for t, c in counts.items() if c <= max_dc))
    buckets = []
    for c, term in rare:
        # membership refinement costs a full ord scan per term — only
        # pay it when sub-aggregations actually consume the bucket ctx
        bucket_ctx = (_refine(
            ctx, [_keyword_membership_mask(seg, field, term)
                  for seg, _m2, _m3 in ctx]) if sub else ctx)
        buckets.append(_bucket_result(sub, bucket_ctx, mapper, c,
                                      {"key": term}))
    _apply_parent_pipelines(_split_parent_pipelines(sub)[1], buckets)
    return {"buckets": buckets}


def _multi_terms(body, sub, ctx, mapper):
    """ref: bucket/terms/MultiTermsAggregator — compound keys over
    several value sources, counted like `terms` (first value per doc
    per source, the reference's default for single-valued use)."""
    terms_spec = body.get("terms") or []
    if len(terms_spec) < 2:
        raise ParsingException(
            "multi_terms requires at least two terms sources")
    size = int(body.get("size", 10))
    fields = [t.get("field") for t in terms_spec]
    counts: Dict[tuple, int] = {}
    seg_rows = []
    for seg, mask, _m in ctx:
        docs = np.nonzero(mask[: seg.n_docs])[0]
        cols = []
        for f in fields:
            kv = seg.keywords.get(f)
            if kv is not None:
                # KeywordDocValues.ords is already first-ord-or-minus-1
                vals = np.asarray(kv.ords, np.int64)[docs]
                cols.append(("k", kv, vals, vals >= 0))
                continue
            nv = seg.numerics.get(f)
            if nv is not None:
                has = ~nv.missing[docs]
                cols.append(("n", None, nv.values[docs], has))
                continue
            cols.append(("x", None, np.full(len(docs), -1),
                         np.zeros(len(docs), bool)))
        seg_rows.append((seg, docs, cols))
        valid = np.ones(len(docs), bool)
        for _, _, _, has in cols:
            valid &= has
        if not valid.any():
            continue
        # vectorized compound counting: stack the per-source code
        # columns (segment-local ords / numeric values), unique the
        # ROWS with counts, and materialize string keys only for the
        # distinct combinations (no per-doc Python — the file's
        # columnar convention)
        mat = np.stack([np.asarray(vals, np.float64)[valid]
                        for _k, _kv, vals, _h in cols], axis=1)
        uniq_rows, row_counts = np.unique(mat, axis=0,
                                          return_counts=True)
        for row, rc in zip(uniq_rows, row_counts):
            key = tuple(
                kv.terms[int(row[j])] if kind == "k" else float(row[j])
                for j, (kind, kv, _v, _h) in enumerate(cols))
            counts[key] = counts.get(key, 0) + int(rc)
    # tie-break per element with a type tag: numeric keys keep NUMERIC
    # order on doc-count ties, while a field mapped keyword in one
    # index and numeric in another still can't raise on comparison
    # (multi-index searches)
    top = sorted(counts.items(),
                 key=lambda kv_: (-kv_[1],
                                  tuple((isinstance(x, str), x)
                                        for x in kv_[0])))[:size]
    buckets = []
    for key, c in top:
        submasks = []
        for seg, docs, cols in seg_rows:
            m = np.zeros(seg.n_docs, bool)
            valid = np.ones(len(docs), bool)
            for (kind, kv, vals, has), want in zip(cols, key):
                if kind == "k":
                    tid = (kv.terms.index(want)
                           if isinstance(want, str)
                           and want in kv.terms else -2)
                    valid &= has & (vals == tid)
                else:
                    valid &= (has & (vals == want)
                              if isinstance(want, float)
                              else np.zeros(len(docs), bool))
            m[docs[valid]] = True
            submasks.append(m)
        buckets.append(_bucket_result(
            sub, _refine(ctx, submasks), mapper, c,
            {"key": list(key),
             "key_as_string": "|".join(str(k) for k in key)}))
    _apply_parent_pipelines(_split_parent_pipelines(sub)[1], buckets)
    return {"buckets": buckets,
            "doc_count_error_upper_bound": 0,
            "sum_other_doc_count": max(0, sum(counts.values())
                                       - sum(c for _, c in top))}


def _significant_text(body, sub, ctx, mapper):
    """ref: bucket/significant/SignificantTextAggregator — re-analyzes
    the text of (a sample of) matched docs, scoring terms JLH against
    the index background (doc_freq from the inverted index). Like the
    reference, sub-aggregations are not supported."""
    if sub:
        raise ParsingException(
            "significant_text does not support sub-aggregations")
    field = body.get("field")
    size = int(body.get("size", 10))
    min_doc_count = int(body.get("min_doc_count", 3))
    shard_size = int(body.get("shard_size", 200))
    filter_dup = bool(body.get("filter_duplicate_text", False))
    import json as _json

    from elasticsearch_tpu.analysis import AnalysisRegistry
    analysis = getattr(mapper, "analysis", None) or AnalysisRegistry()
    # the FIELD's analyzer, not the default — fg terms must live in the
    # same term space as the background postings (the index chain)
    analyzer = analysis.default
    try:
        ft = mapper.field_type(field)
        name = getattr(ft, "analyzer_name", None)
        if name and analysis.has(name):
            analyzer = analysis.get(name)
    except Exception:
        pass
    fg_counts: Dict[str, int] = {}
    fg_total = 0
    bg_df: Dict[str, int] = {}
    bg_total = 0
    seen_text = set()
    for seg, mask, _m in ctx:
        pf = seg.postings.get(field)
        if pf is not None:
            for t, df in zip(pf.terms, pf.doc_freq):
                bg_df[t] = bg_df.get(t, 0) + int(df)
        bg_total += int(seg.live.sum())
        docs = np.nonzero(mask[: seg.n_docs])[0][:shard_size]
        for d in docs:
            try:
                src = _json.loads(seg.stored.source(int(d)))
            except Exception:
                continue
            text = src.get(field)
            if not isinstance(text, str):
                continue
            if filter_dup:
                h = hash(text)
                if h in seen_text:
                    continue
                seen_text.add(h)
            fg_total += 1
            for term in set(analyzer.terms(text)):
                fg_counts[term] = fg_counts.get(term, 0) + 1
    scored = []
    for term, fg in fg_counts.items():
        if fg < min_doc_count:
            continue
        bg = bg_df.get(term, fg)
        fg_rate = fg / max(fg_total, 1)
        bg_rate = bg / max(bg_total, 1)
        if fg_rate <= bg_rate:
            continue
        score = (fg_rate - bg_rate) * (fg_rate / max(bg_rate, 1e-12))
        scored.append((score, term, fg, bg))
    scored.sort(key=lambda t: (-t[0], t[1]))
    return {"doc_count": fg_total, "bg_count": bg_total,
            "buckets": [{"key": term, "doc_count": fg, "score": score,
                         "bg_count": bg}
                        for score, term, fg, bg in scored[:size]]}


def _variable_width_histogram(body, sub, ctx, mapper):
    """ref: bucket/histogram/VariableWidthHistogramAggregator — numeric
    values cluster into at most ``buckets`` variable-width buckets.
    The reference clusters online per shard then merges; here the
    columnar values cluster in one pass (quantile seeding + one k-means
    refinement), which converges to the same shape on settled data."""
    field = body.get("field")
    target = int(body.get("buckets", 10))
    # cluster over the SAME value source the bucket-count pass uses
    # (first value per doc, the range-agg convention) — clustering on
    # all multi-values would shape centroids no doc then lands in
    parts = []
    for seg, mask, _m in ctx:
        vv, m = _first_values_and_mask(seg, mask, field)
        if vv is not None and m.any():
            parts.append(vv[m])
    values = np.sort(np.concatenate(parts)) if parts else np.zeros(0)
    if values.size == 0:
        return {"buckets": []}
    uniq = np.unique(values)
    k = min(target, len(uniq))
    # quantile seeds → one Lloyd pass over the sorted values
    centroids = np.quantile(values, (np.arange(k) + 0.5) / k)
    for _ in range(2):
        bounds = (centroids[:-1] + centroids[1:]) / 2.0
        assign = np.searchsorted(bounds, values)
        centroids = np.array([
            values[assign == i].mean() if (assign == i).any()
            else centroids[i] for i in range(k)])
    bounds = (centroids[:-1] + centroids[1:]) / 2.0
    buckets = []
    for i in range(k):
        lo = -np.inf if i == 0 else bounds[i - 1]
        hi = np.inf if i == k - 1 else bounds[i]
        submasks = []
        count = 0
        bmin, bmax = None, None
        for seg, mask, _m in ctx:
            vv, m = _first_values_and_mask(seg, mask, field)
            if vv is None:
                submasks.append(np.zeros(seg.n_docs, bool))
                continue
            in_b = m & (vv >= lo) & (vv < hi) if i < k - 1 \
                else m & (vv >= lo)
            submasks.append(in_b)
            count += int(in_b.sum())
            if in_b.any():
                lo_v, hi_v = float(vv[in_b].min()), float(vv[in_b].max())
                bmin = lo_v if bmin is None else min(bmin, lo_v)
                bmax = hi_v if bmax is None else max(bmax, hi_v)
        if count == 0:
            continue
        buckets.append(_bucket_result(
            sub, _refine(ctx, submasks), mapper, count,
            {"key": float(centroids[i]), "min": bmin, "max": bmax}))
    _apply_parent_pipelines(_split_parent_pipelines(sub)[1], buckets)
    return {"buckets": buckets}


def _ip_range(body, sub, ctx, mapper):
    """ref: bucket/range/ip/IpRangeAggregator — ranges (or CIDR masks)
    over an ip field; the numeric ip doc values make each range a
    vectorized bound check."""
    import ipaddress
    field = body.get("field")
    buckets = []
    for r in body.get("ranges", []):
        if "mask" in r:
            net = ipaddress.ip_network(r["mask"], strict=False)
            frm = float(int(net.network_address))
            # +1 in INTEGER space before the float conversion: at IPv6
            # magnitudes a float +1.0 is a no-op (the stored ip doc
            # values share the mapper's float representation, so IPv6
            # boundaries are as precise as the storage — IPv4 is exact)
            to = float(int(net.broadcast_address) + 1)
            key = r.get("key", r["mask"])
        else:
            frm = (float(int(ipaddress.ip_address(r["from"])))
                   if r.get("from") is not None else None)
            to = (float(int(ipaddress.ip_address(r["to"])))
                  if r.get("to") is not None else None)
            key = r.get("key",
                        f"{r.get('from', '*')}-{r.get('to', '*')}")
        submasks = []
        count = 0
        for seg, mask, _m in ctx:
            vv, m = _first_values_and_mask(seg, mask, field)
            if vv is None:
                submasks.append(np.zeros(seg.n_docs, bool))
                continue
            in_r = m.copy()
            if frm is not None:
                in_r &= vv >= frm
            if to is not None:
                in_r &= vv < to
            submasks.append(in_r)
            count += int(in_r.sum())
        extra = {"key": key}
        if "mask" in r:
            extra["mask"] = r["mask"]
        if r.get("from") is not None:
            extra["from"] = r["from"]
        if r.get("to") is not None:
            extra["to"] = r["to"]
        buckets.append(_bucket_result(sub, _refine(ctx, submasks),
                                      mapper, count, extra))
    return {"buckets": buckets}


def _children_parent(agg_type, body, sub, ctx, mapper):
    """ref: modules/parent-join join/aggregations —
    ParentToChildrenAggregator (``children``: buckets switch from
    matched parents to their children of the given type) and
    ChildrenToParentAggregator (``parent``: from matched children of
    the given type to their parents). The shard-local join rides the
    same ``{field}#parent`` keyword doc values as has_child/has_parent
    (search/join.py), vectorized through ordinal membership."""
    from elasticsearch_tpu.index.mapper import JoinFieldType
    jf = None
    for ft in mapper.mapper.fields.values():
        if isinstance(ft, JoinFieldType):
            jf = ft
            break
    if jf is None:
        raise ParsingException(
            f"[{agg_type}] aggregation requires a [join] field in the "
            "mapping")
    rel_type = body.get("type")
    if not rel_type:
        raise ParsingException(f"[{agg_type}] requires [type]")
    if jf.parent_of(rel_type) is None:
        raise ParsingException(
            f"unknown join relation type [{rel_type}] for [{agg_type}]")
    from elasticsearch_tpu.search.join import _relation_docs

    # pass 1 — collect the join keys across ALL segments (a parent and
    # its children may live in different segments; has_child/has_parent
    # do the same two-pass join)
    keys: set = set()
    for seg, mask, _m in ctx:
        if agg_type == "children":
            keys.update(seg.stored.ids[int(d)]
                        for d in np.nonzero(mask[: seg.n_docs])[0])
        else:
            pkv = seg.keywords.get(f"{jf.name}#parent")
            if pkv is None:
                continue
            is_child = _relation_docs(seg, jf.name, [rel_type])
            child_docs = np.nonzero(mask[: seg.n_docs] & is_child)[0]
            keys.update(pkv.terms[int(o)]
                        for o in pkv.ords[child_docs] if o >= 0)
    # pass 2 — resolve the keys on every segment, live docs only
    submasks = []
    count = 0
    for seg, mask, _m in ctx:
        out = np.zeros(seg.n_docs, bool)
        if agg_type == "children":
            pkv = seg.keywords.get(f"{jf.name}#parent")
            if pkv is not None:
                want_ords = np.asarray(
                    [i for i, t in enumerate(pkv.terms) if t in keys],
                    np.int64)
                out = (_relation_docs(seg, jf.name, [rel_type])
                       & np.isin(pkv.ords[: seg.n_docs], want_ords))
        else:
            for pid in keys:
                d = seg.docid_for(pid)
                if d >= 0:
                    out[d] = True
        out &= seg.live[: seg.n_docs]
        submasks.append(out)
        count += int(out.sum())
    bucket_ctx = _refine([(seg, np.ones(seg.n_docs, bool) & seg.live, m)
                          for seg, _msk, m in ctx], submasks)
    out_doc = {"doc_count": count}
    if sub:
        out_doc.update(_compute_aggs(sub, bucket_ctx, mapper))
    return out_doc


def _bucket(agg_type, body, sub, ctx, mapper):
    if agg_type in ("children", "parent"):
        return _children_parent(agg_type, body, sub, ctx, mapper)
    if agg_type == "rare_terms":
        return _rare_terms(body, sub, ctx, mapper)
    if agg_type == "multi_terms":
        return _multi_terms(body, sub, ctx, mapper)
    if agg_type == "significant_text":
        return _significant_text(body, sub, ctx, mapper)
    if agg_type == "variable_width_histogram":
        return _variable_width_histogram(body, sub, ctx, mapper)
    if agg_type == "ip_range":
        return _ip_range(body, sub, ctx, mapper)
    if agg_type == "significant_terms":
        return _significant_terms(body, sub, ctx, mapper)
    if agg_type == "adjacency_matrix":
        # ref: bucket/adjacency/AdjacencyMatrixAggregator — one bucket
        # per named filter plus one per intersecting pair (A&B)
        from elasticsearch_tpu.search.queries import parse_query
        filters = body.get("filters", {})
        sep = body.get("separator", "&")
        masks = {}
        for fname, fspec in filters.items():
            q = parse_query(fspec)
            masks[fname] = _query_masks(q, ctx, mapper)
        names = sorted(masks)
        buckets = []
        for i, a in enumerate(names):
            bucket_ctx = _refine(ctx, masks[a])
            count = sum(int(m.sum()) for _, m, _x in bucket_ctx)
            if count:
                buckets.append(_bucket_result(sub, bucket_ctx, mapper,
                                              count, {"key": a}))
            for bname in names[i + 1:]:
                inter = [ma & mb for ma, mb in zip(masks[a],
                                                   masks[bname])]
                bucket_ctx = _refine(ctx, inter)
                count = sum(int(m.sum()) for _, m, _x in bucket_ctx)
                if count:
                    buckets.append(_bucket_result(
                        sub, bucket_ctx, mapper, count,
                        {"key": f"{a}{sep}{bname}"}))
        _apply_parent_pipelines(_split_parent_pipelines(sub)[1], buckets)
        return {"buckets": buckets}
    if agg_type in ("sampler", "diversified_sampler"):
        # ref: bucket/sampler/SamplerAggregator — restrict sub-aggs to
        # the first shard_size matched docs per shard/segment;
        # diversified_sampler additionally caps docs sharing one value
        # of `field` (DiversifiedBytesHashSamplerAggregator)
        shard_size = int(body.get("shard_size", 100))
        div_field = (body.get("field")
                     if agg_type == "diversified_sampler" else None)
        max_per_value = int(body.get("max_docs_per_value", 1))
        submasks = []
        for seg, mask, _m in ctx:
            docs = np.nonzero(mask[: seg.n_docs])[0]
            if div_field is not None:
                per_value: Dict[Any, int] = {}
                picked = []
                kv = seg.keywords.get(div_field)
                nv = seg.numerics.get(div_field)
                for d in docs:
                    if kv is not None:
                        vals = tuple(kv.get(int(d))) or ("",)
                    elif nv is not None and not nv.missing[d]:
                        vals = (float(nv.values[d]),)
                    else:
                        vals = ("",)
                    if any(per_value.get(v, 0) >= max_per_value
                           for v in vals):
                        continue
                    for v in vals:
                        per_value[v] = per_value.get(v, 0) + 1
                    picked.append(int(d))
                    if len(picked) >= shard_size:
                        break
                docs = np.asarray(picked, np.int64)
            else:
                docs = docs[:shard_size]
            sm = np.zeros(seg.n_docs, bool)
            sm[docs] = True
            submasks.append(sm)
        bucket_ctx = _refine(ctx, submasks)
        return _bucket_result(
            sub, bucket_ctx, mapper,
            sum(int(m.sum()) for _, m, _x in bucket_ctx), {})
    if agg_type == "nested":
        # ref: bucket/nested/NestedAggregator — doc_count is the number
        # of NESTED OBJECTS under the path across matched docs. Columns
        # here are flattened (every object's values are already in the
        # parent doc's multi-value slots), so sub-agg values match the
        # reference; the object count reads the ragged offsets of any
        # subfield under the path.
        path = body.get("path", "")
        prefix = path + "."
        n_objects = 0
        for seg, mask, _m in ctx:
            counts = None
            for fname, nv in seg.numerics.items():
                if fname.startswith(prefix):
                    c = (nv.offsets[1:] - nv.offsets[:-1])
                    counts = c if counts is None else np.maximum(counts, c)
            for fname, kv in seg.keywords.items():
                if fname.startswith(prefix):
                    c = (kv.offsets[1:] - kv.offsets[:-1])
                    counts = c if counts is None else np.maximum(counts, c)
            if counts is not None:
                n_objects += int(counts[mask[: seg.n_docs]].sum())
        out = {"doc_count": n_objects}
        if sub:
            out.update(_compute_aggs(sub, ctx, mapper))
        return out
    if agg_type == "composite":
        return _composite(body, sub, ctx, mapper)
    if agg_type == "global":
        # ignores the query mask entirely (ref: GlobalAggregator)
        global_ctx = [(seg, seg.live.copy(), m) for seg, _msk, m in ctx]
        out = {"doc_count": sum(int(msk.sum()) for _, msk, _m in global_ctx)}
        if sub:
            out.update(_compute_aggs(sub, global_ctx, mapper))
        return out

    if agg_type == "filter":
        from elasticsearch_tpu.search.queries import parse_query
        q = parse_query(body)
        submasks = _query_masks(q, ctx, mapper)
        bucket_ctx = _refine(ctx, submasks)
        return _bucket_result(sub, bucket_ctx,  mapper,
                              sum(int(msk.sum()) for _, msk, _m in bucket_ctx), {})

    if agg_type == "filters":
        from elasticsearch_tpu.search.queries import parse_query
        filters = body.get("filters", {})
        buckets = {}
        for fname, fspec in filters.items():
            q = parse_query(fspec)
            bucket_ctx = _refine(ctx, _query_masks(q, ctx, mapper))
            buckets[fname] = _bucket_result(
                sub, bucket_ctx, mapper,
                sum(int(msk.sum()) for _, msk, _m in bucket_ctx), {})
        return {"buckets": buckets}

    if agg_type == "missing":
        field = body.get("field")
        submasks = []
        for seg, mask, _m in ctx:
            present = np.zeros(seg.n_docs, bool)
            nv = seg.numerics.get(field)
            if nv is not None:
                present |= ~nv.missing
            kv = seg.keywords.get(field)
            if kv is not None:
                present |= (kv.offsets[1:] - kv.offsets[:-1]) > 0
            pf = seg.postings.get(field)
            if pf is not None:
                present |= pf.field_lengths > 0
            submasks.append(~present)
        bucket_ctx = _refine(ctx, submasks)
        return _bucket_result(sub, bucket_ctx, mapper,
                              sum(int(msk.sum()) for _, msk, _m in bucket_ctx), {})

    if agg_type == "terms":
        field = body.get("field")
        size = int(body.get("size", 10))
        order = body.get("order", {"_count": "desc"})
        counts = _keyword_terms_counts(ctx, field)
        if not counts:
            # numeric terms agg
            return _numeric_terms(body, sub, ctx, mapper)
        (order_key, order_dir), = (order.items() if isinstance(order, dict)
                                   else [("_count", "desc")])
        rev = order_dir == "desc"
        if order_key == "_count":
            items = sorted(counts.items(), key=lambda kv_: (-kv_[1] if rev else kv_[1], kv_[0]))
        else:  # _key
            items = sorted(counts.items(), key=lambda kv_: kv_[0], reverse=rev)
        buckets = []
        for term, count in items[:size]:
            bucket_ctx = _refine(
                ctx, [_keyword_membership_mask(seg, field, term)
                      for seg, _m2, _m3 in ctx])
            buckets.append(_bucket_result(sub, bucket_ctx, mapper, count,
                                          {"key": term}))
        other = sum(c for _, c in items[size:])
        _apply_parent_pipelines(_split_parent_pipelines(sub)[1], buckets)
        return {"doc_count_error_upper_bound": 0,
                "sum_other_doc_count": other, "buckets": buckets}

    if agg_type == "auto_date_histogram":
        # ref: bucket/histogram/AutoDateHistogramAggregationBuilder —
        # pick the smallest rounding whose bucket count fits `buckets`
        field = body.get("field")
        target = int(body.get("buckets", 10))
        lo = hi = None
        for seg, mask, _m in ctx:
            vv, m = _first_values_and_mask(seg, mask, field)
            if vv is None or not m.any():
                continue
            vals = vv[m]
            lo = float(vals.min()) if lo is None else min(lo, vals.min())
            hi = float(vals.max()) if hi is None else max(hi, vals.max())
        if lo is None:
            return {"buckets": [], "interval": "1s"}
        # interval lengths come from the ONE table the bucketing itself
        # uses (_INTERVALS_MS) so the estimate and the buckets agree
        ladder = [("1s", {"fixed_interval": "1s"}),
                  ("1m", {"fixed_interval": "1m"}),
                  ("1h", {"fixed_interval": "1h"}),
                  ("1d", {"fixed_interval": "1d"}),
                  ("7d", {"calendar_interval": "week"}),
                  ("1M", {"calendar_interval": "month"}),
                  ("1q", {"calendar_interval": "quarter"}),
                  ("1y", {"calendar_interval": "year"})]
        chosen_label, chosen = ladder[-1]
        label_to_key = {"7d": "week", "1q": "quarter"}
        for label, spec in ladder:
            unit = _INTERVALS_MS[label_to_key.get(label, label)]
            # worst-case bucket count with floor-based bucketing is
            # floor(hi/i) - floor(lo/i) + 1
            count = (int(np.floor(hi / unit)) - int(np.floor(lo / unit))
                     + 1)
            if count <= target:
                chosen_label, chosen = label, spec
                break
        inner = dict(chosen)
        inner["field"] = field
        # contiguous buckets with zero-count gap fill, matching
        # InternalAutoDateHistogram's reduce
        inner["min_doc_count"] = 0
        out = _bucket("date_histogram", inner, sub, ctx, mapper)
        out["interval"] = chosen_label
        return out

    if agg_type in ("histogram", "date_histogram"):
        field = body.get("field")
        cal_unit = (_calendar_unit(body) if agg_type == "date_histogram"
                    else None)
        if agg_type == "histogram":
            interval = float(body["interval"])
        elif cal_unit is None:
            interval = _date_interval_ms(body)
        min_doc_count = int(body.get("min_doc_count", 0))
        # work in INTEGER step space so bucket membership is exact — for
        # fixed intervals step = floor(v / interval); calendar intervals
        # (year/quarter/month/week) floor to true calendar boundaries
        if cal_unit is not None:
            def step_of(vv):
                return _calendar_floor_ms(vv, cal_unit).astype(np.int64)

            def key_of(step):
                return float(step)
        else:
            def step_of(vv):
                return np.floor(vv / interval).astype(np.int64)

            def key_of(step):
                return step * interval
        regular_sub, parent_pipes = (_split_parent_pipelines(sub)
                                     if sub else ({}, {}))
        if cal_unit is None:
            # device-side batched bucketing (ops/aggs.py scatter-add):
            # bucket-id arithmetic stays host f64-exact, the reduction
            # — counts AND the per-bucket sub-metric columns — runs in
            # one launch per (segment, column). Fixed intervals only
            # (calendar steps are epoch-ms keys, not a dense id space);
            # None falls through to the exact host path unchanged.
            dev_buckets = _device_histogram_buckets(
                ctx, field, interval, min_doc_count,
                gap_fill=(body.get("extended_bounds") is None
                          and min_doc_count == 0),
                key_of=key_of, is_date=(agg_type == "date_histogram"),
                regular_sub=regular_sub)
            if dev_buckets is not None:
                _apply_parent_pipelines(parent_pipes, dev_buckets)
                return {"buckets": dev_buckets}
        step_counts: Dict[int, int] = {}
        for seg, mask, _m in ctx:
            vv, m = _first_values_and_mask(seg, mask, field)
            if vv is None:
                continue
            uniq, cnts = np.unique(step_of(vv[m]), return_counts=True)
            for u, c in zip(uniq, cnts):
                step_counts[int(u)] = step_counts.get(int(u), 0) + int(c)
        buckets = []
        all_steps = sorted(step_counts)
        if all_steps and body.get("extended_bounds") is None and min_doc_count == 0:
            # fill gaps between min and max (ES default for histograms),
            # capped — a sparse value pair must not OOM the node
            if cal_unit is not None:
                filled, cur = [], all_steps[0]
                while cur <= all_steps[-1]:
                    filled.append(cur)
                    _check_bucket_cap(len(filled), agg_type)
                    cur = _calendar_next_ms(cur, cal_unit)
                all_steps = filled
            else:
                _check_bucket_cap(all_steps[-1] - all_steps[0] + 1,
                                  agg_type)
                all_steps = list(range(all_steps[0], all_steps[-1] + 1))
        for step in all_steps:
            count = step_counts.get(step, 0)
            if count < min_doc_count:
                continue
            key = key_of(step)
            extra = {"key": key}
            if agg_type == "date_histogram":
                extra["key_as_string"] = _ms_to_iso(key)
            if regular_sub:
                # per-bucket doc masks only when sub-aggs need them —
                # counts came from the one-pass unique above
                submasks = []
                for seg, mask, _m in ctx:
                    vv, m = _first_values_and_mask(seg, mask, field)
                    if vv is None:
                        submasks.append(np.zeros(seg.n_docs, bool))
                        continue
                    submasks.append(m & (step_of(vv) == step))
                bucket_ctx = _refine(ctx, submasks)
            else:
                bucket_ctx = ctx
            buckets.append(_bucket_result(sub, bucket_ctx, mapper, count, extra))
        _apply_parent_pipelines(parent_pipes, buckets)
        return {"buckets": buckets}

    if agg_type == "range":
        field = body.get("field")
        ranges = body.get("ranges", [])
        buckets = []
        for r in ranges:
            frm = r.get("from")
            to = r.get("to")
            submasks = []
            count = 0
            for seg, mask, _m in ctx:
                vv, m = _first_values_and_mask(seg, mask, field)
                if vv is None:
                    submasks.append(np.zeros(seg.n_docs, bool))
                    continue
                in_r = m.copy()
                if frm is not None:
                    in_r &= vv >= float(frm)
                if to is not None:
                    in_r &= vv < float(to)
                submasks.append(in_r)
                count += int(in_r.sum())
            key = r.get("key", f"{frm if frm is not None else '*'}-"
                               f"{to if to is not None else '*'}")
            extra = {"key": key}
            if frm is not None:
                extra["from"] = float(frm)
            if to is not None:
                extra["to"] = float(to)
            buckets.append(_bucket_result(sub, _refine(ctx, submasks), mapper,
                                          count, extra))
        return {"buckets": buckets}

    if agg_type == "date_range":
        # ref: bucket/range/DateRangeAggregationBuilder.java:39 — range
        # buckets over a date field; from/to accept epoch millis, the
        # mapper's date formats, and `now` date math (now-7d, now+1h/d)
        field = body.get("field")
        ft = mapper.field_type(field) if mapper is not None else None

        def to_ms(v):
            if v is None:
                return None
            if isinstance(v, (int, float)):
                return float(v)
            s = str(v)
            m = re.fullmatch(
                r"now(?:([+-]\d+)([smhdwMy]))?(?:/([smhdwMy]))?", s)
            if m:
                import time as _time
                ms = _time.time() * 1000.0
                if m.group(1):
                    mult = {"s": 1e3, "m": 60e3, "h": 3600e3,
                            "d": 86400e3, "w": 7 * 86400e3,
                            "M": 30 * 86400e3, "y": 365 * 86400e3}
                    ms += int(m.group(1)) * mult[m.group(2)]
                if m.group(3):      # rounding: floor to the unit start
                    u = m.group(3)
                    if u in ("w", "M", "y"):
                        # REAL calendar boundaries (ISO weeks, month
                        # and year starts) — the fixed-size flooring
                        # the smaller units use would land mid-month
                        cal = {"w": "week", "M": "month",
                               "y": "year"}[u]
                        ms = float(_calendar_floor_ms(
                            np.array([ms]), cal)[0])
                    else:
                        fixed = {"s": 1e3, "m": 60e3, "h": 3600e3,
                                 "d": 86400e3}[u]
                        ms = math.floor(ms / fixed) * fixed
                return ms
            if ft is not None and hasattr(ft, "parse"):
                return float(ft.parse(s))
            raise IllegalArgumentException(
                f"cannot parse date range bound [{v}]")

        buckets = []
        for r in body.get("ranges", []):
            frm = to_ms(r.get("from"))
            to = to_ms(r.get("to"))
            submasks = []
            count = 0
            for seg, mask, _m in ctx:
                vv, m = _first_values_and_mask(seg, mask, field)
                if vv is None:
                    submasks.append(np.zeros(seg.n_docs, bool))
                    continue
                in_r = m.copy()
                if frm is not None:
                    in_r &= vv >= frm
                if to is not None:
                    in_r &= vv < to
                submasks.append(in_r)
                count += int(in_r.sum())
            frm_s = _ms_to_iso(frm) if frm is not None else "*"
            to_s = _ms_to_iso(to) if to is not None else "*"
            extra = {"key": r.get("key", f"{frm_s}-{to_s}")}
            if frm is not None:
                extra["from"] = frm
                extra["from_as_string"] = frm_s
            if to is not None:
                extra["to"] = to
                extra["to_as_string"] = to_s
            buckets.append(_bucket_result(sub, _refine(ctx, submasks),
                                          mapper, count, extra))
        return {"buckets": buckets}

    if agg_type == "geo_distance":
        # ref: bucket/range/GeoDistanceAggregationBuilder — range buckets
        # keyed by haversine distance from an origin
        from elasticsearch_tpu.common.geo import (
            haversine_meters, parse_geo_point, _UNITS)
        field = body.get("field")
        o_lat, o_lon = parse_geo_point(body.get("origin"))
        unit = body.get("unit", "m")
        scale = _UNITS.get(unit)
        if scale is None:
            raise IllegalArgumentException(
                f"unknown distance unit [{unit}] for geo_distance aggregation")
        buckets = []
        for r in body.get("ranges", []):
            frm = r.get("from")
            to = r.get("to")
            submasks = []
            count = 0
            for seg, mask, _m in ctx:
                nlat = seg.numerics.get(f"{field}.lat")
                nlon = seg.numerics.get(f"{field}.lon")
                if nlat is None or nlon is None:
                    submasks.append(np.zeros(seg.n_docs, bool))
                    continue
                dist = haversine_meters(nlat.values, nlon.values, o_lat, o_lon)
                in_r = mask[: seg.n_docs] & ~nlat.missing
                if frm is not None:
                    in_r &= dist >= float(frm) * scale
                if to is not None:
                    in_r &= dist < float(to) * scale
                submasks.append(in_r)
                count += int(in_r.sum())
            key = r.get("key", f"{frm if frm is not None else '*'}-"
                               f"{to if to is not None else '*'}")
            extra = {"key": key}
            if frm is not None:
                extra["from"] = float(frm)
            if to is not None:
                extra["to"] = float(to)
            buckets.append(_bucket_result(sub, _refine(ctx, submasks), mapper,
                                          count, extra))
        return {"buckets": buckets}

    if agg_type in ("geohash_grid", "geotile_grid"):
        # ref: bucket/geogrid/GeoHashGridAggregator / GeoTileGridAggregator
        from elasticsearch_tpu.common.geo import geohash_cells, geotile_cells
        field = body.get("field")
        default_p = 5 if agg_type == "geohash_grid" else 7
        precision = int(body.get("precision", default_p))
        size = int(body.get("size", 10000))
        cell_fn = geohash_cells if agg_type == "geohash_grid" else geotile_cells
        counts: Dict[str, int] = {}
        per_seg_cells = []
        for seg, mask, _m in ctx:
            nlat = seg.numerics.get(f"{field}.lat")
            nlon = seg.numerics.get(f"{field}.lon")
            if nlat is None or nlon is None:
                per_seg_cells.append(None)
                continue
            m = mask[: seg.n_docs] & ~nlat.missing
            cells = np.full(seg.n_docs, "", f"U{max(precision, 16)}")
            if m.any():
                cells[m] = cell_fn(nlat.values[m], nlon.values[m], precision)
            per_seg_cells.append(cells)
            for c, n in zip(*np.unique(cells[m], return_counts=True)):
                counts[str(c)] = counts.get(str(c), 0) + int(n)
        top = sorted(counts.items(), key=lambda kv_: (-kv_[1], kv_[0]))[:size]
        buckets = []
        for cell, count in top:
            submasks = [
                (cells == cell) if cells is not None
                else np.zeros(seg.n_docs, bool)
                for (seg, _m2, _m3), cells in zip(ctx, per_seg_cells)]
            buckets.append(_bucket_result(sub, _refine(ctx, submasks), mapper,
                                          count, {"key": cell}))
        return {"buckets": buckets}

    raise IllegalArgumentException(f"unhandled bucket agg [{agg_type}]")


def _numeric_terms(body, sub, ctx, mapper):
    field = body.get("field")
    size = int(body.get("size", 10))
    counts: Dict[float, int] = {}
    for seg, mask, _m in ctx:
        nv = seg.numerics.get(field)
        if nv is None:
            continue
        m = mask[: seg.n_docs] & ~nv.missing
        vals, cnts = np.unique(nv.values[m], return_counts=True)
        for v, c in zip(vals, cnts):
            counts[float(v)] = counts.get(float(v), 0) + int(c)
    items = sorted(counts.items(), key=lambda kv_: (-kv_[1], kv_[0]))[:size]
    buckets = []
    for val, count in items:
        submasks = []
        for seg, _m2, _m3 in ctx:
            nv = seg.numerics.get(field)
            if nv is None:
                submasks.append(np.zeros(seg.n_docs, bool))
            else:
                submasks.append(~nv.missing & (nv.values == val))
        key = int(val) if float(val).is_integer() else val
        buckets.append(_bucket_result(sub, _refine(ctx, submasks), mapper,
                                      count, {"key": key}))
    other = sum(c for _, c in sorted(counts.items(),
                                     key=lambda kv_: (-kv_[1], kv_[0]))[size:])
    _apply_parent_pipelines(_split_parent_pipelines(sub)[1], buckets)
    return {"doc_count_error_upper_bound": 0, "sum_other_doc_count": other,
            "buckets": buckets}


def _query_masks(q, ctx: CollectCtx, mapper) -> List[np.ndarray]:
    """Execute a filter query per segment, returning host masks."""
    from elasticsearch_tpu.search.context import SegmentContext, ShardStats
    from elasticsearch_tpu.search.context import DeviceSegmentCache

    # lightweight: reuse the segments' device state via a throwaway cache
    # (SegmentContext needs a DeviceSegment; the global cache is preferred
    # but not reachable from here — callers pass mapper with analysis)
    masks = []
    cache = _DEVICE_CACHE.get() or _query_masks._fallback_cache
    stats = ShardStats([seg for seg, _m2, _m3 in ctx])
    for seg, _m2, _m3 in ctx:
        sctx = SegmentContext(seg, cache.get(seg), mapper, stats)
        _, mask = q.execute(sctx)
        masks.append(np.asarray(mask)[: seg.n_docs])
    return masks


# fallback cache for callers that pass no device cache (tests, tools)
from elasticsearch_tpu.search.context import DeviceSegmentCache as _DSC  # noqa: E402

_query_masks._fallback_cache = _DSC()


# calendar units whose bucket length varies — these floor to true calendar
# boundaries instead of fixed-ms multiples (ref: Rounding.java calendar
# rounding vs fixed-interval rounding)
_CALENDAR_UNITS = {"year": "year", "1y": "year", "quarter": "quarter",
                   "1q": "quarter", "month": "month", "1M": "month",
                   "week": "week", "1w": "week"}


def _calendar_unit(body) -> Optional[str]:
    v = body.get("calendar_interval")
    return _CALENDAR_UNITS.get(v) if v is not None else None


def _calendar_floor_ms(values, unit: str) -> np.ndarray:
    """Floor epoch-ms values to calendar bucket starts (UTC)."""
    ms = np.nan_to_num(np.asarray(values, np.float64)).astype(np.int64)
    dt = ms.astype("datetime64[ms]")
    if unit == "year":
        start = dt.astype("datetime64[Y]")
    elif unit == "month":
        start = dt.astype("datetime64[M]")
    elif unit == "quarter":
        m = dt.astype("datetime64[M]").astype(np.int64)
        start = (m - (m % 3)).astype("datetime64[M]")
    else:  # week: ISO weeks start Monday (epoch 1970-01-01 is a Thursday)
        days = ms // 86_400_000
        dow = (days + 3) % 7
        start = ((days - dow) * 86_400_000).astype("datetime64[ms]")
    return start.astype("datetime64[ms]").astype(np.int64).astype(np.float64)


def _calendar_next_ms(ms: float, unit: str) -> int:
    """Start of the NEXT calendar bucket after bucket-start `ms`."""
    d = np.datetime64(int(ms), "ms")
    if unit == "year":
        n = d.astype("datetime64[Y]") + 1
    elif unit == "month":
        n = d.astype("datetime64[M]") + 1
    elif unit == "quarter":
        n = d.astype("datetime64[M]") + 3
    else:
        return int(ms) + 604_800_000
    return int(n.astype("datetime64[ms]").astype(np.int64))


_INTERVALS_MS = {
    "second": 1000, "1s": 1000, "minute": 60_000, "1m": 60_000,
    "hour": 3_600_000, "1h": 3_600_000, "day": 86_400_000, "1d": 86_400_000,
    "week": 604_800_000, "1w": 604_800_000, "month": 2_592_000_000,
    "1M": 2_592_000_000, "quarter": 7_776_000_000, "year": 31_536_000_000,
    "1y": 31_536_000_000,
}


def _date_interval_ms(body) -> float:
    for key in ("calendar_interval", "fixed_interval", "interval"):
        if key in body:
            val = body[key]
            if val in _INTERVALS_MS:
                return float(_INTERVALS_MS[val])
            # fixed forms like "30m", "12h", "500ms"
            import re
            m = re.fullmatch(r"(\d+)(ms|s|m|h|d)", str(val))
            if m:
                mult = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000,
                        "d": 86_400_000}[m.group(2)]
                return float(int(m.group(1)) * mult)
            raise ParsingException(f"unknown interval [{val}]")
    raise ParsingException("date_histogram requires an interval")


def _ms_to_iso(ms: float) -> str:
    import datetime as dt
    return dt.datetime.fromtimestamp(ms / 1000.0, dt.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.000Z")


# ---------------------------------------------------------------------------
# pipeline aggs (operate on sibling agg results, ref: search/aggregations/
# pipeline/)
# ---------------------------------------------------------------------------

def _extract_bucket_values(path: str, results: Dict[str, Any]) -> List[float]:
    agg_name, _, metric = path.partition(">")
    agg = results.get(agg_name)
    if agg is None or "buckets" not in agg:
        raise IllegalArgumentException(
            f"No bucket aggregation found at path [{path}]")
    values = []
    buckets = agg["buckets"]
    iterable = buckets.values() if isinstance(buckets, dict) else buckets
    for b in iterable:
        if metric:
            node = b.get(metric.strip())
            values.append(node.get("value") if isinstance(node, dict) else None)
        else:
            values.append(b.get("doc_count"))
    return [v for v in values if v is not None]


def _compute_pipeline(agg_type, body, results):
    path = body.get("buckets_path", "")
    if agg_type == "cumulative_sum":
        agg_name, _, metric = path.partition(">")
        agg = results.get(agg_name, {})
        cum = 0.0
        for b in agg.get("buckets", []):
            v = (b.get(metric, {}).get("value") if metric else b.get("doc_count")) or 0.0
            cum += v
            b["cumulative_sum"] = {"value": cum}
        return {"value": cum}
    if agg_type == "derivative":
        agg_name, _, metric = path.partition(">")
        agg = results.get(agg_name, {})
        prev = None
        for b in agg.get("buckets", []):
            v = (b.get(metric, {}).get("value") if metric else b.get("doc_count"))
            if prev is not None and v is not None:
                b["derivative"] = {"value": v - prev}
            prev = v
        return {"value": None}
    if agg_type == "cumulative_cardinality":
        # ref: x-pack/plugin/analytics CumulativeCardinality — running
        # distinct count over a sibling histogram's cardinality sub-aggs
        # (exact here: union of the carried value sets)
        agg_name, _, metric = path.partition(">")
        agg = results.get(agg_name, {})
        seen: set = set()
        for b in agg.get("buckets", []):
            s = b.get(metric, {}).get("_set")
            if s is not None:
                seen |= s
            b["cumulative_cardinality"] = {"value": len(seen)}
        return {"value": len(seen)}
    if agg_type == "bucket_sort":
        return {}
    values = _extract_bucket_values(path, results)
    if not values:
        # multi-value pipelines keep their response SHAPE on empty
        # input (ref: the reference's null-filled InternalPercentiles
        # Bucket / InternalExtendedStatsBucket)
        if agg_type == "percentiles_bucket":
            pcts = body.get("percents") or [1.0, 5.0, 25.0, 50.0, 75.0,
                                            95.0, 99.0]
            return {"values": {str(float(p)): None for p in pcts}}
        if agg_type == "extended_stats_bucket":
            return {"count": 0, "min": None, "max": None, "avg": None,
                    "sum": 0.0, "sum_of_squares": None,
                    "variance": None, "std_deviation": None,
                    "std_deviation_bounds": {"upper": None,
                                             "lower": None}}
        return {"value": None}
    if agg_type == "avg_bucket":
        return {"value": float(np.mean(values))}
    if agg_type == "sum_bucket":
        return {"value": float(np.sum(values))}
    if agg_type == "min_bucket":
        return {"value": float(np.min(values))}
    if agg_type == "max_bucket":
        return {"value": float(np.max(values))}
    if agg_type == "stats_bucket":
        arr = np.asarray(values, float)
        return {"count": len(arr), "min": float(arr.min()),
                "max": float(arr.max()), "avg": float(arr.mean()),
                "sum": float(arr.sum())}
    if agg_type == "extended_stats_bucket":
        # ref: pipeline/ExtendedStatsBucketPipelineAggregator
        arr = np.asarray(values, float)
        sigma = float(body.get("sigma", 2.0))
        mean = float(arr.mean())
        var = float(arr.var())
        std = float(np.sqrt(var))
        return {"count": len(arr), "min": float(arr.min()),
                "max": float(arr.max()), "avg": mean,
                "sum": float(arr.sum()),
                "sum_of_squares": float((arr * arr).sum()),
                "variance": var, "std_deviation": std,
                "std_deviation_bounds": {
                    "upper": mean + sigma * std,
                    "lower": mean - sigma * std}}
    if agg_type == "percentiles_bucket":
        # ONE percentile semantics engine-wide: linear interpolation,
        # the same estimator the `percentiles` metric (and its digest's
        # exact mode) uses. The reference's PercentilesBucket returns
        # the nearest input point instead — this engine deliberately
        # diverges so a percentile over bucket metrics and a percentile
        # over doc values can never disagree on identical series
        # (pinned by test_percentile_interpolation_consistency; see
        # COMPONENTS.md "Distributed aggregations").
        pcts = body.get("percents") or [1.0, 5.0, 25.0, 50.0, 75.0,
                                        95.0, 99.0]
        arr = np.asarray(values, float)
        return {"values": {str(float(p)): float(np.percentile(arr, p))
                           for p in pcts}}
    raise IllegalArgumentException(f"unhandled pipeline agg [{agg_type}]")
