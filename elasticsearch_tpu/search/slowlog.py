"""Search slow log: one threshold check shared by the single-node
service and the distributed coordinator.

Ref: index/SearchSlowLog.java — per-index, per-level thresholds under
``index.search.slowlog.threshold.query.{warn,info,debug,trace}``; -1
disables a level. The reference logs on the shard; this engine applies
the same thresholds to whichever side measured the took time — the
in-process `SearchService` (search/service.py) and the coordinator
(`cluster/search_action.py`), both of which keep a bounded
``slowlog_recent`` list of entries in ONE shared shape::

    {"index": name, "took_ms": int, "level": "warn", "source": "..."}

so `_nodes/stats`-style surfaces and tests read either side the same
way.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Callable, Dict, List, Optional

_slowlog_logger = logging.getLogger("index.search.slowlog")

LEVELS = ("warn", "info", "debug", "trace")
_LEVEL_NUM = {"warn": 30, "info": 20, "debug": 10, "trace": 5}

MAX_RECENT = 128


def slowest_stage_summary(response: Optional[Dict[str, Any]]
                          ) -> Optional[str]:
    """One-line summary of the slowest profile stage of a finished
    search response (``"launch 1.24ms [idx][0]"``), or None when the
    response carries no profile section — the slowlog → `_traces` →
    profile navigation hook."""
    profile = (response or {}).get("profile") or {}
    worst: Optional[tuple] = None
    shard_fetch_seen = False
    for shard in profile.get("shards", []):
        try:
            bd = shard["searches"][0]["query"][0]["breakdown"]
        except (KeyError, IndexError, TypeError):
            continue
        for stage, ns in bd.items():
            if stage.endswith("_time_in_nanos") \
                    or not isinstance(ns, (int, float)):
                continue
            if worst is None or ns > worst[0]:
                worst = (ns, stage, shard.get("id", "?"))
        fetch = shard.get("fetch")
        if fetch:
            shard_fetch_seen = True
            if worst is None or fetch["time_in_nanos"] > worst[0]:
                worst = (fetch["time_in_nanos"], "fetch",
                         shard.get("id", "?"))
    # coordinator phases compete on equal terms (same ns unit): a
    # dominant reduce/aggs merge must win over small shard stages.
    # WRAPPING phases are excluded — charging them against the stages
    # they wrap would always blame the coordinator for shard time:
    # query_ns always wraps the shard stages, and fetch_ns wraps the
    # per-shard fetch entries whenever the shards carry them (the
    # single-node path; the distributed fetch phase has no per-shard
    # entries and competes as its own cost).
    phases = (profile.get("coordinator") or {}).get("phases") or {}
    for stage, ns in phases.items():
        if stage == "query_ns" or (stage == "fetch_ns"
                                   and shard_fetch_seen):
            continue
        if worst is None or ns > worst[0]:
            worst = (ns, stage.replace("_ns", ""), "coordinator")
    if worst is None:
        return None
    ns, stage, where = worst
    return f"{stage} {ns / 1e6:.2f}ms {where}"


def record_search_slowlog(
        settings_of: Callable[[str], Optional[Any]],
        index_names: List[str], took_ms: float, body: Dict[str, Any],
        recent: List[Dict[str, Any]],
        trace_id: Optional[str] = None,
        slowest_stage: Optional[str] = None,
        opaque_id: Optional[str] = None,
        flight: Optional[Dict[str, Any]] = None,
        tenant: Optional[str] = None,
        workload_class: Optional[str] = None) -> List[Dict[str, Any]]:
    """Check every searched index's thresholds against the search took
    time; append matches (highest matching level per index) to
    ``recent`` and return the new entries. ``settings_of(name)`` yields
    a ``.get``-able settings view or None for an unknown index.

    ``trace_id`` / ``slowest_stage`` (optional) tie the entry into the
    observability chain: slowlog → ``GET /_traces/{id}`` → the profiled
    request's stage breakdown. ``opaque_id`` attributes the entry to
    the client that sent it (the X-Opaque-Id header, ref:
    SearchSlowLog's opaque-id field). ``flight`` is the flight
    recorder's per-trace summary — launches, readbacks, worst cohort
    fill, regime — so one slowlog line answers "was this slow request
    under-batched or running degraded?" without replaying it."""
    from elasticsearch_tpu.common.settings import parse_time_value
    new_entries: List[Dict[str, Any]] = []
    for name in index_names:
        settings = settings_of(name)
        if settings is None:
            continue
        for level in LEVELS:
            thr = settings.get(
                f"index.search.slowlog.threshold.query.{level}")
            if thr is None:
                continue
            thr_ms = parse_time_value(str(thr), "slowlog") * 1000
            if thr_ms < 0:
                continue                # -1 disables the level
            if took_ms >= thr_ms:
                entry = {"index": name, "took_ms": int(took_ms),
                         "level": level,
                         "source": json.dumps(body or {})[:1000]}
                if trace_id is not None:
                    entry["trace.id"] = trace_id
                if slowest_stage is not None:
                    entry["slowest_stage"] = slowest_stage
                if opaque_id is not None:
                    entry["x_opaque_id"] = opaque_id
                if tenant is not None:
                    entry["tenant"] = tenant
                if workload_class is not None:
                    entry["search.class"] = workload_class
                if flight:
                    entry["cohort_fill_pct"] = flight.get(
                        "cohort_fill_pct")
                    entry["readbacks"] = flight.get("readbacks")
                    entry["regime"] = flight.get("regime")
                _slowlog_logger.log(
                    _LEVEL_NUM[level],
                    "[%s] took[%dms], source[%s]",
                    name, took_ms, entry["source"])
                recent.append(entry)
                new_entries.append(entry)
                while len(recent) > MAX_RECENT:
                    recent.pop(0)
                break
    return new_entries
