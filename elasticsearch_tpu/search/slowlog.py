"""Search slow log: one threshold check shared by the single-node
service and the distributed coordinator.

Ref: index/SearchSlowLog.java — per-index, per-level thresholds under
``index.search.slowlog.threshold.query.{warn,info,debug,trace}``; -1
disables a level. The reference logs on the shard; this engine applies
the same thresholds to whichever side measured the took time — the
in-process `SearchService` (search/service.py) and the coordinator
(`cluster/search_action.py`), both of which keep a bounded
``slowlog_recent`` list of entries in ONE shared shape::

    {"index": name, "took_ms": int, "level": "warn", "source": "..."}

so `_nodes/stats`-style surfaces and tests read either side the same
way.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Callable, Dict, List, Optional

_slowlog_logger = logging.getLogger("index.search.slowlog")

LEVELS = ("warn", "info", "debug", "trace")
_LEVEL_NUM = {"warn": 30, "info": 20, "debug": 10, "trace": 5}

MAX_RECENT = 128


def record_search_slowlog(
        settings_of: Callable[[str], Optional[Any]],
        index_names: List[str], took_ms: float, body: Dict[str, Any],
        recent: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Check every searched index's thresholds against the search took
    time; append matches (highest matching level per index) to
    ``recent`` and return the new entries. ``settings_of(name)`` yields
    a ``.get``-able settings view or None for an unknown index."""
    from elasticsearch_tpu.common.settings import parse_time_value
    new_entries: List[Dict[str, Any]] = []
    for name in index_names:
        settings = settings_of(name)
        if settings is None:
            continue
        for level in LEVELS:
            thr = settings.get(
                f"index.search.slowlog.threshold.query.{level}")
            if thr is None:
                continue
            thr_ms = parse_time_value(str(thr), "slowlog") * 1000
            if thr_ms < 0:
                continue                # -1 disables the level
            if took_ms >= thr_ms:
                entry = {"index": name, "took_ms": int(took_ms),
                         "level": level,
                         "source": json.dumps(body or {})[:1000]}
                _slowlog_logger.log(
                    _LEVEL_NUM[level],
                    "[%s] took[%dms], source[%s]",
                    name, took_ms, entry["source"])
                recent.append(entry)
                new_entries.append(entry)
                while len(recent) > MAX_RECENT:
                    recent.pop(0)
                break
    return new_entries
