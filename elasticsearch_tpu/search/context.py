"""Search execution context: shard-level stats + per-segment device state.

Mirrors the reference's QueryShardContext + ContextIndexSearcher roles (ref:
index/query/QueryShardContext.java, search/internal/ContextIndexSearcher.java):
queries compile against shard-level term statistics (Lucene computes IDF from
IndexSearcher-level stats so scores are segment-independent) and execute
per segment against HBM-resident DeviceSegments.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.index.mapper import MapperService
from elasticsearch_tpu.index.segment import Segment
from elasticsearch_tpu.ops.device import DeviceSegment


class ShardStats:
    """Shard-level (cross-segment) field/term statistics for BM25."""

    def __init__(self, segments: List[Segment]):
        self.segments = segments
        self._field_cache: Dict[str, Tuple[int, float]] = {}
        self._df_cache: Dict[Tuple[str, str], int] = {}

    def field_stats(self, field: str) -> Tuple[int, float]:
        """(doc_count_with_field, avg_field_length) across the shard."""
        cached = self._field_cache.get(field)
        if cached is None:
            doc_count = 0
            sum_ttf = 0
            for seg in self.segments:
                pf = seg.postings.get(field)
                if pf is not None:
                    doc_count += pf.doc_count
                    sum_ttf += pf.sum_total_term_freq
            cached = (doc_count, sum_ttf / doc_count if doc_count else 1.0)
            self._field_cache[field] = cached
        return cached

    def doc_freq(self, field: str, term: str) -> int:
        key = (field, term)
        cached = self._df_cache.get(key)
        if cached is None:
            cached = 0
            for seg in self.segments:
                pf = seg.postings.get(field)
                if pf is not None:
                    tid = pf.term_id(term)
                    if tid >= 0:
                        cached += int(pf.doc_freq[tid])
            self._df_cache[key] = cached
        return cached


class SegmentContext:
    """One segment's view for query execution."""

    def __init__(self, segment: Segment, device: DeviceSegment,
                 mapper: MapperService, stats: ShardStats,
                 k1: float = 1.2, b: float = 0.75):
        self.segment = segment
        self.device = device
        self.mapper = mapper
        self.stats = stats
        self.k1 = k1
        self.b = b

    @property
    def n_docs_padded(self) -> int:
        return self.device.n_docs_padded

    @property
    def live(self):
        return self.device.live

    def all_true(self):
        """Mask of all real (non-padding) docs."""
        m = np.zeros(self.n_docs_padded, bool)
        m[: self.segment.n_docs] = True
        return jnp.asarray(m)

    def numeric_column(self, field: str):
        col = self.device.numerics.get(field)
        miss = self.device.numeric_missing.get(field)
        if col is None:
            col = jnp.zeros(self.n_docs_padded, jnp.float32)
            miss = jnp.ones(self.n_docs_padded, bool)
        return col, miss

    def keyword_ord_column(self, field: str):
        """Per-doc first-ordinal sort key for a keyword field, or None.

        Segment term dicts are sorted, so segment-local ordinals order
        lexicographically WITHIN the segment (the Lucene
        SortedSetDocValues model); cross-segment merges must compare the
        term strings (searcher host-side re-sort)."""
        kv = self.segment.keywords.get(field)
        if kv is None:
            return None
        col = np.zeros(self.n_docs_padded, np.float32)
        miss = np.ones(self.n_docs_padded, bool)
        col[: self.segment.n_docs] = np.maximum(kv.ords, 0)
        miss[: self.segment.n_docs] = kv.ords < 0
        return jnp.asarray(col), jnp.asarray(miss)


# DeviceSegment cache: segments are immutable except their live mask, so the
# cache key is (segment name, live_version); a delete only re-uploads live.
class DeviceSegmentCache:
    def __init__(self, device=None, vector_dtype=jnp.bfloat16):
        self._cache: Dict[str, Tuple[int, DeviceSegment]] = {}
        self._lock = threading.Lock()
        self._device = device
        self._vector_dtype = vector_dtype
        # compiled-LogicalPlan memo keyed by (segment names, epoch,
        # query json, k1, b) — ShardSearchers are per-request, this
        # cache is the persistent home (None = query not plannable).
        # Skipping parse→rewrite→compile on repeats is a large slice of
        # the per-query Python cost in the serving hot loop.
        from collections import OrderedDict
        self.plan_cache: "OrderedDict[tuple, object]" = OrderedDict()
        self.plan_cache_max = 512
        # engine observability: plan-cache counters (incremented by the
        # searcher, the cache's only client) + the node-level HBM peak
        # watermark, refreshed on every DeviceSegment build and on every
        # stats read
        self.plan_cache_hits = 0
        self.plan_cache_misses = 0
        self.plan_cache_evictions = 0
        self.peak_hbm_bytes = 0

    def get(self, segment: Segment) -> DeviceSegment:
        with self._lock:
            entry = self._cache.get(segment.name)
            if entry is not None:
                version, dev = entry
                if version == segment.live_version:
                    return dev
                if dev.segment is segment or dev.n_docs == segment.n_docs:
                    dev.update_live(segment.live)
                    self._cache[segment.name] = (segment.live_version, dev)
                    return dev
            dev = DeviceSegment(segment, self._device, self._vector_dtype)
            self._cache[segment.name] = (segment.live_version, dev)
            total = sum(d.hbm_bytes() for _v, d in self._cache.values())
            self.peak_hbm_bytes = max(self.peak_hbm_bytes, total)
            return dev

    def evict(self, names) -> None:
        """Drop device copies of retired segments (called by IndexService
        after merges/deletes so HBM doesn't grow with dead segments)."""
        with self._lock:
            for name in names:
                self._cache.pop(name, None)

    def evict_except(self, names: set) -> None:
        with self._lock:
            for name in list(self._cache):
                if name not in names:
                    del self._cache[name]

    # -- engine observability (the `engine` stats rollup) -----------------

    def _devices(self, segment_names=None) -> Dict[str, DeviceSegment]:
        with self._lock:
            devs = {name: dev for name, (_v, dev) in self._cache.items()}
        if segment_names is not None:
            devs = {n: d for n, d in devs.items() if n in segment_names}
        return devs

    def hbm_stats(self, segment_names=None) -> Dict[str, object]:
        """HBM bytes rolled up over live DeviceSegments, per slab class.

        ``segment_names=None`` is the node-level view and refreshes the
        peak watermark; a name set gives the per-index/per-shard slice
        (its peak is tracked by the owner — IndexService.stats())."""
        from elasticsearch_tpu.ops.device import HBM_SLAB_CLASSES
        devs = self._devices(segment_names)
        by_class = dict.fromkeys(HBM_SLAB_CLASSES, 0)
        total = 0
        for dev in devs.values():
            for cls, n in dev.hbm_bytes_by_class().items():
                by_class[cls] = by_class.get(cls, 0) + n
                total += n
        out: Dict[str, object] = {"total_bytes": total,
                                  "by_class": by_class,
                                  "segments": len(devs)}
        if segment_names is None:
            self.peak_hbm_bytes = max(self.peak_hbm_bytes, total)
            out["peak_bytes"] = self.peak_hbm_bytes
        return out

    def cache_stats(self, segment_names=None) -> Dict[str, object]:
        """Device-cache counters aggregated over live DeviceSegments
        (+ the compiled-plan memo, which is cache-global and only
        reported on the unfiltered node-level view)."""
        agg: Dict[str, Dict[str, int]] = {}
        for dev in self._devices(segment_names).values():
            for cache_name, stats in dev.cache_stats().items():
                bucket = agg.setdefault(cache_name, {})
                for k, v in stats.items():
                    bucket[k] = bucket.get(k, 0) + v
        agg.setdefault("filter_mask", {"hits": 0, "misses": 0,
                                       "evictions": 0, "entries": 0,
                                       "bytes": 0})
        agg.setdefault("bound_plan", {"hits": 0, "misses": 0,
                                      "evictions": 0, "entries": 0})
        if segment_names is None:
            agg["plan"] = {"hits": self.plan_cache_hits,
                           "misses": self.plan_cache_misses,
                           "evictions": self.plan_cache_evictions,
                           "entries": len(self.plan_cache)}
        return agg

    def engine_stats(self, segment_names=None) -> Dict[str, object]:
        return {"hbm": self.hbm_stats(segment_names),
                "caches": self.cache_stats(segment_names)}
