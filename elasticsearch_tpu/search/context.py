"""Search execution context: shard-level stats + per-segment device state.

Mirrors the reference's QueryShardContext + ContextIndexSearcher roles (ref:
index/query/QueryShardContext.java, search/internal/ContextIndexSearcher.java):
queries compile against shard-level term statistics (Lucene computes IDF from
IndexSearcher-level stats so scores are segment-independent) and execute
per segment against HBM-resident DeviceSegments.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.index.mapper import MapperService
from elasticsearch_tpu.index.segment import Segment
from elasticsearch_tpu.ops.device import DeviceSegment


class ShardStats:
    """Shard-level (cross-segment) field/term statistics for BM25."""

    def __init__(self, segments: List[Segment]):
        self.segments = segments
        self._field_cache: Dict[str, Tuple[int, float]] = {}
        self._df_cache: Dict[Tuple[str, str], int] = {}

    def field_stats(self, field: str) -> Tuple[int, float]:
        """(doc_count_with_field, avg_field_length) across the shard."""
        cached = self._field_cache.get(field)
        if cached is None:
            doc_count = 0
            sum_ttf = 0
            for seg in self.segments:
                pf = seg.postings.get(field)
                if pf is not None:
                    doc_count += pf.doc_count
                    sum_ttf += pf.sum_total_term_freq
            cached = (doc_count, sum_ttf / doc_count if doc_count else 1.0)
            self._field_cache[field] = cached
        return cached

    def doc_freq(self, field: str, term: str) -> int:
        key = (field, term)
        cached = self._df_cache.get(key)
        if cached is None:
            cached = 0
            for seg in self.segments:
                pf = seg.postings.get(field)
                if pf is not None:
                    tid = pf.term_id(term)
                    if tid >= 0:
                        cached += int(pf.doc_freq[tid])
            self._df_cache[key] = cached
        return cached


class SegmentContext:
    """One segment's view for query execution."""

    def __init__(self, segment: Segment, device: DeviceSegment,
                 mapper: MapperService, stats: ShardStats,
                 k1: float = 1.2, b: float = 0.75):
        self.segment = segment
        self.device = device
        self.mapper = mapper
        self.stats = stats
        self.k1 = k1
        self.b = b

    @property
    def n_docs_padded(self) -> int:
        return self.device.n_docs_padded

    @property
    def live(self):
        return self.device.live

    def all_true(self):
        """Mask of all real (non-padding) docs."""
        m = np.zeros(self.n_docs_padded, bool)
        m[: self.segment.n_docs] = True
        return jnp.asarray(m)

    def numeric_column(self, field: str):
        col = self.device.numerics.get(field)
        miss = self.device.numeric_missing.get(field)
        if col is None:
            col = jnp.zeros(self.n_docs_padded, jnp.float32)
            miss = jnp.ones(self.n_docs_padded, bool)
        return col, miss

    def keyword_ord_column(self, field: str):
        """Per-doc first-ordinal sort key for a keyword field, or None.

        Segment term dicts are sorted, so segment-local ordinals order
        lexicographically WITHIN the segment (the Lucene
        SortedSetDocValues model); cross-segment merges must compare the
        term strings (searcher host-side re-sort)."""
        kv = self.segment.keywords.get(field)
        if kv is None:
            return None
        col = np.zeros(self.n_docs_padded, np.float32)
        miss = np.ones(self.n_docs_padded, bool)
        col[: self.segment.n_docs] = np.maximum(kv.ords, 0)
        miss[: self.segment.n_docs] = kv.ords < 0
        return jnp.asarray(col), jnp.asarray(miss)


# DeviceSegment cache: segments are immutable except their live mask, so the
# cache key is (segment name, live_version); a delete only re-uploads live.
class DeviceSegmentCache:
    def __init__(self, device=None, vector_dtype=jnp.bfloat16):
        self._cache: Dict[str, Tuple[int, DeviceSegment]] = {}
        self._lock = threading.Lock()
        self._device = device
        self._vector_dtype = vector_dtype
        # compiled-LogicalPlan memo keyed by (segment names, epoch,
        # query json, k1, b) — ShardSearchers are per-request, this
        # cache is the persistent home (None = query not plannable).
        # Skipping parse→rewrite→compile on repeats is a large slice of
        # the per-query Python cost in the serving hot loop.
        from collections import OrderedDict
        self.plan_cache: "OrderedDict[tuple, object]" = OrderedDict()
        self.plan_cache_max = 512

    def get(self, segment: Segment) -> DeviceSegment:
        with self._lock:
            entry = self._cache.get(segment.name)
            if entry is not None:
                version, dev = entry
                if version == segment.live_version:
                    return dev
                if dev.segment is segment or dev.n_docs == segment.n_docs:
                    dev.update_live(segment.live)
                    self._cache[segment.name] = (segment.live_version, dev)
                    return dev
            dev = DeviceSegment(segment, self._device, self._vector_dtype)
            self._cache[segment.name] = (segment.live_version, dev)
            return dev

    def evict(self, names) -> None:
        """Drop device copies of retired segments (called by IndexService
        after merges/deletes so HBM doesn't grow with dead segments)."""
        with self._lock:
            for name in names:
                self._cache.pop(name, None)

    def evict_except(self, names: set) -> None:
        with self._lock:
            for name in list(self._cache):
                if name not in names:
                    del self._cache[name]
