"""Search execution context: shard-level stats + per-segment device state.

Mirrors the reference's QueryShardContext + ContextIndexSearcher roles (ref:
index/query/QueryShardContext.java, search/internal/ContextIndexSearcher.java):
queries compile against shard-level term statistics (Lucene computes IDF from
IndexSearcher-level stats so scores are segment-independent) and execute
per segment against HBM-resident DeviceSegments.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.index.mapper import MapperService
from elasticsearch_tpu.index.segment import Segment
from elasticsearch_tpu.ops.device import DeviceSegment


class ShardStats:
    """Shard-level (cross-segment) field/term statistics for BM25."""

    def __init__(self, segments: List[Segment]):
        self.segments = segments
        self._field_cache: Dict[str, Tuple[int, float]] = {}
        self._df_cache: Dict[Tuple[str, str], int] = {}

    def field_stats(self, field: str) -> Tuple[int, float]:
        """(doc_count_with_field, avg_field_length) across the shard."""
        cached = self._field_cache.get(field)
        if cached is None:
            doc_count = 0
            sum_ttf = 0
            for seg in self.segments:
                pf = seg.postings.get(field)
                if pf is not None:
                    doc_count += pf.doc_count
                    sum_ttf += pf.sum_total_term_freq
            cached = (doc_count, sum_ttf / doc_count if doc_count else 1.0)
            self._field_cache[field] = cached
        return cached

    def doc_freq(self, field: str, term: str) -> int:
        key = (field, term)
        cached = self._df_cache.get(key)
        if cached is None:
            cached = 0
            for seg in self.segments:
                pf = seg.postings.get(field)
                if pf is not None:
                    tid = pf.term_id(term)
                    if tid >= 0:
                        cached += int(pf.doc_freq[tid])
            self._df_cache[key] = cached
        return cached


class SegmentContext:
    """One segment's view for query execution."""

    def __init__(self, segment: Segment, device: DeviceSegment,
                 mapper: MapperService, stats: ShardStats,
                 k1: float = 1.2, b: float = 0.75):
        self.segment = segment
        self.device = device
        self.mapper = mapper
        self.stats = stats
        self.k1 = k1
        self.b = b

    @property
    def n_docs_padded(self) -> int:
        return self.device.n_docs_padded

    @property
    def live(self):
        return self.device.live

    def all_true(self):
        """Mask of all real (non-padding) docs."""
        m = np.zeros(self.n_docs_padded, bool)
        m[: self.segment.n_docs] = True
        return jnp.asarray(m)

    def numeric_column(self, field: str):
        col = self.device.numerics.get(field)
        miss = self.device.numeric_missing.get(field)
        if col is None:
            col = jnp.zeros(self.n_docs_padded, jnp.float32)
            miss = jnp.ones(self.n_docs_padded, bool)
        return col, miss

    def keyword_ord_column(self, field: str):
        """Per-doc first-ordinal sort key for a keyword field, or None.

        Segment term dicts are sorted, so segment-local ordinals order
        lexicographically WITHIN the segment (the Lucene
        SortedSetDocValues model); cross-segment merges must compare the
        term strings (searcher host-side re-sort)."""
        kv = self.segment.keywords.get(field)
        if kv is None:
            return None
        col = np.zeros(self.n_docs_padded, np.float32)
        miss = np.ones(self.n_docs_padded, bool)
        col[: self.segment.n_docs] = np.maximum(kv.ords, 0)
        miss[: self.segment.n_docs] = kv.ords < 0
        return jnp.asarray(col), jnp.asarray(miss)


# DeviceSegment cache: segments are immutable except their live mask, so the
# cache key is (segment name, live_version); a delete only re-uploads live.
class DeviceSegmentCache:
    def __init__(self, device=None, vector_dtype=jnp.bfloat16,
                 breaker=None):
        from collections import OrderedDict as _OD
        # insertion/touch order IS the LRU order the hbm breaker's
        # eviction pressure walks (admission past the limit evicts
        # least-recently-used device segments before tripping)
        self._cache: "_OD[str, Tuple[int, DeviceSegment]]" = _OD()
        self._lock = threading.Lock()
        self._device = device
        self._vector_dtype = vector_dtype
        # hbm child breaker (utils/breaker.py CircuitBreaker) — None
        # keeps every admission site a single branch
        self.breaker = breaker
        self._charged: Dict[str, int] = {}   # segment name -> hbm bytes
        self.hbm_breaker_evictions = 0       # LRU evictions forced by it
        # request-breaker-accounted host allocator; ShardSearchers built
        # over this cache inherit it (searcher.py)
        self.bigarrays = None
        # compiled-LogicalPlan memo keyed by (segment names, epoch,
        # query json, k1, b) — ShardSearchers are per-request, this
        # cache is the persistent home (None = query not plannable).
        # Skipping parse→rewrite→compile on repeats is a large slice of
        # the per-query Python cost in the serving hot loop.
        from collections import OrderedDict
        self.plan_cache: "OrderedDict[tuple, object]" = OrderedDict()
        self.plan_cache_max = 512
        # engine observability: plan-cache counters (incremented by the
        # searcher, the cache's only client) + the node-level HBM peak
        # watermark, refreshed on every DeviceSegment build and on every
        # stats read
        self.plan_cache_hits = 0
        self.plan_cache_misses = 0
        self.plan_cache_evictions = 0
        self.peak_hbm_bytes = 0
        # lifetime device-segment builds (uploads admitted to HBM) —
        # with hbm_breaker_evictions, the churn pair a profiled query
        # snapshots before/after so `profile: true` charges HBM
        # admissions/evictions to the request that caused them
        self.admissions = 0

    def set_breaker(self, breaker) -> None:
        """Wire the `hbm` child breaker (node startup: Node/ClusterNode).
        Charges already-resident segments so accounting matches reality
        even when wiring happens after warm-up."""
        with self._lock:
            self.breaker = breaker
            if breaker is not None:
                for name, (_v, dev) in self._cache.items():
                    if name not in self._charged:
                        nbytes = dev.hbm_bytes()
                        breaker.add_without_breaking(nbytes)
                        self._charged[name] = nbytes

    def _admit_locked(self, nbytes: int, label: str,
                      exclude: str) -> None:
        """Charge the hbm breaker for ``nbytes``, applying LRU eviction
        pressure first: past the limit, least-recently-used device
        segments are dropped (their bytes released) until the charge
        fits; the breaker trips only when eviction cannot free enough
        (ref: the fielddata breaker + IndicesFieldDataCache eviction
        interplay, recast for device memory)."""
        br = self.breaker
        if br is None:
            return
        # evict-first probe: over-limit admissions drop LRU residents
        # WITHOUT counting a trip; the breaker's trip counter (and the
        # raised CircuitBreakingException) fires only when eviction has
        # nothing left to free
        while br.limit >= 0 and \
                (br.used + nbytes) * br.overhead > br.limit:
            victim = next((n for n in self._cache if n != exclude),
                          None)
            if victim is None:
                break
            self._cache.pop(victim)
            br.release(self._charged.pop(victim, 0))
            self.hbm_breaker_evictions += 1
        br.add_estimate_bytes_and_maybe_break(nbytes, label)

    def _release_locked(self, name: str) -> None:
        if self.breaker is not None:
            self.breaker.release(self._charged.pop(name, 0))
        else:
            self._charged.pop(name, None)

    def account_filter_mask(self, name: str, delta: int,
                            label: str = "filter_mask") -> None:
        """Filter-mask admission/release for a resident DeviceSegment
        (called by ops/device.py). Positive deltas go through the same
        eviction-pressure admission as segment builds; negative deltas
        (mask LRU eviction) release. Orphan segments (already evicted
        from this cache) are not accounted."""
        with self._lock:
            if self.breaker is None or name not in self._charged:
                # unwired cache, or an orphan segment already evicted:
                # no accounting (set_breaker charges residents by their
                # FULL hbm_bytes — masks included — when wiring later)
                return
            if delta >= 0:
                self._admit_locked(delta, label, exclude=name)
            else:
                self.breaker.release(-delta)
            self._charged[name] = self._charged.get(name, 0) + delta

    def get(self, segment: Segment) -> DeviceSegment:
        with self._lock:
            entry = self._cache.get(segment.name)
            if entry is not None:
                version, dev = entry
                if version == segment.live_version:
                    self._cache.move_to_end(segment.name)
                    return dev
                if dev.segment is segment or dev.n_docs == segment.n_docs:
                    dev.update_live(segment.live)
                    self._cache[segment.name] = (segment.live_version, dev)
                    self._cache.move_to_end(segment.name)
                    return dev
                # stale copy replaced below: release its accounting
                self._cache.pop(segment.name, None)
                self._release_locked(segment.name)
            # segment admission charges AFTER the build (the slab sizes
            # fall out of it) — the breaker bounds steady-state
            # residency; the build itself transiently overshoots by one
            # segment, like the reference's fielddata loads that are
            # accounted as they materialize
            dev = DeviceSegment(segment, self._device, self._vector_dtype)
            nbytes = dev.hbm_bytes()
            # admission: evict LRU residents before ever tripping
            self._admit_locked(
                nbytes, f"device_segment[{segment.name}]",
                exclude=segment.name)
            if self.breaker is not None:
                self._charged[segment.name] = nbytes
            dev.hbm_sink = self
            self.admissions += 1
            self._cache[segment.name] = (segment.live_version, dev)
            total = sum(d.hbm_bytes() for _v, d in self._cache.values())
            self.peak_hbm_bytes = max(self.peak_hbm_bytes, total)
            return dev

    def evict(self, names) -> None:
        """Drop device copies of retired segments (called by IndexService
        after merges/deletes so HBM doesn't grow with dead segments)."""
        with self._lock:
            for name in names:
                if self._cache.pop(name, None) is not None:
                    self._release_locked(name)

    def evict_except(self, names: set) -> None:
        with self._lock:
            for name in list(self._cache):
                if name not in names:
                    del self._cache[name]
                    self._release_locked(name)

    def churn_counters(self) -> Tuple[int, int]:
        """(admissions, breaker_evictions) lifetime pair — a profiled
        query snapshots it before/after to report the HBM churn that
        happened during its window (node-wide: concurrent queries'
        uploads land in the same delta)."""
        with self._lock:
            return self.admissions, self.hbm_breaker_evictions

    # -- engine observability (the `engine` stats rollup) -----------------

    def _devices(self, segment_names=None) -> Dict[str, DeviceSegment]:
        with self._lock:
            devs = {name: dev for name, (_v, dev) in self._cache.items()}
        if segment_names is not None:
            devs = {n: d for n, d in devs.items() if n in segment_names}
        return devs

    def hbm_stats(self, segment_names=None) -> Dict[str, object]:
        """HBM bytes rolled up over live DeviceSegments, per slab class.

        ``segment_names=None`` is the node-level view and refreshes the
        peak watermark; a name set gives the per-index/per-shard slice
        (its peak is tracked by the owner — IndexService.stats())."""
        from elasticsearch_tpu.ops.device import HBM_SLAB_CLASSES
        devs = self._devices(segment_names)
        by_class = dict.fromkeys(HBM_SLAB_CLASSES, 0)
        total = 0
        for dev in devs.values():
            for cls, n in dev.hbm_bytes_by_class().items():
                by_class[cls] = by_class.get(cls, 0) + n
                total += n
        out: Dict[str, object] = {"total_bytes": total,
                                  "by_class": by_class,
                                  "segments": len(devs)}
        if segment_names is None:
            self.peak_hbm_bytes = max(self.peak_hbm_bytes, total)
            out["peak_bytes"] = self.peak_hbm_bytes
            # lifetime segment uploads; the per-query delta is what
            # `profile: true` charges to a request (churn_counters)
            out["admissions"] = self.admissions
            # admissions forced to drop an LRU resident by the hbm
            # breaker (zero in a healthy, fits-in-HBM deployment)
            out["breaker_evictions"] = self.hbm_breaker_evictions
        return out

    def cache_stats(self, segment_names=None) -> Dict[str, object]:
        """Device-cache counters aggregated over live DeviceSegments
        (+ the compiled-plan memo, which is cache-global and only
        reported on the unfiltered node-level view)."""
        agg: Dict[str, Dict[str, int]] = {}
        for dev in self._devices(segment_names).values():
            for cache_name, stats in dev.cache_stats().items():
                bucket = agg.setdefault(cache_name, {})
                for k, v in stats.items():
                    bucket[k] = bucket.get(k, 0) + v
        agg.setdefault("filter_mask", {"hits": 0, "misses": 0,
                                       "evictions": 0, "entries": 0,
                                       "bytes": 0})
        agg.setdefault("bound_plan", {"hits": 0, "misses": 0,
                                      "evictions": 0, "entries": 0})
        if segment_names is None:
            agg["plan"] = {"hits": self.plan_cache_hits,
                           "misses": self.plan_cache_misses,
                           "evictions": self.plan_cache_evictions,
                           "entries": len(self.plan_cache)}
        return agg

    def engine_stats(self, segment_names=None) -> Dict[str, object]:
        return {"hbm": self.hbm_stats(segment_names),
                "caches": self.cache_stats(segment_names)}
