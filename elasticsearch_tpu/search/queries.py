"""Query DSL: JSON query tree → executable device plans.

Mirrors the reference's query layer (ref: index/query/ — 41 registered query
types, search/SearchModule.java:268; AbstractQueryBuilder parse/rewrite).
Each QueryBuilder parses from the JSON DSL and executes per segment,
returning ``(scores, mask)`` device arrays:

- ``scores`` float32 [ND_padded]: relevance (0 where unmatched/filter-only
  — matching ES, where filter-only bool queries score 0.0)
- ``mask``  bool  [ND_padded]: which docs matched

Where Lucene builds Weight/Scorer iterator trees walked per doc, these
builders compose whole-array kernel calls: a bool query is mask algebra +
score addition over dense arrays; operator-AND and minimum_should_match are
clause-count scatter kernels (ops/bm25.py match_count).

Implemented: match_all, match_none, match, multi_match, term, terms, range,
exists, ids, bool, constant_score, dis_max, boosting, script_score, knn,
function_score(scripts+weight). Positional queries (match_phrase,
intervals, span) need a positions index — postings positions land in a later
round (gap tracked in SURVEY parity).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.common.errors import ParsingException, QueryShardException
from elasticsearch_tpu.index.mapper import (
    DenseVectorFieldType,
    KeywordFieldType,
    TextFieldType,
)
from elasticsearch_tpu.ops import bm25 as bm25_ops
from elasticsearch_tpu.ops import vector as vec_ops
from elasticsearch_tpu.search.context import SegmentContext
from elasticsearch_tpu.search.script import ScriptContext, _DocColumn, compile_script

Result = Tuple[jnp.ndarray, jnp.ndarray]  # (scores f32 [ND], mask bool [ND])


def parse_minimum_should_match(value, n_clauses: int) -> int:
    """ES minimum_should_match forms: int, "2", "-1", "75%", "-25%"
    (ref: common/lucene/search/Queries.calculateMinShouldMatch)."""
    if value is None:
        return 0
    if isinstance(value, int):
        n = value
    else:
        s = str(value).strip()
        try:
            if s.endswith("%"):
                pct = float(s[:-1])
                n = int(n_clauses * pct / 100.0) if pct >= 0 else \
                    n_clauses + int(n_clauses * pct / 100.0)
            else:
                n = int(s)
        except ValueError:
            raise ParsingException(
                f"could not parse minimum_should_match [{value}]")
    if n < 0:
        n = n_clauses + n
    return max(0, min(n, n_clauses))


class QueryBuilder:
    name = "?"

    def __init__(self):
        self.boost = 1.0

    def execute(self, ctx: SegmentContext) -> Result:
        scores, mask = self.do_execute(ctx)
        if self.boost != 1.0:
            scores = scores * self.boost
        return scores, mask

    def do_execute(self, ctx: SegmentContext) -> Result:
        raise NotImplementedError

    # can_match-style pruning hook (ref: CanMatchPreFilterSearchPhase)
    def can_match(self, ctx: SegmentContext) -> bool:
        return True


class MatchAllQuery(QueryBuilder):
    name = "match_all"

    def do_execute(self, ctx):
        mask = ctx.all_true()
        return mask.astype(jnp.float32), mask


class MatchNoneQuery(QueryBuilder):
    name = "match_none"

    def do_execute(self, ctx):
        z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
        return z, z.astype(bool)

    def can_match(self, ctx):
        return False


def _analyze_terms(ctx: SegmentContext, field: str, text: str) -> List[str]:
    ft = ctx.mapper.field_type(field)
    if isinstance(ft, TextFieldType):
        name = ft.search_analyzer_name
        analyzer = (ctx.mapper.analysis.get(name)
                    if ctx.mapper.analysis.has(name)
                    else ctx.mapper.analysis.default)
        return analyzer.terms(text)
    # keyword/numeric fields: the term is the literal value
    return [str(text)]


def _bm25_terms(ctx: SegmentContext, field: str, terms: List[str]) -> Result:
    """Shared scorer: BM25 over the field's postings for the given terms."""
    dp = ctx.device.postings.get(field)
    if dp is None:
        z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
        return z, z.astype(bool)
    doc_count, avg_len = ctx.stats.field_stats(field)
    tids, weights = [], []
    for t in terms:
        tid = dp.host.term_id(t)
        df = ctx.stats.doc_freq(field, t)
        tids.append(tid)
        weights.append(bm25_ops.idf(df, doc_count) if df > 0 else 0.0)
    sel, ws = dp.select_blocks(tids, weights)
    scores = bm25_ops.bm25_block_scores(
        dp.block_docids, dp.block_tfs, jnp.asarray(sel), jnp.asarray(ws),
        dp.doc_lens, jnp.float32(avg_len), ctx.k1, ctx.b)
    return scores, scores > 0.0


class MatchQuery(QueryBuilder):
    """ref: index/query/MatchQueryBuilder.java — analyzed full-text query;
    multi-term OR/AND with minimum_should_match."""

    name = "match"

    def __init__(self, field: str, query: str, operator: str = "or",
                 minimum_should_match: Optional[int] = None):
        super().__init__()
        self.field = field
        self.query = query
        self.operator = operator.lower()
        self.minimum_should_match = minimum_should_match

    def do_execute(self, ctx):
        terms = _analyze_terms(ctx, self.field, self.query)
        if not terms:
            z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
            return z, z.astype(bool)
        scores, mask = _bm25_terms(ctx, self.field, terms)
        required = None
        if self.operator == "and":
            required = len(terms)
        elif self.minimum_should_match:
            required = parse_minimum_should_match(
                self.minimum_should_match, len(terms))
        if required is not None and required > 1:
            dp = ctx.device.postings.get(self.field)
            if dp is None:
                return scores, mask
            sels, cids = [], []
            uniq = sorted(set(terms))
            for ci, t in enumerate(uniq):
                s, _ = dp.select_blocks([dp.host.term_id(t)], [1.0])
                sels.append(s)
                cids.append(np.full(len(s), ci, np.int32))
            counts = bm25_ops.match_count(
                dp.block_docids, dp.block_tfs,
                jnp.asarray(np.concatenate(sels)),
                jnp.asarray(np.concatenate(cids)),
                len(uniq), ctx.n_docs_padded)
            need = len(uniq) if self.operator == "and" else min(required, len(uniq))
            mask = mask & (counts >= need)
            scores = jnp.where(mask, scores, 0.0)
        return scores, mask


class MultiMatchQuery(QueryBuilder):
    """ref: MultiMatchQueryBuilder — best_fields (dis-max over per-field
    match) and most_fields (sum)."""

    name = "multi_match"

    def __init__(self, fields: List[str], query: str, type_: str = "best_fields",
                 tie_breaker: float = 0.0):
        super().__init__()
        self.fields = fields
        self.query = query
        self.type = type_
        self.tie_breaker = tie_breaker

    def do_execute(self, ctx):
        fields = self.fields
        if not fields or fields == ["*"]:
            # default: all text fields (ref: multi_match default field "*")
            fields = [name for name, ft in ctx.mapper.mapper.fields.items()
                      if isinstance(ft, TextFieldType)]
        if not fields:
            z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
            return z, z.astype(bool)
        results = [MatchQuery(f, self.query).execute(ctx) for f in fields]
        scores = [s for s, _ in results]
        masks = [m for _, m in results]
        any_mask = masks[0]
        for m in masks[1:]:
            any_mask = any_mask | m
        if self.type == "most_fields":
            total = scores[0]
            for s in scores[1:]:
                total = total + s
            return total, any_mask
        stacked = jnp.stack(scores)
        best = stacked.max(axis=0)
        if self.tie_breaker > 0.0:
            best = best + self.tie_breaker * (stacked.sum(axis=0) - best)
        return best, any_mask


class TermQuery(QueryBuilder):
    """ref: TermQueryBuilder — exact term; keyword fields score BM25 with
    tf=1 and norms omitted (Lucene keyword fields have no norms:
    score = idf·1/(1+k1)); numeric/date/bool terms are constant-score
    point matches."""

    name = "term"

    def __init__(self, field: str, value: Any):
        super().__init__()
        self.field = field
        self.value = value

    def do_execute(self, ctx):
        ft = ctx.mapper.field_type(self.field)
        if ft is None or isinstance(ft, (TextFieldType, KeywordFieldType)):
            dp = ctx.device.postings.get(self.field)
            if dp is None:
                z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
                return z, z.astype(bool)
            term = str(self.value)
            tid = dp.host.term_id(term)
            sel, _ = dp.select_blocks([tid], [1.0])
            mask = bm25_ops.match_mask(
                dp.block_docids, dp.block_tfs, jnp.asarray(sel),
                ctx.n_docs_padded)
            if isinstance(ft, KeywordFieldType) or ft is None:
                doc_count, _ = ctx.stats.field_stats(self.field)
                df = ctx.stats.doc_freq(self.field, term)
                w = bm25_ops.idf(df, doc_count) if df else 0.0
                const = w * 1.0 / (1.0 + ctx.k1)   # tf=1, no norms
                return mask.astype(jnp.float32) * const, mask
            # text field + term query: unanalyzed exact term, BM25-scored
            scores, mask2 = _bm25_terms(ctx, self.field, [term])
            return scores, mask2
        # numeric/date/boolean: point match, constant score
        parsed = float(ft.parse(self.value))
        col, miss = ctx.numeric_column(self.field)
        mask = (~miss) & (col == parsed) & ctx.all_true()
        return mask.astype(jnp.float32), mask


class TermsQuery(QueryBuilder):
    """ref: TermsQueryBuilder — constant score 1.0 for any-of."""

    name = "terms"

    def __init__(self, field: str, values: List[Any]):
        super().__init__()
        self.field = field
        self.values = values

    def do_execute(self, ctx):
        ft = ctx.mapper.field_type(self.field)
        if ft is None or isinstance(ft, (TextFieldType, KeywordFieldType)):
            dp = ctx.device.postings.get(self.field)
            if dp is None:
                z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
                return z, z.astype(bool)
            tids = [dp.host.term_id(str(v)) for v in self.values]
            sel, _ = dp.select_blocks(tids, [1.0] * len(tids))
            mask = bm25_ops.match_mask(
                dp.block_docids, dp.block_tfs, jnp.asarray(sel),
                ctx.n_docs_padded)
            return mask.astype(jnp.float32), mask
        col, miss = ctx.numeric_column(self.field)
        mask = jnp.zeros(ctx.n_docs_padded, bool)
        for v in self.values:
            mask = mask | (col == float(ft.parse(v)))
        mask = mask & (~miss) & ctx.all_true()
        return mask.astype(jnp.float32), mask


class RangeQuery(QueryBuilder):
    name = "range"

    def __init__(self, field: str, gte=None, gt=None, lte=None, lt=None):
        super().__init__()
        self.field = field
        self.gte, self.gt, self.lte, self.lt = gte, gt, lte, lt

    def do_execute(self, ctx):
        ft = ctx.mapper.field_type(self.field)
        if ft is None:
            z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
            return z, z.astype(bool)
        parse = lambda v: float(ft.parse(v))  # noqa: E731
        col, miss = ctx.numeric_column(self.field)
        mask = (~miss) & ctx.all_true()
        if self.gte is not None:
            mask = mask & (col >= parse(self.gte))
        if self.gt is not None:
            mask = mask & (col > parse(self.gt))
        if self.lte is not None:
            mask = mask & (col <= parse(self.lte))
        if self.lt is not None:
            mask = mask & (col < parse(self.lt))
        return mask.astype(jnp.float32), mask


class ExistsQuery(QueryBuilder):
    name = "exists"

    def __init__(self, field: str):
        super().__init__()
        self.field = field

    def do_execute(self, ctx):
        dev = ctx.device
        if self.field in dev.postings:
            lens = dev.postings[self.field].doc_lens
            mask = (lens > 0) & ctx.all_true()
        elif self.field in dev.numerics:
            mask = (~dev.numeric_missing[self.field]) & ctx.all_true()
        elif self.field in dev.vectors:
            mask = dev.vectors[self.field].has_value & ctx.all_true()
        else:
            mask = jnp.zeros(ctx.n_docs_padded, bool)
        return mask.astype(jnp.float32), mask


class IdsQuery(QueryBuilder):
    name = "ids"

    def __init__(self, values: List[str]):
        super().__init__()
        self.values = values

    def do_execute(self, ctx):
        m = np.zeros(ctx.n_docs_padded, bool)
        for doc_id in self.values:
            docid = ctx.segment.docid_for(str(doc_id))
            if docid >= 0:
                m[docid] = True
        mask = jnp.asarray(m)
        return mask.astype(jnp.float32), mask


class BoolQuery(QueryBuilder):
    """ref: BoolQueryBuilder — must (scoring, all required), filter
    (non-scoring, required), should (scoring, optional unless no
    must/filter), must_not (excluded). Composed as mask algebra over dense
    arrays instead of Lucene's ConjunctionDISI/disjunction iterators."""

    name = "bool"

    def __init__(self, must=None, filter=None, should=None, must_not=None,
                 minimum_should_match: Optional[int] = None):
        super().__init__()
        self.must = must or []
        self.filter = filter or []
        self.should = should or []
        self.must_not = must_not or []
        self.minimum_should_match = minimum_should_match

    def do_execute(self, ctx):
        scores = jnp.zeros(ctx.n_docs_padded, jnp.float32)
        mask = ctx.all_true()
        for q in self.must:
            s, m = q.execute(ctx)
            scores = scores + s
            mask = mask & m
        for q in self.filter:
            _, m = q.execute(ctx)
            mask = mask & m
        for q in self.must_not:
            _, m = q.execute(ctx)
            mask = mask & (~m)
        if self.should:
            should_results = [q.execute(ctx) for q in self.should]
            for s, _ in should_results:
                scores = scores + s
            if self.minimum_should_match is None:
                msm = 1 if not (self.must or self.filter) else 0
            else:
                msm = parse_minimum_should_match(
                    self.minimum_should_match, len(self.should))
            if msm > 0:
                count = jnp.zeros(ctx.n_docs_padded, jnp.int32)
                for _, m in should_results:
                    count = count + m.astype(jnp.int32)
                mask = mask & (count >= msm)
        scores = jnp.where(mask, scores, 0.0)
        return scores, mask


class ConstantScoreQuery(QueryBuilder):
    name = "constant_score"

    def __init__(self, filter_query: QueryBuilder):
        super().__init__()
        self.filter_query = filter_query

    def do_execute(self, ctx):
        _, mask = self.filter_query.execute(ctx)
        return mask.astype(jnp.float32), mask


class DisMaxQuery(QueryBuilder):
    name = "dis_max"

    def __init__(self, queries: List[QueryBuilder], tie_breaker: float = 0.0):
        super().__init__()
        self.queries = queries
        self.tie_breaker = tie_breaker

    def do_execute(self, ctx):
        results = [q.execute(ctx) for q in self.queries]
        stacked = jnp.stack([s for s, _ in results])
        mask = results[0][1]
        for _, m in results[1:]:
            mask = mask | m
        best = stacked.max(axis=0)
        if self.tie_breaker > 0.0:
            best = best + self.tie_breaker * (stacked.sum(axis=0) - best)
        best = jnp.where(mask, best, 0.0)
        return best, mask


class BoostingQuery(QueryBuilder):
    """ref: BoostingQueryBuilder — demote (not exclude) negative matches."""

    name = "boosting"

    def __init__(self, positive: QueryBuilder, negative: QueryBuilder,
                 negative_boost: float):
        super().__init__()
        self.positive = positive
        self.negative = negative
        self.negative_boost = negative_boost

    def do_execute(self, ctx):
        s, mask = self.positive.execute(ctx)
        _, neg = self.negative.execute(ctx)
        s = jnp.where(neg, s * self.negative_boost, s)
        return s, mask


def _make_vector_fns(ctx: SegmentContext):
    """cosineSimilarity/dotProduct/l2norm for scripts (parity surface of
    ScoreScriptUtils.java:112-170), batched over the whole segment."""

    def _get(field):
        dv = ctx.device.vectors.get(field)
        if dv is None:
            raise QueryShardException(f"unknown vector field [{field}]")
        return dv

    def cosine(query_vector, field):
        dv = _get(field)
        q = jnp.asarray(np.asarray(query_vector, np.float32))[None, :]
        if dv.similarity == "cosine":
            return vec_ops.cosine_scores(q, dv.vectors)[0]
        qn = jnp.linalg.norm(q)
        raw = vec_ops.dot_scores(q, dv.vectors)[0]
        denom = jnp.where(dv.norms > 0, dv.norms * qn, 1.0)
        return raw / denom

    def dot(query_vector, field):
        dv = _get(field)
        q = jnp.asarray(np.asarray(query_vector, np.float32))[None, :]
        raw = vec_ops.dot_scores(q, dv.vectors)[0]
        if dv.similarity == "cosine":   # slab is pre-normalized; undo
            raw = raw * dv.norms
        return raw

    def l2norm(query_vector, field):
        dv = _get(field)
        q = jnp.asarray(np.asarray(query_vector, np.float32))[None, :]
        vecs = dv.vectors * dv.norms[:, None] if dv.similarity == "cosine" else dv.vectors
        return jnp.sqrt(jnp.maximum(
            0.0, -vec_ops.l2_scores(q, vecs, dv.sq_norms)[0]))

    return {"cosineSimilarity": cosine, "dotProduct": dot, "l2norm": l2norm}


class ScriptScoreQuery(QueryBuilder):
    """ref: ScriptScoreQueryBuilder + ScriptScoreQuery.java:51,91-109 — the
    subquery filters, the script replaces the score. Script runs once over
    columns, not per doc."""

    name = "script_score"

    def __init__(self, query: QueryBuilder, source: str,
                 params: Optional[Dict[str, Any]] = None,
                 min_score: Optional[float] = None):
        super().__init__()
        self.query = query
        self.source = source
        self.params = params or {}
        self.min_score = min_score
        self._compiled = compile_script(source)

    def do_execute(self, ctx):
        base_scores, mask = self.query.execute(ctx)

        def doc_columns(field):
            col, miss = ctx.numeric_column(field)
            return _DocColumn(col, miss)

        sctx = ScriptContext(doc_columns, self.params, score=base_scores,
                             vector_fns=_make_vector_fns(ctx))
        scores = jnp.asarray(self._compiled(sctx), jnp.float32)
        scores = jnp.broadcast_to(scores, (ctx.n_docs_padded,))
        scores = jnp.where(mask, scores, 0.0)
        if self.min_score is not None:
            mask = mask & (scores >= self.min_score)
            scores = jnp.where(mask, scores, 0.0)
        return scores, mask


class KnnQuery(QueryBuilder):
    """Native brute-force kNN — net-new surface (the reference only has
    script_score brute force; no ANN at this version, SURVEY.md §2.6).
    Score transforms follow the modern ES kNN conventions:
    cosine → (1+cos)/2, dot_product → (1+dot)/2, l2_norm → 1/(1+d²)."""

    name = "knn"

    def __init__(self, field: str, query_vector: List[float],
                 num_candidates: Optional[int] = None,
                 filter_query: Optional[QueryBuilder] = None):
        super().__init__()
        self.field = field
        self.query_vector = np.asarray(query_vector, np.float32)
        self.num_candidates = num_candidates
        self.filter_query = filter_query

    def do_execute(self, ctx):
        dv = ctx.device.vectors.get(self.field)
        if dv is None:
            z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
            return z, z.astype(bool)
        q = jnp.asarray(self.query_vector)[None, :]
        if dv.similarity == "cosine":
            raw = vec_ops.cosine_scores(q, dv.vectors)[0]
            scores = (1.0 + raw) / 2.0
        elif dv.similarity == "dot_product":
            raw = vec_ops.dot_scores(q, dv.vectors)[0]
            scores = (1.0 + raw) / 2.0
        else:  # l2_norm
            neg_sq = vec_ops.l2_scores(q, dv.vectors, dv.sq_norms)[0]
            scores = 1.0 / (1.0 - neg_sq)
        mask = dv.has_value & ctx.all_true()
        if self.filter_query is not None:
            _, fm = self.filter_query.execute(ctx)
            mask = mask & fm
        scores = jnp.where(mask, scores, 0.0)
        return scores, mask


class FunctionScoreQuery(QueryBuilder):
    """ref: functionscore/FunctionScoreQueryBuilder — subset: script_score
    function, weight, boost_mode/score_mode multiply|sum|replace."""

    name = "function_score"

    def __init__(self, query: QueryBuilder, functions: List[Dict[str, Any]],
                 boost_mode: str = "multiply", score_mode: str = "multiply"):
        super().__init__()
        self.query = query
        self.functions = functions
        self.boost_mode = boost_mode
        self.score_mode = score_mode

    def do_execute(self, ctx):
        base, mask = self.query.execute(ctx)
        fn_scores = []
        for fn in self.functions:
            weight = float(fn.get("weight", 1.0))
            if "script_score" in fn:
                script = fn["script_score"]["script"]
                compiled = compile_script(script.get("source", script)
                                          if isinstance(script, dict) else script)

                def doc_columns(field):
                    col, miss = ctx.numeric_column(field)
                    return _DocColumn(col, miss)

                sctx = ScriptContext(
                    doc_columns,
                    (script.get("params", {}) if isinstance(script, dict) else {}),
                    score=base, vector_fns=_make_vector_fns(ctx))
                val = jnp.broadcast_to(
                    jnp.asarray(compiled(sctx), jnp.float32),
                    (ctx.n_docs_padded,))
                fn_scores.append(val * weight)
            else:
                fn_scores.append(jnp.full(ctx.n_docs_padded, weight, jnp.float32))
        if fn_scores:
            combined = fn_scores[0]
            for f in fn_scores[1:]:
                combined = (combined * f if self.score_mode == "multiply"
                            else combined + f)
            if self.boost_mode == "multiply":
                scores = base * combined
            elif self.boost_mode == "sum":
                scores = base + combined
            else:  # replace
                scores = combined
        else:
            scores = base
        scores = jnp.where(mask, scores, 0.0)
        return scores, mask


# ---------------------------------------------------------------------------
# Parsing (ref: AbstractQueryBuilder.parseInnerQueryBuilder via
# NamedXContentRegistry)
# ---------------------------------------------------------------------------

def parse_query(body: Dict[str, Any]) -> QueryBuilder:
    if not isinstance(body, dict) or len(body) != 1:
        raise ParsingException(
            f"[query] malformed query, expected a single query type, got "
            f"{list(body) if isinstance(body, dict) else type(body).__name__}")
    (qtype, spec), = body.items()
    parser = _PARSERS.get(qtype)
    if parser is None:
        raise ParsingException(f"unknown query [{qtype}]")
    return parser(spec)


def _with_boost(q: QueryBuilder, spec) -> QueryBuilder:
    if isinstance(spec, dict) and "boost" in spec:
        q.boost = float(spec["boost"])
    return q


def _parse_match(spec):
    if not isinstance(spec, dict) or len(spec) != 1:
        raise ParsingException("[match] query malformed")
    (field, params), = spec.items()
    if isinstance(params, dict):
        q = MatchQuery(field, str(params.get("query", "")),
                       operator=params.get("operator", "or"),
                       minimum_should_match=params.get("minimum_should_match"))
        return _with_boost(q, params)
    return MatchQuery(field, str(params))


def _parse_multi_match(spec):
    return MultiMatchQuery(list(spec.get("fields", [])),
                           str(spec.get("query", "")),
                           type_=spec.get("type", "best_fields"),
                           tie_breaker=float(spec.get("tie_breaker", 0.0)))


def _parse_term(spec):
    if not isinstance(spec, dict) or len(spec) != 1:
        raise ParsingException("[term] query malformed")
    (field, params), = spec.items()
    if isinstance(params, dict):
        return _with_boost(TermQuery(field, params.get("value")), params)
    return TermQuery(field, params)


def _parse_terms(spec):
    fields = {k: v for k, v in spec.items() if k != "boost"}
    if len(fields) != 1:
        raise ParsingException("[terms] query requires exactly one field")
    (field, values), = fields.items()
    return _with_boost(TermsQuery(field, list(values)), spec)


def _parse_range(spec):
    (field, params), = spec.items()
    # `from`/`to` legacy aliases
    gte = params.get("gte", params.get("from"))
    lte = params.get("lte", params.get("to"))
    return _with_boost(
        RangeQuery(field, gte=gte, gt=params.get("gt"),
                   lte=lte, lt=params.get("lt")), params)


def _parse_bool(spec):
    def parse_clauses(key):
        v = spec.get(key, [])
        if isinstance(v, dict):
            v = [v]
        return [parse_query(c) for c in v]

    q = BoolQuery(
        must=parse_clauses("must"), filter=parse_clauses("filter"),
        should=parse_clauses("should"), must_not=parse_clauses("must_not"),
        minimum_should_match=spec.get("minimum_should_match"))
    return _with_boost(q, spec)


def _parse_script_score(spec):
    script = spec["script"]
    source = script["source"] if isinstance(script, dict) else str(script)
    params = script.get("params", {}) if isinstance(script, dict) else {}
    q = ScriptScoreQuery(parse_query(spec["query"]), source, params,
                         min_score=spec.get("min_score"))
    return _with_boost(q, spec)


def _parse_knn(spec):
    filt = spec.get("filter")
    return KnnQuery(spec["field"], spec["query_vector"],
                    num_candidates=spec.get("num_candidates"),
                    filter_query=parse_query(filt) if filt else None)


def _parse_dis_max(spec):
    queries = [parse_query(q) for q in spec.get("queries", [])]
    if not queries:
        raise ParsingException("[dis_max] requires 'queries' field with at "
                               "least one clause")
    return DisMaxQuery(queries, tie_breaker=float(spec.get("tie_breaker", 0.0)))


def _parse_function_score(spec):
    inner = parse_query(spec.get("query", {"match_all": {}}))
    functions = spec.get("functions", [])
    if not functions and "script_score" in spec:
        functions = [{"script_score": spec["script_score"]}]
    return _with_boost(
        FunctionScoreQuery(inner, functions,
                           boost_mode=spec.get("boost_mode", "multiply"),
                           score_mode=spec.get("score_mode", "multiply")), spec)


_PARSERS = {
    "match_all": lambda spec: _with_boost(MatchAllQuery(), spec),
    "match_none": lambda spec: MatchNoneQuery(),
    "match": _parse_match,
    "multi_match": _parse_multi_match,
    "term": _parse_term,
    "terms": _parse_terms,
    "range": _parse_range,
    "exists": lambda spec: ExistsQuery(spec["field"]),
    "ids": lambda spec: IdsQuery(list(spec.get("values", []))),
    "bool": _parse_bool,
    "constant_score": lambda spec: _with_boost(
        ConstantScoreQuery(parse_query(spec["filter"])), spec),
    "dis_max": lambda spec: _parse_dis_max(spec),
    "boosting": lambda spec: BoostingQuery(
        parse_query(spec["positive"]), parse_query(spec["negative"]),
        float(spec.get("negative_boost", 0.5))),
    "script_score": _parse_script_score,
    "knn": _parse_knn,
    "function_score": _parse_function_score,
}
