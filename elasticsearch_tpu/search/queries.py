"""Query DSL: JSON query tree → executable device plans.

Mirrors the reference's query layer (ref: index/query/ — 41 registered query
types, search/SearchModule.java:268; AbstractQueryBuilder parse/rewrite).
Each QueryBuilder parses from the JSON DSL and executes per segment,
returning ``(scores, mask)`` device arrays:

- ``scores`` float32 [ND_padded]: relevance (0 where unmatched/filter-only
  — matching ES, where filter-only bool queries score 0.0)
- ``mask``  bool  [ND_padded]: which docs matched

Where Lucene builds Weight/Scorer iterator trees walked per doc, these
builders compose whole-array kernel calls: a bool query is mask algebra +
score addition over dense arrays; operator-AND and minimum_should_match are
clause-count scatter kernels (ops/bm25.py match_count).

Implemented: match_all, match_none, match, multi_match, term, terms, range,
exists, ids, bool, constant_score, dis_max, boosting, script_score, knn,
function_score(scripts+weight), match_phrase (slop), match_phrase_prefix,
match_bool_prefix, prefix, wildcard, regexp, fuzzy, more_like_this, pinned,
distance_feature, query_string, simple_query_string. Positional queries run
on the segment token streams (index/segment.py TokenStreams +
search/phrase.py): device conjunction filter, host position verification.
"""

from __future__ import annotations

import re

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.common.errors import ParsingException, QueryShardException
from elasticsearch_tpu.index.mapper import (
    DenseVectorFieldType,
    KeywordFieldType,
    TextFieldType,
)
from elasticsearch_tpu.ops import bm25 as bm25_ops
from elasticsearch_tpu.ops import device as device_ops
from elasticsearch_tpu.ops import vector as vec_ops
from elasticsearch_tpu.search.context import SegmentContext
from elasticsearch_tpu.search.script import ScriptContext, _DocColumn, compile_script

Result = Tuple[jnp.ndarray, jnp.ndarray]  # (scores f32 [ND], mask bool [ND])


def parse_minimum_should_match(value, n_clauses: int) -> int:
    """ES minimum_should_match forms: int, "2", "-1", "75%", "-25%"
    (ref: common/lucene/search/Queries.calculateMinShouldMatch)."""
    if value is None:
        return 0
    if isinstance(value, int):
        n = value
    else:
        s = str(value).strip()
        try:
            if s.endswith("%"):
                pct = float(s[:-1])
                n = int(n_clauses * pct / 100.0) if pct >= 0 else \
                    n_clauses + int(n_clauses * pct / 100.0)
            else:
                n = int(s)
        except ValueError:
            raise ParsingException(
                f"could not parse minimum_should_match [{value}]")
    if n < 0:
        n = n_clauses + n
    return max(0, min(n, n_clauses))


class QueryBuilder:
    name = "?"

    def __init__(self):
        self.boost = 1.0

    def execute(self, ctx: SegmentContext) -> Result:
        scores, mask = self.do_execute(ctx)
        if self.boost != 1.0:
            scores = scores * self.boost
        return scores, mask

    def do_execute(self, ctx: SegmentContext) -> Result:
        raise NotImplementedError

    # can_match-style pruning hook (ref: CanMatchPreFilterSearchPhase)
    def can_match(self, ctx: SegmentContext) -> bool:
        return True

    # shard-level rewrite before execution (ref: QueryBuilder.rewrite /
    # Rewriteable — more_like_this resolves doc references here)
    def rewrite(self, searcher) -> "QueryBuilder":
        return self


class MatchAllQuery(QueryBuilder):
    name = "match_all"

    def do_execute(self, ctx):
        mask = ctx.all_true()
        return mask.astype(jnp.float32), mask


class MatchNoneQuery(QueryBuilder):
    name = "match_none"

    def do_execute(self, ctx):
        z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
        return z, z.astype(bool)

    def can_match(self, ctx):
        return False


def _analyze_terms(ctx: SegmentContext, field: str, text: str) -> List[str]:
    from elasticsearch_tpu.index.mapper import ShingleSubFieldType
    ft = ctx.mapper.field_type(field)
    if isinstance(ft, TextFieldType):
        name = ft.search_analyzer_name
        analyzer = (ctx.mapper.analysis.get(name)
                    if ctx.mapper.analysis.has(name)
                    else ctx.mapper.analysis.default)
        terms = analyzer.terms(text)
        if isinstance(ft, ShingleSubFieldType):
            n = ft.shingle_size
            return [" ".join(terms[i:i + n])
                    for i in range(len(terms) - n + 1)]
        return terms
    # keyword/numeric fields: the term is the literal value
    return [str(text)]


def _bm25_terms(ctx: SegmentContext, field: str, terms: List[str]) -> Result:
    """Shared scorer: BM25 over the field's postings for the given terms."""
    dp = ctx.device.postings.get(field)
    if dp is None:
        z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
        return z, z.astype(bool)
    doc_count, avg_len = ctx.stats.field_stats(field)
    tids, weights = [], []
    for t in terms:
        tid = dp.host.term_id(t)
        df = ctx.stats.doc_freq(field, t)
        tids.append(tid)
        weights.append(bm25_ops.idf(df, doc_count) if df > 0 else 0.0)
    sel, ws = dp.select_blocks(tids, weights)
    from elasticsearch_tpu.ops.bm25 import scan_run_bound
    from elasticsearch_tpu.ops.plan import bm25_dense_scores_sorted
    scores = bm25_dense_scores_sorted(
        dp.block_docids, dp.block_tfs, jnp.asarray(sel), jnp.asarray(ws),
        dp.doc_lens, jnp.float32(avg_len), ctx.k1, ctx.b,
        max_run=scan_run_bound(len(tids)))
    return scores, scores > 0.0


class MatchQuery(QueryBuilder):
    """ref: index/query/MatchQueryBuilder.java — analyzed full-text query;
    multi-term OR/AND with minimum_should_match."""

    name = "match"

    def __init__(self, field: str, query: str, operator: str = "or",
                 minimum_should_match: Optional[int] = None):
        super().__init__()
        self.field = field
        self.query = query
        self.operator = operator.lower()
        self.minimum_should_match = minimum_should_match

    def do_execute(self, ctx):
        terms = _analyze_terms(ctx, self.field, self.query)
        if not terms:
            z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
            return z, z.astype(bool)
        scores, mask = _bm25_terms(ctx, self.field, terms)
        required = None
        if self.operator == "and":
            required = len(terms)
        elif self.minimum_should_match:
            required = parse_minimum_should_match(
                self.minimum_should_match, len(terms))
        if required is not None and required > 1:
            dp = ctx.device.postings.get(self.field)
            if dp is None:
                return scores, mask
            sels, cids = [], []
            uniq = sorted(set(terms))
            for ci, t in enumerate(uniq):
                s, _ = dp.select_blocks([dp.host.term_id(t)], [1.0])
                sels.append(s)
                cids.append(np.full(len(s), ci, np.int32))
            counts = bm25_ops.match_count(
                dp.block_docids, dp.block_tfs,
                jnp.asarray(np.concatenate(sels)),
                jnp.asarray(np.concatenate(cids)),
                len(uniq), ctx.n_docs_padded)
            need = len(uniq) if self.operator == "and" else min(required, len(uniq))
            mask = mask & (counts >= need)
            scores = jnp.where(mask, scores, 0.0)
        return scores, mask


class MultiMatchQuery(QueryBuilder):
    """ref: MultiMatchQueryBuilder — best_fields (dis-max over per-field
    match) and most_fields (sum)."""

    name = "multi_match"

    def __init__(self, fields: List[str], query: str, type_: str = "best_fields",
                 tie_breaker: float = 0.0):
        super().__init__()
        self.fields = fields
        self.query = query
        self.type = type_
        self.tie_breaker = tie_breaker

    def do_execute(self, ctx):
        fields = self.fields
        if not fields or fields == ["*"]:
            # default: all text fields (ref: multi_match default field "*")
            fields = [name for name, ft in ctx.mapper.mapper.fields.items()
                      if isinstance(ft, TextFieldType)]
        if not fields:
            z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
            return z, z.astype(bool)
        results = [MatchQuery(f, self.query).execute(ctx) for f in fields]
        scores = [s for s, _ in results]
        masks = [m for _, m in results]
        any_mask = masks[0]
        for m in masks[1:]:
            any_mask = any_mask | m
        if self.type == "most_fields":
            total = scores[0]
            for s in scores[1:]:
                total = total + s
            return total, any_mask
        stacked = jnp.stack(scores)
        best = stacked.max(axis=0)
        if self.tie_breaker > 0.0:
            best = best + self.tie_breaker * (stacked.sum(axis=0) - best)
        return best, any_mask


class TermQuery(QueryBuilder):
    """ref: TermQueryBuilder — exact term; keyword fields score BM25 with
    tf=1 and norms omitted (Lucene keyword fields have no norms:
    score = idf·1/(1+k1)); numeric/date/bool terms are constant-score
    point matches."""

    name = "term"

    def __init__(self, field: str, value: Any):
        super().__init__()
        self.field = field
        self.value = value

    def do_execute(self, ctx):
        from elasticsearch_tpu.index.mapper import (ConstantKeywordFieldType,
                                                    _RangeFieldType)
        ft = ctx.mapper.field_type(self.field)
        if isinstance(ft, ConstantKeywordFieldType):
            # matches every doc of the index iff the value equals the constant
            if ft.value is not None and str(self.value) == ft.value:
                mask = ctx.all_true()
            else:
                mask = jnp.zeros(ctx.n_docs_padded, bool)
            return mask.astype(jnp.float32), mask
        if isinstance(ft, _RangeFieldType):
            # point containment in the stored interval (ref: RangeFieldMapper
            # term query semantics: ranges containing the value match)
            v = float(ft.value_type(ft.name).parse(self.value))
            lo, miss = ctx.numeric_column(f"{self.field}.lo")
            hi, _ = ctx.numeric_column(f"{self.field}.hi")
            mask = (~miss) & (lo <= v) & (v <= hi) & ctx.all_true()
            return mask.astype(jnp.float32), mask
        if (ft is None or isinstance(ft, (TextFieldType, KeywordFieldType))
                # join relation names and flattened leaves index as
                # plain terms (ref: ParentJoinFieldMapper — the join
                # field is searchable like a keyword)
                or ft.docvalue_kind in ("flattened", "join")):
            dp = ctx.device.postings.get(self.field)
            if dp is None:
                z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
                return z, z.astype(bool)
            term = str(self.value)
            tid = dp.host.term_id(term)
            sel, _ = dp.select_blocks([tid], [1.0])
            mask = bm25_ops.match_mask(
                dp.block_docids, dp.block_tfs, jnp.asarray(sel),
                ctx.n_docs_padded)
            if not isinstance(ft, TextFieldType):
                doc_count, _ = ctx.stats.field_stats(self.field)
                df = ctx.stats.doc_freq(self.field, term)
                w = bm25_ops.idf(df, doc_count) if df else 0.0
                const = w * 1.0 / (1.0 + ctx.k1)   # tf=1, no norms
                return mask.astype(jnp.float32) * const, mask
            # text field + term query: unanalyzed exact term, BM25-scored
            scores, mask2 = _bm25_terms(ctx, self.field, [term])
            return scores, mask2
        if (getattr(ft, "type_name", "") == "ip"
                and "/" in str(self.value)):
            # CIDR term on an ip field matches the whole block (ref:
            # IpFieldMapper termQuery accepts prefix expressions)
            import ipaddress
            try:
                net = ipaddress.ip_network(str(self.value), strict=False)
            except ValueError:
                raise IllegalArgumentException(
                    f"'{self.value}' is not an IP string literal or "
                    f"CIDR block")
            lo = float(int(net.network_address))
            hi = float(int(net.broadcast_address))
            col, miss = ctx.numeric_column(self.field)
            mask = (~miss) & (col >= lo) & (col <= hi) & ctx.all_true()
            return mask.astype(jnp.float32), mask
        # numeric/date/boolean: point match, constant score
        parsed = float(ft.parse(self.value))
        col, miss = ctx.numeric_column(self.field)
        mask = (~miss) & (col == parsed) & ctx.all_true()
        return mask.astype(jnp.float32), mask


class TermsQuery(QueryBuilder):
    """ref: TermsQueryBuilder — constant score 1.0 for any-of."""

    name = "terms"

    def __init__(self, field: str, values: List[Any]):
        super().__init__()
        self.field = field
        self.values = values

    def do_execute(self, ctx):
        ft = ctx.mapper.field_type(self.field)
        if ft is None or isinstance(ft, (TextFieldType, KeywordFieldType)):
            dp = ctx.device.postings.get(self.field)
            if dp is None:
                z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
                return z, z.astype(bool)
            tids = [dp.host.term_id(str(v)) for v in self.values]
            sel, _ = dp.select_blocks(tids, [1.0] * len(tids))
            mask = bm25_ops.match_mask(
                dp.block_docids, dp.block_tfs, jnp.asarray(sel),
                ctx.n_docs_padded)
            return mask.astype(jnp.float32), mask
        col, miss = ctx.numeric_column(self.field)
        mask = jnp.zeros(ctx.n_docs_padded, bool)
        for v in self.values:
            mask = mask | (col == float(ft.parse(v)))
        mask = mask & (~miss) & ctx.all_true()
        return mask.astype(jnp.float32), mask


class RangeQuery(QueryBuilder):
    name = "range"

    def __init__(self, field: str, gte=None, gt=None, lte=None, lt=None,
                 relation: str = "intersects"):
        super().__init__()
        self.field = field
        self.gte, self.gt, self.lte, self.lt = gte, gt, lte, lt
        self.relation = relation.lower()

    def do_execute(self, ctx):
        from elasticsearch_tpu.index.mapper import _RangeFieldType
        ft = ctx.mapper.field_type(self.field)
        if ft is None:
            z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
            return z, z.astype(bool)
        if isinstance(ft, _RangeFieldType):
            return self._execute_on_range_field(ctx, ft)
        parse = lambda v: float(ft.parse(v))  # noqa: E731
        col, miss = ctx.numeric_column(self.field)
        mask = (~miss) & ctx.all_true()
        if self.gte is not None:
            mask = mask & (col >= parse(self.gte))
        if self.gt is not None:
            mask = mask & (col > parse(self.gt))
        if self.lte is not None:
            mask = mask & (col <= parse(self.lte))
        if self.lt is not None:
            mask = mask & (col < parse(self.lt))
        return mask.astype(jnp.float32), mask

    def _execute_on_range_field(self, ctx, ft) -> Result:
        """Interval relation against range-typed fields (ref:
        RangeFieldMapper + the range query `relation` param:
        intersects | within | contains)."""
        vt = ft.value_type(ft.name)
        q_lo, q_hi = -np.inf, np.inf
        if self.gte is not None:
            q_lo = float(vt.parse(self.gte))
        if self.gt is not None:
            q_lo = np.nextafter(float(vt.parse(self.gt)), np.inf)
        if self.lte is not None:
            q_hi = float(vt.parse(self.lte))
        if self.lt is not None:
            q_hi = np.nextafter(float(vt.parse(self.lt)), -np.inf)
        lo, miss = ctx.numeric_column(f"{self.field}.lo")
        hi, _ = ctx.numeric_column(f"{self.field}.hi")
        if self.relation == "within":
            mask = (lo >= q_lo) & (hi <= q_hi)
        elif self.relation == "contains":
            mask = (lo <= q_lo) & (hi >= q_hi)
        elif self.relation == "intersects":
            mask = (lo <= q_hi) & (hi >= q_lo)
        else:
            raise ParsingException(
                f"[range] unknown relation [{self.relation}]")
        mask = mask & (~miss) & ctx.all_true()
        return mask.astype(jnp.float32), mask


class ExistsQuery(QueryBuilder):
    name = "exists"

    def __init__(self, field: str):
        super().__init__()
        self.field = field

    def do_execute(self, ctx):
        from elasticsearch_tpu.index.mapper import (ConstantKeywordFieldType,
                                                    _RangeFieldType)
        ft = ctx.mapper.field_type(self.field)
        dev = ctx.device
        if isinstance(ft, ConstantKeywordFieldType):
            # every doc of the index "has" the constant (ref: x-pack
            # constant-keyword exists semantics)
            mask = ctx.all_true()
        elif isinstance(ft, _RangeFieldType):
            _, miss = ctx.numeric_column(f"{self.field}.lo")
            mask = (~miss) & ctx.all_true()
        elif ft is not None and ft.docvalue_kind == "geo":
            _, miss = ctx.numeric_column(f"{self.field}.lat")
            mask = (~miss) & ctx.all_true()
        elif self.field in dev.postings:
            lens = dev.postings[self.field].doc_lens
            mask = (lens > 0) & ctx.all_true()
        elif self.field in dev.numerics:
            mask = (~dev.numeric_missing[self.field]) & ctx.all_true()
        elif self.field in dev.vectors:
            mask = dev.vectors[self.field].has_value & ctx.all_true()
        else:
            mask = jnp.zeros(ctx.n_docs_padded, bool)
        return mask.astype(jnp.float32), mask


class IdsQuery(QueryBuilder):
    name = "ids"

    def __init__(self, values: List[str]):
        super().__init__()
        self.values = values

    def do_execute(self, ctx):
        m = np.zeros(ctx.n_docs_padded, bool)
        for doc_id in self.values:
            docid = ctx.segment.docid_for(str(doc_id))
            if docid >= 0:
                m[docid] = True
        mask = jnp.asarray(m)
        return mask.astype(jnp.float32), mask


class BoolQuery(QueryBuilder):
    """ref: BoolQueryBuilder — must (scoring, all required), filter
    (non-scoring, required), should (scoring, optional unless no
    must/filter), must_not (excluded). Composed as mask algebra over dense
    arrays instead of Lucene's ConjunctionDISI/disjunction iterators."""

    name = "bool"

    def __init__(self, must=None, filter=None, should=None, must_not=None,
                 minimum_should_match: Optional[int] = None):
        super().__init__()
        self.must = must or []
        self.filter = filter or []
        self.should = should or []
        self.must_not = must_not or []
        self.minimum_should_match = minimum_should_match

    def do_execute(self, ctx):
        scores = jnp.zeros(ctx.n_docs_padded, jnp.float32)
        mask = ctx.all_true()
        for q in self.must:
            s, m = q.execute(ctx)
            scores = scores + s
            mask = mask & m
        for q in self.filter:
            _, m = q.execute(ctx)
            mask = mask & m
        for q in self.must_not:
            _, m = q.execute(ctx)
            mask = mask & (~m)
        if self.should:
            should_results = [q.execute(ctx) for q in self.should]
            for s, _ in should_results:
                scores = scores + s
            if self.minimum_should_match is None:
                msm = 1 if not (self.must or self.filter) else 0
            else:
                msm = parse_minimum_should_match(
                    self.minimum_should_match, len(self.should))
            if msm > 0:
                count = jnp.zeros(ctx.n_docs_padded, jnp.int32)
                for _, m in should_results:
                    count = count + m.astype(jnp.int32)
                mask = mask & (count >= msm)
        scores = jnp.where(mask, scores, 0.0)
        return scores, mask

    def rewrite(self, searcher):
        # non-mutating: shards must not see each other's rewrites
        must = [q.rewrite(searcher) for q in self.must]
        filt = [q.rewrite(searcher) for q in self.filter]
        should = [q.rewrite(searcher) for q in self.should]
        must_not = [q.rewrite(searcher) for q in self.must_not]
        if (all(a is b for a, b in zip(must, self.must))
                and all(a is b for a, b in zip(filt, self.filter))
                and all(a is b for a, b in zip(should, self.should))
                and all(a is b for a, b in zip(must_not, self.must_not))):
            return self
        q = BoolQuery(must=must, filter=filt, should=should,
                      must_not=must_not,
                      minimum_should_match=self.minimum_should_match)
        q.boost = self.boost
        return q


class ConstantScoreQuery(QueryBuilder):
    name = "constant_score"

    def __init__(self, filter_query: QueryBuilder):
        super().__init__()
        self.filter_query = filter_query

    def do_execute(self, ctx):
        _, mask = self.filter_query.execute(ctx)
        return mask.astype(jnp.float32), mask

    def rewrite(self, searcher):
        inner = self.filter_query.rewrite(searcher)
        if inner is self.filter_query:
            return self
        q = ConstantScoreQuery(inner)
        q.boost = self.boost
        return q


class DisMaxQuery(QueryBuilder):
    name = "dis_max"

    def __init__(self, queries: List[QueryBuilder], tie_breaker: float = 0.0):
        super().__init__()
        self.queries = queries
        self.tie_breaker = tie_breaker

    def do_execute(self, ctx):
        results = [q.execute(ctx) for q in self.queries]
        stacked = jnp.stack([s for s, _ in results])
        mask = results[0][1]
        for _, m in results[1:]:
            mask = mask | m
        best = stacked.max(axis=0)
        if self.tie_breaker > 0.0:
            best = best + self.tie_breaker * (stacked.sum(axis=0) - best)
        best = jnp.where(mask, best, 0.0)
        return best, mask

    def rewrite(self, searcher):
        queries = [q.rewrite(searcher) for q in self.queries]
        if all(a is b for a, b in zip(queries, self.queries)):
            return self
        q = DisMaxQuery(queries, tie_breaker=self.tie_breaker)
        q.boost = self.boost
        return q


class BoostingQuery(QueryBuilder):
    """ref: BoostingQueryBuilder — demote (not exclude) negative matches."""

    name = "boosting"

    def __init__(self, positive: QueryBuilder, negative: QueryBuilder,
                 negative_boost: float):
        super().__init__()
        self.positive = positive
        self.negative = negative
        self.negative_boost = negative_boost

    def do_execute(self, ctx):
        s, mask = self.positive.execute(ctx)
        _, neg = self.negative.execute(ctx)
        s = jnp.where(neg, s * self.negative_boost, s)
        return s, mask

    def rewrite(self, searcher):
        pos = self.positive.rewrite(searcher)
        neg = self.negative.rewrite(searcher)
        if pos is self.positive and neg is self.negative:
            return self
        q = BoostingQuery(pos, neg, self.negative_boost)
        q.boost = self.boost
        return q


def _make_vector_fns(ctx: SegmentContext):
    """cosineSimilarity/dotProduct/l2norm for scripts (parity surface of
    ScoreScriptUtils.java:112-170), batched over the whole segment."""

    def _get(field):
        dv = ctx.device.vectors.get(field)
        if dv is None:
            raise QueryShardException(f"unknown vector field [{field}]")
        return dv

    def cosine(query_vector, field):
        dv = _get(field)
        q = jnp.asarray(np.asarray(query_vector, np.float32))[None, :]
        if dv.similarity == "cosine":
            return vec_ops.cosine_scores(q, dv.vectors)[0]
        qn = jnp.linalg.norm(q)
        raw = vec_ops.dot_scores(q, dv.vectors)[0]
        denom = jnp.where(dv.norms > 0, dv.norms * qn, 1.0)
        return raw / denom

    def dot(query_vector, field):
        dv = _get(field)
        q = jnp.asarray(np.asarray(query_vector, np.float32))[None, :]
        raw = vec_ops.dot_scores(q, dv.vectors)[0]
        if dv.similarity == "cosine":   # slab is pre-normalized; undo
            raw = raw * dv.norms
        return raw

    def l2norm(query_vector, field):
        dv = _get(field)
        q = jnp.asarray(np.asarray(query_vector, np.float32))[None, :]
        vecs = dv.vectors * dv.norms[:, None] if dv.similarity == "cosine" else dv.vectors
        return jnp.sqrt(jnp.maximum(
            0.0, -vec_ops.l2_scores(q, vecs, dv.sq_norms)[0]))

    return {"cosineSimilarity": cosine, "dotProduct": dot, "l2norm": l2norm}


class ScriptScoreQuery(QueryBuilder):
    """ref: ScriptScoreQueryBuilder + ScriptScoreQuery.java:51,91-109 — the
    subquery filters, the script replaces the score. Script runs once over
    columns, not per doc."""

    name = "script_score"

    def __init__(self, query: QueryBuilder, source: str,
                 params: Optional[Dict[str, Any]] = None,
                 min_score: Optional[float] = None):
        super().__init__()
        self.query = query
        self.source = source
        self.params = params or {}
        self.min_score = min_score
        self._compiled = compile_script(source)

    def do_execute(self, ctx):
        base_scores, mask = self.query.execute(ctx)

        def doc_columns(field):
            col, miss = ctx.numeric_column(field)
            return _DocColumn(col, miss)

        sctx = ScriptContext(doc_columns, self.params, score=base_scores,
                             vector_fns=_make_vector_fns(ctx),
                             mask=mask)
        scores = jnp.asarray(self._compiled(sctx), jnp.float32)
        scores = jnp.broadcast_to(scores, (ctx.n_docs_padded,))
        scores = jnp.where(mask, scores, 0.0)
        if self.min_score is not None:
            mask = mask & (scores >= self.min_score)
            scores = jnp.where(mask, scores, 0.0)
        return scores, mask

    def rewrite(self, searcher):
        inner = self.query.rewrite(searcher)
        if inner is self.query:
            return self
        q = ScriptScoreQuery(inner, self.source, self.params,
                             min_score=self.min_score)
        q.boost = self.boost
        return q


class KnnQuery(QueryBuilder):
    """Native brute-force kNN — net-new surface (the reference only has
    script_score brute force; no ANN at this version, SURVEY.md §2.6).
    Score transforms follow the modern ES kNN conventions:
    cosine → (1+cos)/2, dot_product → (1+dot)/2, l2_norm → 1/(1+d²)."""

    name = "knn"

    def __init__(self, field: str, query_vector: List[float],
                 num_candidates: Optional[int] = None,
                 filter_query: Optional[QueryBuilder] = None,
                 k: Optional[int] = None):
        super().__init__()
        self.field = field
        self.query_vector = np.asarray(query_vector, np.float32)
        self.num_candidates = num_candidates
        self.filter_query = filter_query
        self.k = k

    def do_execute(self, ctx):
        dv = ctx.device.vectors.get(self.field)
        if dv is None:
            z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
            return z, z.astype(bool)
        q = jnp.asarray(self.query_vector)[None, :]
        if dv.similarity == "cosine":
            raw = vec_ops.cosine_scores(q, dv.vectors)[0]
            scores = (1.0 + raw) / 2.0
        elif dv.similarity == "dot_product":
            raw = vec_ops.dot_scores(q, dv.vectors)[0]
            scores = (1.0 + raw) / 2.0
        else:  # l2_norm
            neg_sq = vec_ops.l2_scores(q, dv.vectors, dv.sq_norms)[0]
            scores = 1.0 / (1.0 - neg_sq)
        mask = dv.has_value & ctx.all_true()
        if self.filter_query is not None:
            _, fm = self.filter_query.execute(ctx)
            mask = mask & fm
        scores = jnp.where(mask, scores, 0.0)
        scores = self._exact_rerank(ctx, dv, scores)
        cut = self.k or self.num_candidates
        if cut is not None and cut < ctx.n_docs_padded:
            # keep only the k nearest per segment (the gather half of
            # ES's gather-then-merge kNN — the coordinator merge keeps
            # the global k)
            kth = jnp.sort(jnp.where(mask, scores, -jnp.inf))[
                ctx.n_docs_padded - int(cut)]
            mask = mask & (scores >= kth)
            scores = jnp.where(mask, scores, 0.0)
        return scores, mask

    def _exact_rerank(self, ctx, dv, scores):
        """When the device slab is QUANTIZED (bf16 — an 8M×768 f32 slab
        exceeds single-chip HBM, BASELINE.md config 4), the quantized
        scores only NOMINATE candidates: the top num_candidates
        (default 3k) get their similarities recomputed exactly in
        float32 from the segment's host vectors and scattered back, so
        the final top-k ranks on exact f32 — recall vs an f32 oracle is
        then bounded only by candidate coverage, not by bf16 rounding."""
        if dv.vectors.dtype == jnp.float32:
            return scores
        seg = getattr(ctx.device, "segment", None)
        vv = seg.vectors.get(self.field) if seg is not None else None
        if vv is None:
            return scores
        nc = int(self.num_candidates or 3 * (self.k or 1000))
        nc = min(nc, ctx.n_docs_padded)
        _, ids = jax.lax.top_k(scores, nc)
        # tiny readback [nc] — THE canonical degraded-regime trigger
        # (BENCH ×56-79 notes); tracked so the flight recorder can name
        # it when the regime flips
        ids_h = device_ops.readback("search.queries.knn_rerank_ids", ids)
        ids_h = ids_h[ids_h < vv.vectors.shape[0]]
        exact = vec_ops.exact_rerank_scores(
            vv.vectors[ids_h], self.query_vector.astype(np.float32),
            dv.similarity)
        return scores.at[jnp.asarray(ids_h)].set(
            jnp.asarray(exact), mode="drop", unique_indices=True)

    def rewrite(self, searcher):
        if self.filter_query is None:
            return self
        inner = self.filter_query.rewrite(searcher)
        if inner is self.filter_query:
            return self
        q = KnnQuery(self.field, self.query_vector,
                     num_candidates=self.num_candidates, filter_query=inner,
                     k=self.k)
        q.boost = self.boost
        return q


class FunctionScoreQuery(QueryBuilder):
    """ref: functionscore/FunctionScoreQueryBuilder — subset: script_score
    function, weight, boost_mode/score_mode multiply|sum|replace."""

    name = "function_score"

    def __init__(self, query: QueryBuilder, functions: List[Dict[str, Any]],
                 boost_mode: str = "multiply", score_mode: str = "multiply"):
        super().__init__()
        self.query = query
        self.functions = functions
        self.boost_mode = boost_mode
        self.score_mode = score_mode

    def do_execute(self, ctx):
        base, mask = self.query.execute(ctx)
        fn_scores = []
        for fn in self.functions:
            weight = float(fn.get("weight", 1.0))
            if "script_score" in fn:
                script = fn["script_score"]["script"]
                compiled = compile_script(script.get("source", script)
                                          if isinstance(script, dict) else script)

                def doc_columns(field):
                    col, miss = ctx.numeric_column(field)
                    return _DocColumn(col, miss)

                sctx = ScriptContext(
                    doc_columns,
                    (script.get("params", {}) if isinstance(script, dict) else {}),
                    score=base, vector_fns=_make_vector_fns(ctx))
                val = jnp.broadcast_to(
                    jnp.asarray(compiled(sctx), jnp.float32),
                    (ctx.n_docs_padded,))
                fn_scores.append(val * weight)
            else:
                fn_scores.append(jnp.full(ctx.n_docs_padded, weight, jnp.float32))
        if fn_scores:
            combined = fn_scores[0]
            for f in fn_scores[1:]:
                combined = (combined * f if self.score_mode == "multiply"
                            else combined + f)
            if self.boost_mode == "multiply":
                scores = base * combined
            elif self.boost_mode == "sum":
                scores = base + combined
            else:  # replace
                scores = combined
        else:
            scores = base
        scores = jnp.where(mask, scores, 0.0)
        return scores, mask

    def rewrite(self, searcher):
        inner = self.query.rewrite(searcher)
        if inner is self.query:
            return self
        q = FunctionScoreQuery(inner, self.functions,
                               boost_mode=self.boost_mode,
                               score_mode=self.score_mode)
        q.boost = self.boost
        return q


# ---------------------------------------------------------------------------
# Positional queries (token-stream based; see search/phrase.py)
# ---------------------------------------------------------------------------

def _conjunction_mask(ctx: SegmentContext, field: str,
                      tids: List[int]) -> jnp.ndarray:
    """Device mask of docs containing ALL the given term ids."""
    dp = ctx.device.postings.get(field)
    if dp is None:
        return jnp.zeros(ctx.n_docs_padded, bool)
    sels, cids = [], []
    for ci, tid in enumerate(tids):
        s, _ = dp.select_blocks([tid], [1.0])
        sels.append(s)
        cids.append(np.full(len(s), ci, np.int32))
    counts = bm25_ops.match_count(
        dp.block_docids, dp.block_tfs,
        jnp.asarray(np.concatenate(sels)), jnp.asarray(np.concatenate(cids)),
        len(tids), ctx.n_docs_padded)
    return counts >= len(tids)


def _phrase_scores_from_freqs(ctx: SegmentContext, field: str,
                              cand: np.ndarray, freqs: np.ndarray,
                              idf_weight: float) -> Result:
    """BM25 with tf = phrase frequency (ref: Lucene PhraseWeight: idf is
    summed over member terms, norms are the field's)."""
    pf = ctx.segment.postings[field]
    keep = freqs > 0
    cand, freqs = cand[keep], freqs[keep]
    z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
    if len(cand) == 0:
        return z, z.astype(bool)
    _, avg_len = ctx.stats.field_stats(field)
    dl = pf.field_lengths[cand]
    tf = freqs.astype(np.float32)
    norm = ctx.k1 * (1.0 - ctx.b + ctx.b * dl / max(avg_len, 1e-9))
    s = idf_weight * tf / (tf + norm)
    scores_np = np.zeros(ctx.n_docs_padded, np.float32)
    scores_np[cand] = s
    scores = jnp.asarray(scores_np)
    return scores, scores > 0.0


class MatchPhraseQuery(QueryBuilder):
    """ref: MatchPhraseQueryBuilder / Lucene PhraseQuery. Device-side
    conjunctive filter over the phrase's terms, then exact position
    verification on the host over only the surviving candidates' token
    streams (search/phrase.py)."""

    name = "match_phrase"

    def __init__(self, field: str, query: str, slop: int = 0):
        super().__init__()
        self.field = field
        self.query = query
        self.slop = slop

    def do_execute(self, ctx):
        from elasticsearch_tpu.search.phrase import sloppy_phrase_freqs
        terms = _analyze_terms(ctx, self.field, self.query)
        z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
        empty = (z, z.astype(bool))
        if not terms:
            return empty
        if len(terms) == 1:
            return _bm25_terms(ctx, self.field, terms)
        seg = ctx.segment
        pf = seg.postings.get(self.field)
        ts = seg.streams.get(self.field)
        if pf is None or ts is None:
            return empty
        tids = [pf.term_id(t) for t in terms]
        if any(t < 0 for t in tids):
            return empty  # a missing term can't complete the phrase
        cand_mask = np.asarray(_conjunction_mask(
            ctx, self.field, sorted(set(tids))))[: seg.n_docs]
        cand = np.nonzero(cand_mask)[0]
        if len(cand) == 0:
            return empty
        freqs = sloppy_phrase_freqs(ts.tokens[cand], ts.lengths[cand],
                                    tids, self.slop)
        doc_count, _ = ctx.stats.field_stats(self.field)
        w = sum(bm25_ops.idf(ctx.stats.doc_freq(self.field, t), doc_count)
                for t in set(terms))
        return _phrase_scores_from_freqs(ctx, self.field, cand, freqs, w)


class MatchPhrasePrefixQuery(QueryBuilder):
    """ref: MatchPhrasePrefixQueryBuilder — phrase whose last token is a
    prefix, expanded against the segment's term dictionary (capped at
    max_expansions, default 50)."""

    name = "match_phrase_prefix"

    def __init__(self, field: str, query: str, max_expansions: int = 50,
                 slop: int = 0):
        super().__init__()
        self.field = field
        self.query = query
        self.max_expansions = max_expansions
        self.slop = slop

    def do_execute(self, ctx):
        from elasticsearch_tpu.search.phrase import (
            phrase_prefix_freqs,
            sloppy_phrase_freqs,
        )
        terms = _analyze_terms(ctx, self.field, self.query)
        z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
        empty = (z, z.astype(bool))
        if not terms:
            return empty
        seg = ctx.segment
        pf = seg.postings.get(self.field)
        ts = seg.streams.get(self.field)
        if pf is None or ts is None:
            return empty
        *fixed, last = terms
        exp = _expand_prefix(pf.terms, last, self.max_expansions)
        if not exp:
            return empty
        exp_ids = [pf.term_id(t) for t in exp]
        if not fixed:
            # single-token prefix: behaves like a prefix query, scored as a
            # one-term phrase with union df
            dp = ctx.device.postings.get(self.field)
            sel, _ = dp.select_blocks(exp_ids, [1.0] * len(exp_ids))
            mask = bm25_ops.match_mask(dp.block_docids, dp.block_tfs,
                                       jnp.asarray(sel), ctx.n_docs_padded)
            return mask.astype(jnp.float32), mask
        tids = [pf.term_id(t) for t in fixed]
        if any(t < 0 for t in tids):
            return empty
        cand_mask = np.asarray(_conjunction_mask(
            ctx, self.field, sorted(set(tids))))[: seg.n_docs]
        cand = np.nonzero(cand_mask)[0]
        if len(cand) == 0:
            return empty
        if self.slop > 0:
            freqs = sloppy_phrase_freqs(ts.tokens[cand], ts.lengths[cand],
                                        tids, self.slop,
                                        last_alternatives=exp_ids)
        else:
            freqs = phrase_prefix_freqs(ts.tokens[cand], tids, exp_ids)
        doc_count, _ = ctx.stats.field_stats(self.field)
        w = sum(bm25_ops.idf(ctx.stats.doc_freq(self.field, t), doc_count)
                for t in set(fixed))
        # shard-level stats for the expansion slot, matching the fixed
        # terms' idfs above (segment-local df would skew per-segment scores)
        df_union = min(doc_count,
                       sum(ctx.stats.doc_freq(self.field, t) for t in exp))
        w += bm25_ops.idf(max(df_union, 1), doc_count)
        return _phrase_scores_from_freqs(ctx, self.field, cand, freqs, w)


class MatchBoolPrefixQuery(QueryBuilder):
    """ref: MatchBoolPrefixQueryBuilder — bool OR of the analyzed terms,
    with the final term as a prefix."""

    name = "match_bool_prefix"

    def __init__(self, field: str, query: str, max_expansions: int = 50):
        super().__init__()
        self.field = field
        self.query = query
        self.max_expansions = max_expansions

    def do_execute(self, ctx):
        terms = _analyze_terms(ctx, self.field, self.query)
        z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
        if not terms:
            return z, z.astype(bool)
        *fixed, last = terms
        scores, mask = (_bm25_terms(ctx, self.field, fixed) if fixed
                        else (z, z.astype(bool)))
        ps, pm = PrefixQuery(self.field, last,
                             max_expansions=self.max_expansions).execute(ctx)
        return scores + ps, mask | pm


# ---------------------------------------------------------------------------
# Multi-term queries (term-dictionary expansion, constant-score rewrite —
# ref: Lucene MultiTermQuery CONSTANT_SCORE_REWRITE)
# ---------------------------------------------------------------------------

MAX_TERM_EXPANSIONS = 1024  # ref: indices.query.bool.max_clause_count


def _expand_prefix(terms: List[str], prefix: str, cap: int) -> List[str]:
    import bisect
    lo = bisect.bisect_left(terms, prefix)
    out = []
    for i in range(lo, len(terms)):
        if not terms[i].startswith(prefix):
            break
        out.append(terms[i])
        if len(out) >= cap:
            break
    return out


def _expand_regex(terms: List[str], pattern, cap: int) -> List[str]:
    out = []
    for t in terms:
        if pattern.fullmatch(t):
            out.append(t)
            if len(out) >= cap:
                break
    return out


def _edit_distance_within(a: str, b: str, k: int) -> int:
    """Damerau-Levenshtein (optimal string alignment — adjacent
    transposition counts as ONE edit, matching Lucene fuzzy's default
    ``transpositions=true``) if <= k else k+1, with early exit."""
    la, lb = len(a), len(b)
    if abs(la - lb) > k:
        return k + 1
    prev2: Optional[List[int]] = None
    prev = list(range(lb + 1))
    for i in range(1, la + 1):
        cur = [i] + [0] * lb
        row_min = cur[0]
        for j in range(1, lb + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            d = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
            if (prev2 is not None and i > 1 and j > 1
                    and a[i - 1] == b[j - 2] and a[i - 2] == b[j - 1]):
                d = min(d, prev2[j - 2] + 1)
            cur[j] = d
            row_min = min(row_min, d)
        if row_min > k:
            return k + 1
        prev2, prev = prev, cur
    return prev[lb]


def resolve_fuzziness(fuzziness, term: str) -> int:
    """ES Fuzziness: int, "AUTO", "AUTO:low,high"."""
    if fuzziness is None or (isinstance(fuzziness, str)
                             and fuzziness.upper().startswith("AUTO")):
        low, high = 3, 6
        if isinstance(fuzziness, str) and ":" in fuzziness:
            try:
                low, high = (int(x) for x in fuzziness.split(":")[1].split(","))
            except ValueError:
                pass
        n = len(term)
        return 0 if n < low else (1 if n < high else 2)
    return int(fuzziness)


class _MultiTermQuery(QueryBuilder):
    """Shared machinery: expand per segment against the term dictionary,
    match any expansion, constant score 1.0."""

    def __init__(self, field: str):
        super().__init__()
        self.field = field

    def expand(self, terms: List[str]) -> List[str]:
        raise NotImplementedError

    def do_execute(self, ctx):
        dp = ctx.device.postings.get(self.field)
        z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
        if dp is None:
            return z, z.astype(bool)
        expanded = self.expand(dp.host.terms)
        if not expanded:
            return z, z.astype(bool)
        tids = [dp.host.term_id(t) for t in expanded]
        sel, _ = dp.select_blocks(tids, [1.0] * len(tids))
        mask = bm25_ops.match_mask(dp.block_docids, dp.block_tfs,
                                   jnp.asarray(sel), ctx.n_docs_padded)
        return mask.astype(jnp.float32), mask


class PrefixQuery(_MultiTermQuery):
    """ref: PrefixQueryBuilder."""

    name = "prefix"

    def __init__(self, field: str, value: str, max_expansions: int = MAX_TERM_EXPANSIONS):
        super().__init__(field)
        self.value = str(value)
        self.max_expansions = max_expansions

    def expand(self, terms):
        return _expand_prefix(terms, self.value, self.max_expansions)


class WildcardQuery(_MultiTermQuery):
    """ref: WildcardQueryBuilder — `*` any sequence, `?` any single char."""

    name = "wildcard"

    def __init__(self, field: str, value: str):
        super().__init__(field)
        self.value = str(value)
        import re as _re
        esc = "".join(
            ".*" if c == "*" else "." if c == "?" else _re.escape(c)
            for c in self.value)
        self._re = _re.compile(esc)

    def expand(self, terms):
        # literal prefix before the first wildcard narrows the scan
        import re as _re
        lit = _re.split(r"[*?]", self.value, maxsplit=1)[0]
        if lit:
            cands = _expand_prefix(terms, lit, len(terms))
            return [t for t in cands if self._re.fullmatch(t)][:MAX_TERM_EXPANSIONS]
        return _expand_regex(terms, self._re, MAX_TERM_EXPANSIONS)


class RegexpQuery(_MultiTermQuery):
    """ref: RegexpQueryBuilder — anchored regexp over the term dict."""

    name = "regexp"

    def __init__(self, field: str, value: str):
        super().__init__(field)
        import re as _re
        try:
            self._re = _re.compile(str(value))
        except _re.error as e:
            raise ParsingException(f"invalid regexp [{value}]: {e}")

    def expand(self, terms):
        return _expand_regex(terms, self._re, MAX_TERM_EXPANSIONS)


class FuzzyQuery(QueryBuilder):
    """ref: FuzzyQueryBuilder / Lucene FuzzyQuery with blended rewrite —
    expansions are scored as down-weighted synonyms in ONE kernel call:
    weight = idf · (1 - dist/len)."""

    name = "fuzzy"

    def __init__(self, field: str, value: str, fuzziness=None,
                 prefix_length: int = 0, max_expansions: int = 50):
        super().__init__()
        self.field = field
        self.value = str(value)
        self.fuzziness = fuzziness
        self.prefix_length = prefix_length
        self.max_expansions = max_expansions

    def matching_terms(self, terms: List[str]) -> List[Tuple[str, int]]:
        k = resolve_fuzziness(self.fuzziness, self.value)
        pre = self.value[: self.prefix_length]
        cands = (_expand_prefix(terms, pre, len(terms)) if pre else terms)
        out = []
        for t in cands:
            d = _edit_distance_within(self.value, t, k)
            if d <= k:
                out.append((t, d))
        out.sort(key=lambda td: (td[1], td[0]))
        return out[: self.max_expansions]

    def do_execute(self, ctx):
        dp = ctx.device.postings.get(self.field)
        z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
        if dp is None:
            return z, z.astype(bool)
        matches = self.matching_terms(dp.host.terms)
        if not matches:
            return z, z.astype(bool)
        doc_count, avg_len = ctx.stats.field_stats(self.field)
        tids, weights = [], []
        L = max(len(self.value), 1)
        for t, d in matches:
            df = ctx.stats.doc_freq(self.field, t)
            w = bm25_ops.idf(df, doc_count) if df > 0 else 0.0
            tids.append(dp.host.term_id(t))
            weights.append(w * (1.0 - d / L))
        sel, ws = dp.select_blocks(tids, weights)
        from elasticsearch_tpu.ops.bm25 import scan_run_bound
        from elasticsearch_tpu.ops.plan import bm25_dense_scores_sorted
        scores = bm25_dense_scores_sorted(
            dp.block_docids, dp.block_tfs, jnp.asarray(sel), jnp.asarray(ws),
            dp.doc_lens, jnp.float32(avg_len), ctx.k1, ctx.b,
            max_run=scan_run_bound(len(tids)))
        return scores, scores > 0.0


# ---------------------------------------------------------------------------
# more_like_this / pinned / distance_feature
# ---------------------------------------------------------------------------

class MoreLikeThisQuery(QueryBuilder):
    """ref: MoreLikeThisQueryBuilder / Lucene MoreLikeThis — select the
    like-text's most significant terms by tf·idf (shard statistics), then
    run them as an OR with minimum_should_match. Doc references are
    resolved in ``rewrite`` against the shard (the reference fetches
    termvectors on the shard for the same reason)."""

    name = "more_like_this"

    def __init__(self, fields: Optional[List[str]], like, unlike=None,
                 max_query_terms: int = 25, min_term_freq: int = 2,
                 min_doc_freq: int = 5, max_doc_freq: Optional[int] = None,
                 minimum_should_match: str = "30%", include: bool = False):
        super().__init__()
        self.fields = fields
        self.like = like if isinstance(like, list) else [like]
        self.unlike = (unlike if isinstance(unlike, list) else [unlike]) if unlike else []
        self.max_query_terms = max_query_terms
        self.min_term_freq = min_term_freq
        self.min_doc_freq = min_doc_freq
        self.max_doc_freq = max_doc_freq
        self.minimum_should_match = minimum_should_match
        self.include = include

    def rewrite(self, searcher) -> QueryBuilder:
        import json as _json
        mapper = searcher.mapper
        fields = self.fields
        if not fields:
            fields = [name for name, ft in mapper.mapper.fields.items()
                      if isinstance(ft, TextFieldType)]
        like_texts: Dict[str, List[str]] = {f: [] for f in fields}
        doc_ids: List[str] = []
        for like in self.like:
            if isinstance(like, str):
                for f in fields:
                    like_texts[f].append(like)
            elif isinstance(like, dict):
                did = like.get("_id")
                doc_ids.append(did)
                for seg in searcher.segments:
                    d = seg.docid_for(did)
                    if d >= 0:
                        src = _json.loads(seg.stored.source(d))
                        for f in fields:
                            v = src.get(f)
                            if isinstance(v, str):
                                like_texts[f].append(v)
                        break
        unlike_terms: Dict[str, set] = {f: set() for f in fields}
        for ul in self.unlike:
            if isinstance(ul, str):
                for f in fields:
                    ft = mapper.field_type(f)
                    name = getattr(ft, "analyzer_name", "standard")
                    an = (mapper.analysis.get(name) if mapper.analysis.has(name)
                          else mapper.analysis.default)
                    unlike_terms[f].update(an.terms(ul))

        scored: List[Tuple[float, str, str]] = []  # (score, field, term)
        for f in fields:
            ft = mapper.field_type(f)
            name = getattr(ft, "analyzer_name", "standard")
            an = (mapper.analysis.get(name) if mapper.analysis.has(name)
                  else mapper.analysis.default)
            counts: Dict[str, int] = {}
            for text in like_texts[f]:
                for t in an.terms(text):
                    counts[t] = counts.get(t, 0) + 1
            doc_count, _ = searcher.stats.field_stats(f)
            for t, tf in counts.items():
                if tf < self.min_term_freq or t in unlike_terms[f]:
                    continue
                df = searcher.stats.doc_freq(f, t)
                if df < self.min_doc_freq:
                    continue
                if self.max_doc_freq is not None and df > self.max_doc_freq:
                    continue
                scored.append((tf * bm25_ops.idf(df, max(doc_count, 1)), f, t))
        scored.sort(reverse=True)
        selected = scored[: self.max_query_terms]
        if not selected:
            return MatchNoneQuery()
        should: List[QueryBuilder] = [TermQuery(f, t) for _, f, t in selected]
        must_not: List[QueryBuilder] = []
        if doc_ids and not self.include:
            must_not.append(IdsQuery([d for d in doc_ids if d]))
        q = BoolQuery(should=should, must_not=must_not,
                      minimum_should_match=self.minimum_should_match)
        q.boost = self.boost
        return q

    def do_execute(self, ctx):  # pragma: no cover - rewritten before execute
        raise QueryShardException("more_like_this must be rewritten first")


class PinnedQuery(QueryBuilder):
    """ref: x-pack search-business-rules PinnedQueryBuilder — the given ids
    rank above all organic results, in list order."""

    name = "pinned"
    PIN_BASE = 1.0e6  # above any BM25 score; f32-exact spacing of 10

    def __init__(self, ids: List[str], organic: QueryBuilder):
        super().__init__()
        self.ids = ids
        self.organic = organic

    def do_execute(self, ctx):
        scores, mask = self.organic.execute(ctx)
        pin_np = np.zeros(ctx.n_docs_padded, np.float32)
        seg = ctx.segment
        for rank, did in enumerate(self.ids):
            d = seg.docid_for(did)
            if d >= 0:
                pin_np[d] = self.PIN_BASE - 10.0 * rank
        pins = jnp.asarray(pin_np)
        pinned_mask = pins > 0
        scores = jnp.where(pinned_mask, pins, scores)
        return scores, mask | pinned_mask

    def rewrite(self, searcher):
        organic = self.organic.rewrite(searcher)
        if organic is self.organic:
            return self
        q = PinnedQuery(self.ids, organic)
        q.boost = self.boost
        return q


class DistanceFeatureQuery(QueryBuilder):
    """ref: DistanceFeatureQueryBuilder — score decays with distance from
    origin: boost · pivot / (pivot + |value - origin|)."""

    name = "distance_feature"

    def __init__(self, field: str, origin, pivot):
        super().__init__()
        self.field = field
        self.origin = origin
        self.pivot = pivot

    def do_execute(self, ctx):
        ft = ctx.mapper.field_type(self.field)
        origin = float(ft.parse(self.origin)) if ft else float(self.origin)
        pivot = _parse_duration_or_number(self.pivot, ft)
        col, miss = ctx.numeric_column(self.field)
        mask = (~miss) & ctx.all_true()
        dist = jnp.abs(col - origin)
        scores = jnp.where(mask, pivot / (pivot + dist), 0.0).astype(jnp.float32)
        return scores, mask


def _parse_duration_or_number(v, ft) -> float:
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    units = {"ms": 1.0, "s": 1000.0, "m": 60_000.0, "h": 3_600_000.0,
             "d": 86_400_000.0, "w": 604_800_000.0}
    for suffix in sorted(units, key=len, reverse=True):
        if s.endswith(suffix):
            return float(s[: -len(suffix)]) * units[suffix]
    return float(s)


# ---------------------------------------------------------------------------
# query_string / simple_query_string (lite grammars)
# ---------------------------------------------------------------------------

class _QueryStringParser:
    """Recursive-descent mini-grammar for query_string (ref:
    modules/lang-expression + Lucene classic QueryParser surface actually
    used by the REST tests): AND/OR/NOT, parentheses, field:term, quoted
    phrases, wildcard terms, +/- prefixes."""

    def __init__(self, text: str, default_field: Optional[str],
                 fields: Optional[List[str]], default_operator: str):
        self.toks = self._lex(text)
        self.i = 0
        self.default_field = default_field
        self.fields = fields
        self.default_operator = default_operator.lower()

    @staticmethod
    def _lex(text: str) -> List[str]:
        import re as _re
        # field:"phrase" stays one token; then bare phrases, parens, words
        pat = _re.compile(r'[+\-]?[^\s:"()]+:"[^"]*"|"[^"]*"|\(|\)|\S+')
        return pat.findall(text)

    def peek(self) -> Optional[str]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> str:
        t = self.toks[self.i]
        self.i += 1
        return t

    def parse(self) -> QueryBuilder:
        q = self.parse_or()
        if q is None:
            return MatchNoneQuery()
        return q

    def parse_or(self):
        clauses = [self.parse_and()]
        while True:
            nxt = self.peek()
            if nxt in ("OR", "||"):
                self.next()
                clauses.append(self.parse_and())
            elif (nxt is not None and nxt != ")"
                  and self.default_operator == "or"):
                # implicit adjacency binds with the default operator
                clauses.append(self.parse_and())
            else:
                break
        clauses = [c for c in clauses if c]
        if len(clauses) <= 1:
            return clauses[0] if clauses else None
        return BoolQuery(should=clauses, minimum_should_match=1)

    def parse_and(self):
        musts = [self.parse_unary()]
        while True:
            nxt = self.peek()
            if nxt in ("AND", "&&"):
                self.next()
                musts.append(self.parse_unary())
            elif (nxt is not None and nxt not in ("OR", "||", ")")
                  and self.default_operator == "and"):
                musts.append(self.parse_unary())
            else:
                break
        musts = [m for m in musts if m]
        if len(musts) <= 1:
            return musts[0] if musts else None
        return BoolQuery(must=musts)

    def parse_unary(self):
        t = self.peek()
        if t is None or t in (")", "OR", "||", "AND", "&&"):
            return None
        if t == "NOT" or t.startswith("!"):
            if t == "NOT":
                self.next()
            else:
                self.toks[self.i] = t[1:]
            inner = self.parse_unary()
            return BoolQuery(must_not=[inner] if inner else [])
        return self.parse_atom()

    def parse_atom(self):
        t = self.next()
        if t == "(":
            q = self.parse_or()
            if self.peek() == ")":
                self.next()
            return q
        negate = False
        if t.startswith("-") and len(t) > 1:
            negate, t = True, t[1:]
        elif t.startswith("+") and len(t) > 1:
            t = t[1:]
        field = None
        if ":" in t and not t.startswith('"'):
            field, t = t.split(":", 1)
        q = self._term_query(field, t)
        if negate:
            return BoolQuery(must_not=[q])
        return q

    def _term_query(self, field: Optional[str], text: str) -> QueryBuilder:
        targets = ([field] if field
                   else self.fields if self.fields
                   else [self.default_field] if self.default_field
                   else None)
        if text.startswith('"') and text.endswith('"') and len(text) >= 2:
            phrase = text[1:-1]
            if targets and len(targets) == 1:
                return MatchPhraseQuery(targets[0], phrase)
            return MultiMatchPhrase(targets, phrase)
        if "*" in text or "?" in text:
            if targets and len(targets) == 1:
                return WildcardQuery(targets[0], text)
            return BoolQuery(should=[WildcardQuery(f, text) for f in (targets or [])],
                             minimum_should_match=1)
        if targets and len(targets) == 1:
            return MatchQuery(targets[0], text)
        if targets:
            return MultiMatchQuery(targets, text)
        return MultiMatchQuery(["*"], text)


class MultiMatchPhrase(QueryBuilder):
    """Phrase over several fields, dis-max combined."""

    name = "multi_match_phrase"

    def __init__(self, fields: Optional[List[str]], phrase: str):
        super().__init__()
        self.fields = fields
        self.phrase = phrase

    def do_execute(self, ctx):
        fields = self.fields
        if not fields:
            fields = [name for name, ft in ctx.mapper.mapper.fields.items()
                      if isinstance(ft, TextFieldType)]
        results = [MatchPhraseQuery(f, self.phrase).execute(ctx)
                   for f in fields]
        if not results:
            z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
            return z, z.astype(bool)
        scores = jnp.stack([s for s, _ in results]).max(axis=0)
        mask = results[0][1]
        for _, m in results[1:]:
            mask = mask | m
        return scores, mask


class QueryStringQuery(QueryBuilder):
    name = "query_string"

    def __init__(self, query: str, default_field: Optional[str] = None,
                 fields: Optional[List[str]] = None,
                 default_operator: str = "or"):
        super().__init__()
        self.parsed = _QueryStringParser(
            query, default_field, fields, default_operator).parse()

    def do_execute(self, ctx):
        return self.parsed.execute(ctx)

    def rewrite(self, searcher):
        parsed = self.parsed.rewrite(searcher)
        if parsed is self.parsed:
            return self
        q = QueryStringQuery.__new__(QueryStringQuery)
        QueryBuilder.__init__(q)
        q.boost = self.boost
        q.parsed = parsed
        return q


class SimpleQueryStringQuery(QueryBuilder):
    """ref: SimpleQueryStringBuilder — never throws; +,|,-,",* operators."""

    name = "simple_query_string"

    def __init__(self, query: str, fields: Optional[List[str]] = None,
                 default_operator: str = "or"):
        super().__init__()
        self.query = query
        self.fields = fields
        self.default_operator = default_operator.lower()

    def do_execute(self, ctx):
        import re as _re
        toks = _re.findall(r'"[^"]*"|\S+', self.query)
        must_not, should = [], []
        groups = [[]]
        for t in toks:
            if t == "|":
                groups.append([])
                continue
            groups[-1].append(t)

        def tok_query(tok: str) -> Optional[QueryBuilder]:
            if tok.startswith('"') and tok.endswith('"') and len(tok) >= 2:
                return MultiMatchPhrase(self.fields, tok[1:-1])
            if tok.endswith("*") and len(tok) > 1:
                fields = self.fields or ["*"]
                if fields == ["*"]:
                    fields = [name for name, ft in ctx.mapper.mapper.fields.items()
                              if isinstance(ft, TextFieldType)]
                return BoolQuery(should=[PrefixQuery(f, tok[:-1]) for f in fields],
                                 minimum_should_match=1)
            return MultiMatchQuery(self.fields or ["*"], tok)

        for group in groups:
            gclauses = []
            for tok in group:
                if tok.startswith("-") and len(tok) > 1:
                    q = tok_query(tok[1:])
                    if q:
                        must_not.append(q)
                    continue
                if tok.startswith("+") and len(tok) > 1:
                    tok = tok[1:]
                q = tok_query(tok)
                if q:
                    gclauses.append(q)
            if gclauses:
                inner = (gclauses[0] if len(gclauses) == 1
                         else BoolQuery(must=gclauses)
                         if self.default_operator == "and"
                         else BoolQuery(should=gclauses, minimum_should_match=1))
                should.append(inner)
        if not should and not must_not:
            return MatchAllQuery().execute(ctx)
        q = BoolQuery(should=should, must_not=must_not,
                      minimum_should_match=1 if should else None)
        return q.execute(ctx)


# ---------------------------------------------------------------------------
# Parsing (ref: AbstractQueryBuilder.parseInnerQueryBuilder via
# NamedXContentRegistry)
# ---------------------------------------------------------------------------

class RankFeatureQuery(QueryBuilder):
    """Score by a rank_feature(s) column (ref: modules/mapper-extras
    RankFeatureQueryBuilder — saturation (default, pivot≈mean),
    log, sigmoid, linear functions). Pure elementwise math over the
    feature column; docs missing the feature don't match."""

    name = "rank_feature"

    def __init__(self, field: str, saturation=None, log=None, sigmoid=None,
                 linear=None):
        super().__init__()
        self.field = field
        self.saturation = saturation
        self.log = log
        self.sigmoid = sigmoid
        self.linear = linear

    def do_execute(self, ctx):
        from elasticsearch_tpu.index.mapper import RankFeatureFieldType
        col, miss = ctx.numeric_column(self.field)
        mask = (~miss) & ctx.all_true()
        ft = ctx.mapper.field_type(self.field)
        positive = True
        if isinstance(ft, RankFeatureFieldType):
            positive = ft.positive_score_impact
        feat = jnp.where(mask, col.astype(jnp.float32), 0.0)
        if not positive:
            # ref: negative score impact inverts the saturation argument
            feat = jnp.where(mask, 1.0 / jnp.maximum(feat, 1e-9), 0.0)
        if self.log is not None:
            scaling = float(self.log.get("scaling_factor", 1.0))
            scores = jnp.log(scaling + feat)
        elif self.sigmoid is not None:
            pivot = float(self.sigmoid["pivot"])
            exp = float(self.sigmoid["exponent"])
            scores = feat ** exp / (feat ** exp + pivot ** exp)
        elif self.linear is not None:
            scores = feat
        else:
            sat = self.saturation or {}
            if "pivot" in sat:
                pivot = float(sat["pivot"])
            else:
                # ref: pivot defaults to an approximation of the geometric
                # mean of the feature over the index
                vals = np.asarray(col)[np.asarray(~miss)]
                pivot = float(np.mean(vals)) if len(vals) else 1.0
            scores = feat / (feat + pivot)
        return jnp.where(mask, scores, 0.0), mask


class GeoDistanceQuery(QueryBuilder):
    """Docs within `distance` of `origin` (ref: index/query/
    GeoDistanceQueryBuilder). Haversine over the lat/lon doc-value columns —
    one fused elementwise kernel, no per-doc iteration."""

    name = "geo_distance"

    def __init__(self, field: str, origin, distance):
        super().__init__()
        from elasticsearch_tpu.common.geo import parse_distance, parse_geo_point
        self.field = field
        self.lat, self.lon = parse_geo_point(origin)
        self.meters = parse_distance(distance)

    def do_execute(self, ctx):
        from elasticsearch_tpu.common.geo import haversine_meters
        lat, lat_miss = ctx.numeric_column(f"{self.field}.lat")
        lon, _ = ctx.numeric_column(f"{self.field}.lon")
        dist = haversine_meters(lat, lon, self.lat, self.lon, xp=jnp)
        mask = (~lat_miss) & (dist <= self.meters) & ctx.all_true()
        return mask.astype(jnp.float32), mask


class GeoBoundingBoxQuery(QueryBuilder):
    """ref: index/query/GeoBoundingBoxQueryBuilder; handles dateline-crossing
    boxes (left > right)."""

    name = "geo_bounding_box"

    def __init__(self, field: str, top: float, left: float, bottom: float,
                 right: float):
        super().__init__()
        self.field = field
        self.top, self.left, self.bottom, self.right = top, left, bottom, right

    def do_execute(self, ctx):
        from elasticsearch_tpu.common.geo import bbox_contains
        lat, lat_miss = ctx.numeric_column(f"{self.field}.lat")
        lon, _ = ctx.numeric_column(f"{self.field}.lon")
        mask = bbox_contains(lat, lon, self.top, self.left, self.bottom,
                             self.right, xp=jnp)
        mask = mask & (~lat_miss) & ctx.all_true()
        return mask.astype(jnp.float32), mask


class GeoPolygonQuery(QueryBuilder):
    """Point-in-polygon filter (ref: index/query/GeoPolygonQueryBuilder,
    deprecated-but-present in 8.0). Even-odd rule as masked elementwise ops
    over all docs — O(docs x edges) brute force instead of a points tree."""

    name = "geo_polygon"

    def __init__(self, field: str, points):
        super().__init__()
        from elasticsearch_tpu.common.geo import parse_geo_point
        self.field = field
        pts = [parse_geo_point(p) for p in points]
        if len(pts) < 3:
            raise ParsingException(
                "too few points defined for geo_polygon query")
        self.poly_lats = [p[0] for p in pts]
        self.poly_lons = [p[1] for p in pts]

    def do_execute(self, ctx):
        from elasticsearch_tpu.common.geo import points_in_polygon
        lat, lat_miss = ctx.numeric_column(f"{self.field}.lat")
        lon, _ = ctx.numeric_column(f"{self.field}.lon")
        mask = points_in_polygon(lat, lon, self.poly_lats, self.poly_lons,
                                 xp=jnp)
        mask = mask & (~lat_miss) & ctx.all_true()
        return mask.astype(jnp.float32), mask


class GeoShapeQuery(QueryBuilder):
    """Relation of indexed shapes to a query shape (ref: x-pack spatial
    GeoShapeQueryBuilder). Runs at bbox precision over the four bbox
    doc-value columns: exact for point/envelope/bbox-shaped docs, bounding
    approximation for polygon interiors (documented deviation)."""

    name = "geo_shape"

    def __init__(self, field: str, shape: Dict[str, Any],
                 relation: str = "intersects"):
        super().__init__()
        from elasticsearch_tpu.common.geo import shape_bbox
        self.field = field
        self.relation = relation.lower()
        if self.relation not in ("intersects", "disjoint", "within", "contains"):
            raise ParsingException(
                f"invalid geo_shape relation [{relation}]")
        (self.q_minlat, self.q_minlon,
         self.q_maxlat, self.q_maxlon) = shape_bbox(shape)

    def do_execute(self, ctx):
        minlat, miss = ctx.numeric_column(f"{self.field}.min_lat")
        minlon, _ = ctx.numeric_column(f"{self.field}.min_lon")
        maxlat, _ = ctx.numeric_column(f"{self.field}.max_lat")
        maxlon, _ = ctx.numeric_column(f"{self.field}.max_lon")
        overlaps = ~((maxlat < self.q_minlat) | (minlat > self.q_maxlat)
                     | (maxlon < self.q_minlon) | (minlon > self.q_maxlon))
        if self.relation == "intersects":
            mask = overlaps
        elif self.relation == "disjoint":
            mask = ~overlaps
        elif self.relation == "within":
            mask = ((minlat >= self.q_minlat) & (maxlat <= self.q_maxlat)
                    & (minlon >= self.q_minlon) & (maxlon <= self.q_maxlon))
        else:  # contains
            mask = ((minlat <= self.q_minlat) & (maxlat >= self.q_maxlat)
                    & (minlon <= self.q_minlon) & (maxlon >= self.q_maxlon))
        mask = mask & (~miss) & ctx.all_true()
        return mask.astype(jnp.float32), mask


def _parse_geo_distance(spec):
    opts = {k: v for k, v in spec.items()
            if k in ("distance", "distance_type", "validation_method",
                     "ignore_unmapped", "boost", "_name")}
    fields = {k: v for k, v in spec.items() if k not in opts}
    if len(fields) != 1:
        raise ParsingException(
            "[geo_distance] requires exactly one point field")
    (field, origin), = fields.items()
    return _with_boost(GeoDistanceQuery(field, origin, spec["distance"]), spec)


def _parse_geo_bounding_box(spec):
    from elasticsearch_tpu.common.geo import parse_geo_point
    fields = {k: v for k, v in spec.items()
              if k not in ("validation_method", "ignore_unmapped", "boost",
                           "_name", "type")}
    if len(fields) != 1:
        raise ParsingException("[geo_bounding_box] requires one point field")
    (field, box), = fields.items()
    if "top_left" in box:
        top, left = parse_geo_point(box["top_left"])
        bottom, right = parse_geo_point(box["bottom_right"])
    elif "wkt" in box:
        raise ParsingException("[geo_bounding_box] WKT envelope unsupported")
    else:
        top, left = float(box["top"]), float(box["left"])
        bottom, right = float(box["bottom"]), float(box["right"])
    return _with_boost(GeoBoundingBoxQuery(field, top, left, bottom, right),
                       spec)


def _parse_geo_polygon(spec):
    fields = {k: v for k, v in spec.items()
              if k not in ("validation_method", "ignore_unmapped", "boost",
                           "_name")}
    if len(fields) != 1:
        raise ParsingException("[geo_polygon] requires one point field")
    (field, body), = fields.items()
    return _with_boost(GeoPolygonQuery(field, body["points"]), spec)


def _parse_geo_shape(spec):
    fields = {k: v for k, v in spec.items()
              if k not in ("ignore_unmapped", "boost", "_name")}
    if len(fields) != 1:
        raise ParsingException("[geo_shape] requires one shape field")
    (field, body), = fields.items()
    if "indexed_shape" in body:
        raise ParsingException("[geo_shape] indexed_shape is unsupported")
    return _with_boost(
        GeoShapeQuery(field, body["shape"],
                      relation=body.get("relation", "intersects")), spec)


def parse_query(body: Dict[str, Any]) -> QueryBuilder:
    if not isinstance(body, dict) or len(body) != 1:
        raise ParsingException(
            f"[query] malformed query, expected a single query type, got "
            f"{list(body) if isinstance(body, dict) else type(body).__name__}")
    (qtype, spec), = body.items()
    parser = _PARSERS.get(qtype)
    if parser is None:
        raise ParsingException(f"unknown query [{qtype}]")
    return parser(spec)


def _with_boost(q: QueryBuilder, spec) -> QueryBuilder:
    if isinstance(spec, dict) and "boost" in spec:
        q.boost = float(spec["boost"])
    return q


def _parse_match(spec):
    if not isinstance(spec, dict) or len(spec) != 1:
        raise ParsingException("[match] query malformed")
    (field, params), = spec.items()
    if isinstance(params, dict):
        q = MatchQuery(field, str(params.get("query", "")),
                       operator=params.get("operator", "or"),
                       minimum_should_match=params.get("minimum_should_match"))
        return _with_boost(q, params)
    return MatchQuery(field, str(params))


def _parse_multi_match(spec):
    return MultiMatchQuery(list(spec.get("fields", [])),
                           str(spec.get("query", "")),
                           type_=spec.get("type", "best_fields"),
                           tie_breaker=float(spec.get("tie_breaker", 0.0)))


def _parse_term(spec):
    if not isinstance(spec, dict) or len(spec) != 1:
        raise ParsingException("[term] query malformed")
    (field, params), = spec.items()
    if isinstance(params, dict):
        return _with_boost(TermQuery(field, params.get("value")), params)
    return TermQuery(field, params)


def _parse_terms(spec):
    fields = {k: v for k, v in spec.items() if k != "boost"}
    if len(fields) != 1:
        raise ParsingException("[terms] query requires exactly one field")
    (field, values), = fields.items()
    return _with_boost(TermsQuery(field, list(values)), spec)


def _parse_range(spec):
    (field, params), = spec.items()
    # `from`/`to` legacy aliases
    gte = params.get("gte", params.get("from"))
    lte = params.get("lte", params.get("to"))
    return _with_boost(
        RangeQuery(field, gte=gte, gt=params.get("gt"),
                   lte=lte, lt=params.get("lt"),
                   relation=params.get("relation", "intersects")), params)


def _parse_bool(spec):
    def parse_clauses(key):
        v = spec.get(key, [])
        if isinstance(v, dict):
            v = [v]
        return [parse_query(c) for c in v]

    q = BoolQuery(
        must=parse_clauses("must"), filter=parse_clauses("filter"),
        should=parse_clauses("should"), must_not=parse_clauses("must_not"),
        minimum_should_match=spec.get("minimum_should_match"))
    return _with_boost(q, spec)


# stored-script resolver hook ({"id": ...} script references, ref:
# script/ScriptService.getStoredScript) — bound by Node construction;
# the last node constructed in-process wins, which matches the
# single-node-per-process deployment shape
STORED_SCRIPT_RESOLVER = None


def resolve_script_source(script):
    """(source, params) from an inline or stored ({"id": ...}) script."""
    if not isinstance(script, dict):
        return str(script), {}
    if "id" in script and "source" not in script:
        if STORED_SCRIPT_RESOLVER is None:
            raise ParsingException(
                f"unable to resolve stored script [{script['id']}]")
        stored = STORED_SCRIPT_RESOLVER(script["id"])
        if stored is None:
            raise ParsingException(
                f"unable to find script [{script['id']}]")
        return stored["source"], script.get("params", {})
    if "source" not in script:
        raise ParsingException(
            "script must specify either [source] or [id]")
    return script["source"], script.get("params", {})


def _parse_script_score(spec):
    script = spec["script"]
    source, params = resolve_script_source(script)
    q = ScriptScoreQuery(parse_query(spec["query"]), source, params,
                         min_score=spec.get("min_score"))
    return _with_boost(q, spec)


def _parse_knn(spec):
    filt = spec.get("filter")
    return KnnQuery(spec["field"], spec["query_vector"],
                    num_candidates=spec.get("num_candidates"),
                    filter_query=parse_query(filt) if filt else None,
                    k=spec.get("k"))


def _parse_dis_max(spec):
    queries = [parse_query(q) for q in spec.get("queries", [])]
    if not queries:
        raise ParsingException("[dis_max] requires 'queries' field with at "
                               "least one clause")
    return DisMaxQuery(queries, tie_breaker=float(spec.get("tie_breaker", 0.0)))


def _parse_function_score(spec):
    inner = parse_query(spec.get("query", {"match_all": {}}))
    functions = spec.get("functions", [])
    if not functions and "script_score" in spec:
        functions = [{"script_score": spec["script_score"]}]
    return _with_boost(
        FunctionScoreQuery(inner, functions,
                           boost_mode=spec.get("boost_mode", "multiply"),
                           score_mode=spec.get("score_mode", "multiply")), spec)




def _single_field_spec(spec, qname: str):
    """Exactly-one-field specs like {"field": {...}, "boost": 2} — anything
    else is a 400 parsing_exception, never a raw unpack error."""
    if not isinstance(spec, dict):
        raise ParsingException(f"[{qname}] query malformed")
    entries = [(k, v) for k, v in spec.items() if k != "boost"]
    if len(entries) != 1:
        raise ParsingException(
            f"[{qname}] query requires exactly one field, got "
            f"{[k for k, _ in entries]}")
    return entries[0]


def _parse_match_phrase(spec):
    field, params = _single_field_spec(spec, "match_phrase")
    if isinstance(params, dict):
        q = MatchPhraseQuery(field, str(params.get("query", "")),
                             slop=int(params.get("slop", 0)))
        return _with_boost(q, params)
    return MatchPhraseQuery(field, str(params))


def _parse_match_phrase_prefix(spec):
    field, params = _single_field_spec(spec, "match_phrase_prefix")
    if isinstance(params, dict):
        q = MatchPhrasePrefixQuery(
            field, str(params.get("query", "")),
            max_expansions=int(params.get("max_expansions", 50)),
            slop=int(params.get("slop", 0)))
        return _with_boost(q, params)
    return MatchPhrasePrefixQuery(field, str(params))


def _parse_match_bool_prefix(spec):
    field, params = _single_field_spec(spec, "match_bool_prefix")
    if isinstance(params, dict):
        return _with_boost(MatchBoolPrefixQuery(
            field, str(params.get("query", "")),
            max_expansions=int(params.get("max_expansions", 50))), params)
    return MatchBoolPrefixQuery(field, str(params))


def _parse_prefix(spec):
    field, params = _single_field_spec(spec, "prefix")
    if isinstance(params, dict):
        return _with_boost(PrefixQuery(field, str(params.get("value", ""))),
                           params)
    return PrefixQuery(field, str(params))


def _parse_wildcard(spec):
    field, params = _single_field_spec(spec, "wildcard")
    if isinstance(params, dict):
        return _with_boost(
            WildcardQuery(field, str(params.get("value",
                                                params.get("wildcard", "")))),
            params)
    return WildcardQuery(field, str(params))


def _parse_regexp(spec):
    field, params = _single_field_spec(spec, "regexp")
    if isinstance(params, dict):
        return _with_boost(RegexpQuery(field, str(params.get("value", ""))),
                           params)
    return RegexpQuery(field, str(params))


def _parse_fuzzy(spec):
    field, params = _single_field_spec(spec, "fuzzy")
    if isinstance(params, dict):
        return _with_boost(FuzzyQuery(
            field, str(params.get("value", "")),
            fuzziness=params.get("fuzziness"),
            prefix_length=int(params.get("prefix_length", 0)),
            max_expansions=int(params.get("max_expansions", 50))), params)
    return FuzzyQuery(field, str(params))


def _parse_more_like_this(spec):
    return _with_boost(MoreLikeThisQuery(
        spec.get("fields"), spec.get("like", []), unlike=spec.get("unlike"),
        max_query_terms=int(spec.get("max_query_terms", 25)),
        min_term_freq=int(spec.get("min_term_freq", 2)),
        min_doc_freq=int(spec.get("min_doc_freq", 5)),
        max_doc_freq=spec.get("max_doc_freq"),
        minimum_should_match=spec.get("minimum_should_match", "30%"),
        include=bool(spec.get("include", False))), spec)


def _parse_pinned(spec):
    return _with_boost(PinnedQuery(
        list(spec.get("ids", [])),
        parse_query(spec.get("organic", {"match_all": {}}))), spec)


def _parse_has_child(spec):
    from elasticsearch_tpu.search.join import HasChildQuery
    q = HasChildQuery(spec["type"], parse_query(spec["query"]),
                      score_mode=spec.get("score_mode", "none"),
                      min_children=spec.get("min_children", 1),
                      max_children=spec.get("max_children"),
                      ignore_unmapped=bool(spec.get("ignore_unmapped")))
    return _with_boost(q, spec)


def _parse_has_parent(spec):
    from elasticsearch_tpu.search.join import HasParentQuery
    q = HasParentQuery(spec["parent_type"], parse_query(spec["query"]),
                       score=bool(spec.get("score")),
                       ignore_unmapped=bool(spec.get("ignore_unmapped")))
    return _with_boost(q, spec)


def _parse_parent_id(spec):
    from elasticsearch_tpu.search.join import ParentIdQuery
    q = ParentIdQuery(spec["type"], spec["id"],
                      ignore_unmapped=bool(spec.get("ignore_unmapped")))
    return _with_boost(q, spec)


def _parse_percolate(spec):
    from elasticsearch_tpu.search.percolate import parse_percolate
    return parse_percolate(spec)




class IntervalsQuery(QueryBuilder):
    """ref: index/query/IntervalQueryBuilder — minimal-interval matching
    with match/any_of/all_of rules and filters; the span family
    (span_term/span_or/span_near/span_first/span_not/span_containing/
    span_within) parses onto the same engine (search/intervals.py).
    Device coarse filter = union of all leaf terms; exact interval
    algebra verifies candidates host-side (the phrase-query split)."""

    name = "intervals"

    def __init__(self, field: str, rule: Dict[str, Any]):
        super().__init__()
        self.field = field
        self.rule = rule

    # -- rule preparation: analyze leaf text per segment ---------------
    def _prepare(self, ctx, rule, field: Optional[str] = None):
        """Return (resolved rule with _tids, leaf (field, term) pairs).
        ``field`` carries the evaluation field down the tree — nodes
        marked ``_src_field`` (field_masking_span subtrees) switch it."""
        field = field or self.field
        (kind, spec), = ((k, v) for k, v in rule.items()
                         if k != "boost")
        if isinstance(spec, dict) and spec.get("_src_field"):
            field = str(spec["_src_field"])
        pf = ctx.segment.postings.get(field)
        if kind == "match":
            terms = _analyze_terms(ctx, field,
                                   str(spec.get("query", "")))
            tids = [pf.term_id(t) if pf is not None else -1
                    for t in terms]
            out = dict(spec)
            out["_tids"] = tids
            if "filter" in spec and spec["filter"]:
                fprep = {}
                for fk, fr in spec["filter"].items():
                    fprep[fk], _ = self._prepare(ctx, fr, field)
                out["filter"] = fprep
            return {"match": out}, [(field, t) for t in terms]
        if kind == "prefix":
            prefix = str(spec.get("prefix", ""))
            exp = (_expand_prefix(pf.terms, prefix, 128)
                   if pf is not None else [])
            tids = [pf.term_id(t) for t in exp]
            out = {"_tids": tids}
            if isinstance(spec, dict) and spec.get("_src_field"):
                out["_src_field"] = spec["_src_field"]
            return {"prefix": out}, [(field, t) for t in exp]
        if kind == "wildcard":
            # full-pattern expansion against the segment's term dict
            # (capped like multi-term rewrites, MAX_TERM_EXPANSIONS)
            import fnmatch
            pat = str(spec.get("pattern", ""))
            exp = ([t for t in pf.terms if fnmatch.fnmatchcase(t, pat)]
                   [:128] if pf is not None else [])
            tids = [pf.term_id(t) for t in exp]
            out = {"_tids": tids}
            if isinstance(spec, dict) and spec.get("_src_field"):
                out["_src_field"] = spec["_src_field"]
            return {"prefix": out}, [(field, t) for t in exp]
        if kind in ("any_of", "all_of"):
            kids, leaf_terms = [], []
            for child in spec.get("intervals", []):
                prep, terms = self._prepare(ctx, child, field)
                kids.append(prep)
                leaf_terms.extend(terms)
            out = dict(spec)
            out["intervals"] = kids
            if "filter" in spec and spec["filter"]:
                fprep = {}
                for fk, fr in spec["filter"].items():
                    fprep[fk], _ = self._prepare(ctx, fr, field)
                out["filter"] = fprep
            return {kind: out}, leaf_terms
        from elasticsearch_tpu.common.errors import ParsingException
        raise ParsingException(f"unknown intervals rule [{kind}]")

    def do_execute(self, ctx):
        from elasticsearch_tpu.search import intervals as iv
        z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
        empty = (z, z.astype(bool))
        seg = ctx.segment
        pf = seg.postings.get(self.field)
        ts = seg.streams.get(self.field)
        if pf is None or ts is None:
            return empty
        rule, leaf_terms = self._prepare(ctx, self.rule)
        leaf_terms = [(f, t) for f, t in leaf_terms if t]
        if not leaf_terms:
            return empty
        # device coarse filter: docs containing ANY leaf term, each
        # resolved against its OWN field (field_masking_span subtrees
        # read a different field's postings)
        present = []
        for f, t in set(leaf_terms):
            pff = seg.postings.get(f)
            if pff is not None and pff.term_id(t) >= 0:
                present.append((f, t))
        if not present:
            return empty
        union = np.zeros(seg.n_docs, bool)
        for f, t in present:
            docids, tfs = seg.postings[f].postings(t)
            union[docids[tfs > 0]] = True
        cand = np.nonzero(union)[0]
        if len(cand) == 0:
            return empty
        fields = {f for f, _ in leaf_terms} | {self.field}

        def _masked_fields(node, acc):
            if isinstance(node, dict):
                if node.get("_src_field"):
                    acc.add(str(node["_src_field"]))
                for v in node.values():
                    _masked_fields(v, acc)
            elif isinstance(node, list):
                for v in node:
                    _masked_fields(v, acc)
            return acc

        # filter-position masked subtrees (span_not exclude etc.) carry
        # no scoring leaf terms but still need their field's rows
        fields |= _masked_fields(rule, set())
        field_streams = {f: seg.streams.get(f) for f in fields}
        freqs = np.zeros(len(cand), np.int64)
        for i, docid in enumerate(cand):
            rows = {f: (s.tokens[docid, : s.lengths[docid]]
                        if s is not None else ())
                    for f, s in field_streams.items()}
            ivs = iv.evaluate_rule(rule, rows[self.field], pf.term_id,
                                   None, rows=rows)
            freqs[i] = len(ivs)
        # idf uses each term's OWN field stats — a masked source
        # field's doc_freq against the main field's doc_count could go
        # negative (df > N inverts the ranking)
        w = sum(bm25_ops.idf(ctx.stats.doc_freq(f, t),
                             ctx.stats.field_stats(f)[0])
                for f, t in set(leaf_terms))
        return _phrase_scores_from_freqs(ctx, self.field, cand, freqs, w)


class TermsSetQuery(QueryBuilder):
    """ref: index/query/TermsSetQueryBuilder — docs matching at least
    `minimum_should_match_field`'s value (or a constant) of the terms."""

    name = "terms_set"

    def __init__(self, field: str, terms: List[str],
                 msm_field: Optional[str] = None,
                 msm_script: Optional[Dict[str, Any]] = None):
        super().__init__()
        self.field = field
        self.terms = terms
        self.msm_field = msm_field
        self.msm_script = msm_script

    def do_execute(self, ctx):
        scores = jnp.zeros(ctx.n_docs_padded, jnp.float32)
        count = np.zeros(ctx.n_docs_padded, np.int32)
        total_score = np.zeros(ctx.n_docs_padded, np.float32)
        for term in self.terms:
            s, m = TermQuery(self.field, term).do_execute(ctx)
            m_np = np.asarray(m)
            count[m_np] += 1
            total_score += np.asarray(s)
        if self.msm_field is not None:
            nv = ctx.segment.numerics.get(self.msm_field)
            required = np.ones(ctx.n_docs_padded, np.float64)
            if nv is not None:
                required[: ctx.segment.n_docs] = np.where(
                    nv.missing, 1, nv.values)
        elif self.msm_script is not None:
            src = (self.msm_script.get("source", "")
                   if isinstance(self.msm_script, dict)
                   else str(self.msm_script))
            # closed grammar, NEVER the host interpreter: a constant, the
            # canonical "params.num_terms", or Math.min(params.num_terms, N)
            n_terms = len(self.terms)
            src_s = src.strip()
            if src_s.isdigit():
                required_scalar = int(src_s)
            elif src_s == "params.num_terms":
                required_scalar = n_terms
            else:
                m = re.fullmatch(
                    r"Math\.min\(\s*params\.num_terms\s*,\s*(\d+)\s*\)",
                    src_s)
                required_scalar = (min(n_terms, int(m.group(1)))
                                   if m else n_terms)
            required = np.full(ctx.n_docs_padded, required_scalar,
                               np.float64)
        else:
            required = np.ones(ctx.n_docs_padded, np.float64)
        mask = count >= np.maximum(required, 1)
        scores_np = np.where(mask, total_score, 0.0).astype(np.float32)
        return jnp.asarray(scores_np), jnp.asarray(mask)


class ScriptQuery(QueryBuilder):
    """ref: index/query/ScriptQueryBuilder — filter context: the script
    decides per doc (sandboxed expression over doc values)."""

    name = "script"

    def __init__(self, script: Any):
        super().__init__()
        params = {}
        if isinstance(script, dict):
            params = script.get("params", {}) or {}
            script = script.get("source", "")
        self.source = str(script)
        self.params = params

    def do_execute(self, ctx):
        fn = compile_script(self.source)

        def doc_columns(field):
            col, miss = ctx.numeric_column(field)
            return _DocColumn(col, miss)

        sctx = ScriptContext(doc_columns, self.params)
        result = jnp.broadcast_to(
            jnp.asarray(fn(sctx)), (ctx.n_docs_padded,))
        mask = (result != 0) & ctx.all_true()
        scores = jnp.where(mask, 1.0, 0.0)
        return scores, mask




def _parse_intervals(spec):
    """{field: {rule..., boost?}} — the rule tree passes through; span
    queries build the same trees via _span_rule. Boost lives beside the
    rule (ES's intervals shape) or beside the field."""
    (field, rule), = ((k, v) for k, v in spec.items() if k != "boost")
    q = IntervalsQuery(field, rule)
    _with_boost(q, rule)
    return _with_boost(q, spec)


def _span_rule(node):
    (kind, body), = ((k, v) for k, v in node.items() if k != "boost")
    if kind == "span_term":
        (field, v), = body.items()
        term = v.get("value") if isinstance(v, dict) else v
        return field, {"match": {"query": str(term)}}
    if kind == "span_multi":
        # ref: SpanMultiTermQueryBuilder — a prefix/wildcard expanded to
        # an any_of over the terms matching the FULL pattern (the
        # intervals engine expands per segment against the term dict)
        inner = body.get("match", {})
        if len(inner) != 1:
            raise ParsingException(
                "[span_multi] requires exactly one [match] query")
        (iq, ispec), = inner.items()
        if iq not in ("prefix", "wildcard"):
            raise ParsingException(
                f"[span_multi] unsupported inner query [{iq}]")
        if len(ispec) != 1:
            raise ParsingException(
                f"[span_multi] [{iq}] requires exactly one field")
        (field, v), = ispec.items()
        pat = v.get("value") if isinstance(v, dict) else v
        if iq == "prefix":
            return field, {"prefix": {"prefix": str(pat)}}
        return field, {"wildcard": {"pattern": str(pat)}}
    if kind == "span_or":
        parts = [_span_rule(c) for c in body.get("clauses", [])]
        fields = {f for f, _ in parts}
        if len(fields) != 1:
            raise ParsingException(
                "[span_or] clauses must target one field")
        return fields.pop(), {"any_of": {
            "intervals": [r for _, r in parts]}}
    if kind == "span_near":
        parts = [_span_rule(c) for c in body.get("clauses", [])]
        fields = {f for f, _ in parts}
        if len(fields) != 1:
            raise ParsingException(
                "[span_near] clauses must target one field")
        return fields.pop(), {"all_of": {
            "intervals": [r for _, r in parts],
            "ordered": bool(body.get("in_order", True)),
            "max_gaps": int(body.get("slop", 0)),
        }}
    if kind == "span_first":
        field, inner = _span_rule(body.get("match", {}))
        # end position < end → contained_by a synthetic window is not
        # expressible; IntervalsQuery post-filters via _span_first marker
        return field, {"all_of": {"intervals": [inner],
                                  "_first_end": int(body.get("end", 3))}}
    if kind == "span_not":
        field, inc = _span_rule(body.get("include", {}))
        f2, exc = _span_rule(body.get("exclude", {}))
        if f2 != field:
            raise ParsingException("[span_not] fields must match")
        return field, {"all_of": {"intervals": [inc],
                                  "filter": {"not_overlapping": exc}}}
    if kind == "span_containing":
        field, big = _span_rule(body.get("big", {}))
        f2, small = _span_rule(body.get("little", {}))
        if f2 != field:
            raise ParsingException(
                "[span_containing] fields must match")
        return field, {"all_of": {"intervals": [big],
                                  "filter": {"containing": small}}}
    if kind == "span_within":
        field, small = _span_rule(body.get("little", {}))
        f2, big = _span_rule(body.get("big", {}))
        if f2 != field:
            raise ParsingException("[span_within] fields must match")
        return field, {"all_of": {"intervals": [small],
                                  "filter": {"contained_by": big}}}
    if kind in ("field_masking_span", "span_field_masking"):
        # ref: index/query/FieldMaskingSpanQueryBuilder — the inner
        # span evaluates against ITS OWN field's postings/positions but
        # reports the masked field, so an enclosing span_near can
        # combine spans across fields that share position structure
        # (e.g. a stemmed subfield of the same text)
        inner = body.get("query")
        masked = body.get("field")
        if not inner or not masked:
            raise ParsingException(
                "[field_masking_span] requires [query] and [field]")
        src_field, rule = _span_rule(inner)
        (rk, rv), = ((k, v) for k, v in rule.items() if k != "boost")
        rv = dict(rv)
        rv["_src_field"] = src_field
        return str(masked), {rk: rv}
    raise ParsingException(f"unknown span query [{kind}]")


def _parse_span(kind):
    def parse(spec):
        field, rule = _span_rule({kind: spec})
        return _with_boost(IntervalsQuery(field, rule), spec)
    return parse


def _parse_terms_set(spec):
    (field, body), = spec.items()
    return _with_boost(TermsSetQuery(
        field, [str(t) for t in body.get("terms", [])],
        msm_field=body.get("minimum_should_match_field"),
        msm_script=body.get("minimum_should_match_script")), body)


def _parse_script_query(spec):
    return _with_boost(ScriptQuery(spec.get("script", "")), spec)


def _parse_wrapper(spec):
    """ref: WrapperQueryBuilder — base64(JSON) embedded query."""
    import base64
    import json as _json
    raw = spec.get("query", "")
    try:
        decoded = _json.loads(base64.b64decode(raw))
    except Exception:
        raise ParsingException("[wrapper] query must be base64-encoded JSON")
    return parse_query(decoded)




def _walk_source_path(node: Any, parts: List[str]) -> List[Any]:
    """List-aware dotted-path walk: lists flat-map at every step (one
    shared walker for nested-object extraction and per-object values)."""
    cur = [node]
    for part in parts:
        nxt: List[Any] = []
        for n in cur:
            if isinstance(n, list):
                n_items = n
            else:
                n_items = [n]
            for item in n_items:
                if isinstance(item, dict) and part in item:
                    nxt.append(item[part])
        cur = nxt
    out: List[Any] = []
    for n in cur:
        out.extend(n if isinstance(n, list) else [n])
    return out


def _nested_objects(src: Dict[str, Any], path: str) -> List[Dict[str, Any]]:
    return [o for o in _walk_source_path(src, path.split("."))
            if isinstance(o, dict)]


def _obj_values(obj: Dict[str, Any], field: str, path: str) -> List[Any]:
    rel = field[len(path) + 1:] if field.startswith(path + ".") else field
    return [v for v in _walk_source_path(obj, rel.split("."))
            if v is not None]


def _as_clause_list(spec_val) -> List[Dict[str, Any]]:
    """bool clauses accept a single object or a list (ES shorthand)."""
    if spec_val is None:
        return []
    return spec_val if isinstance(spec_val, list) else [spec_val]


def _coerce_pair(ctx, field: str, have, want):
    """Coerce both sides through the field type so the verifier compares
    what the index compared (long "7" vs 5, date strings vs millis)."""
    ft = ctx.mapper.mapper.fields.get(field) if ctx is not None else None
    if ft is not None:
        try:
            return ft.parse(have), ft.parse(want)
        except Exception:
            pass
    return have, want


def _source_matches(q: Dict[str, Any], obj: Dict[str, Any],
                    path: str, ctx=None) -> bool:
    """Per-object verification of an inner nested query against ONE
    nested object from _source. Covers the common inner-query family
    (bool/term/terms/range/match/match_all/exists); anything else
    returns True — falling back to the flattened (device) semantics
    rather than wrongly dropping matches. Values coerce through the
    field type, and match verification analyzes with the field's
    analyzer (matching what the device index compared)."""
    (kind, spec), = ((k, v) for k, v in q.items() if k != "boost")
    if kind == "bool":
        for clause in ("must", "filter"):
            for c in _as_clause_list(spec.get(clause)):
                if not _source_matches(c, obj, path, ctx):
                    return False
        for c in _as_clause_list(spec.get("must_not")):
            if _source_matches(c, obj, path, ctx):
                return False
        should = _as_clause_list(spec.get("should"))
        if should and not (spec.get("must") or spec.get("filter")):
            return any(_source_matches(c, obj, path, ctx)
                       for c in should)
        return True
    if kind == "match_all":
        return True
    if kind == "term":
        (field, body), = spec.items()
        want = body.get("value") if isinstance(body, dict) else body
        for h in _obj_values(obj, field, path):
            ch, cw = _coerce_pair(ctx, field, h, want)
            if ch == cw or str(h) == str(want):
                return True
        return False
    if kind == "terms":
        (field, wants), = ((k, v) for k, v in spec.items()
                           if k != "boost")
        for h in _obj_values(obj, field, path):
            for w in wants:
                ch, cw = _coerce_pair(ctx, field, h, w)
                if ch == cw or str(h) == str(w):
                    return True
        return False
    if kind == "range":
        (field, body), = spec.items()
        haves = _obj_values(obj, field, path)
        if not haves:
            return False
        for have in haves:
            ok = True
            for op, cmp in (("gt", lambda a, b: a > b),
                            ("gte", lambda a, b: a >= b),
                            ("lt", lambda a, b: a < b),
                            ("lte", lambda a, b: a <= b)):
                if op not in body:
                    continue
                ch, cw = _coerce_pair(ctx, field, have, body[op])
                try:
                    if not cmp(ch, cw):
                        ok = False
                        break
                except TypeError:
                    ok = False
                    break
            if ok:
                return True
        return False
    if kind == "match":
        (field, body), = spec.items()
        text = body.get("query") if isinstance(body, dict) else body
        haves = _obj_values(obj, field, path)
        if not haves:
            return False
        if ctx is not None:
            want_tokens = set(_analyze_terms(ctx, field, str(text)))
            for h in haves:
                if want_tokens & set(_analyze_terms(ctx, field, str(h))):
                    return True
            return False
        want_tokens = set(str(text).lower().split())
        return any(want_tokens & set(str(h).lower().split())
                   for h in haves)
    if kind == "exists":
        return bool(_obj_values(obj, spec.get("field", ""), path))
    return True                 # unsupported inner query: flattened fallback


class NestedQuery(QueryBuilder):
    """ref: index/query/NestedQueryBuilder. The reference stores nested
    objects as separate Lucene docs and joins with a bitset; here nested
    fields index FLATTENED (the device coarse filter) and per-object
    correlation is restored by verifying candidates against the _source
    objects at the nested path (the filter-then-verify split used for
    phrases). Unsupported inner queries keep flattened semantics."""

    name = "nested"

    def __init__(self, path: str, query_dict: Dict[str, Any],
                 score_mode: str = "avg", ignore_unmapped: bool = False,
                 inner_hits: Optional[Dict[str, Any]] = None):
        super().__init__()
        self.path = path
        self.raw = query_dict
        self.inner = parse_query(query_dict)
        self.score_mode = score_mode
        self.ignore_unmapped = ignore_unmapped
        self.inner_hits = inner_hits
        # _id -> [(offset, object)] matched objects, for inner_hits
        # decoration (request-scoped: queries parse per request)
        self._matched_objects: Dict[str, List] = {}

    def do_execute(self, ctx):
        import json as _json
        if (self.path not in getattr(ctx.mapper.mapper, "nested_paths",
                                     set())):
            if self.ignore_unmapped:
                z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
                return z, z.astype(bool)
            raise QueryShardException(
                f"[nested] failed to find nested object under path "
                f"[{self.path}]")
        scores, mask = self.inner.execute(ctx)
        seg = ctx.segment
        mask_np = np.asarray(mask)[: seg.n_docs].copy()
        cand = np.nonzero(mask_np)[0]
        for d in cand:
            src = _json.loads(seg.stored.source(int(d)))
            objs = _nested_objects(src, self.path)
            matched = [(i, o) for i, o in enumerate(objs)
                       if _source_matches(self.raw, o, self.path, ctx)]
            if not matched:
                mask_np[d] = False
            elif self.inner_hits is not None:
                self._matched_objects[seg.stored.ids[int(d)]] = matched
        keep = np.zeros(ctx.n_docs_padded, bool)
        keep[: seg.n_docs] = mask_np
        keep_j = jnp.asarray(keep)
        if self.score_mode == "none":
            # filter-only: matching docs contribute 0 to the score (the
            # reference's score_mode none)
            return jnp.zeros(ctx.n_docs_padded, jnp.float32), keep_j
        return jnp.where(keep_j, scores, 0.0), keep_j

    def rewrite(self, searcher):
        return self

    def add_hit_fields(self, hit: Dict[str, Any]) -> None:
        """inner_hits decoration: the matched nested objects (ref:
        InnerHitBuilder — here offsets index the _source array)."""
        if self.inner_hits is None:
            return
        matched = self._matched_objects.get(hit.get("_id"))
        if matched is None:
            return
        name = self.inner_hits.get("name", self.path)
        size = int(self.inner_hits.get("size", 3))
        inner = [{
            "_index": hit.get("_index"),
            "_id": hit.get("_id"),
            "_nested": {"field": self.path, "offset": off},
            "_score": None,
            "_source": obj,
        } for off, obj in matched[:size]]
        hit.setdefault("inner_hits", {})[name] = {"hits": {
            "total": {"value": len(matched), "relation": "eq"},
            "max_score": None,
            "hits": inner,
        }}


class SliceQuery(QueryBuilder):
    """Sliced scroll partition (ref: search/slice/SliceBuilder — splits
    one scroll into `max` disjoint id-hash partitions so deep scans run
    in parallel; SURVEY.md §5.7 calls this the long-context partitioning
    model). Docs belong to slice `hash(_id) % max == id`; the per-segment
    hash column is computed once and cached on the segment."""

    name = "_slice"

    def __init__(self, slice_id: int, slice_max: int, inner: QueryBuilder):
        super().__init__()
        if not (0 <= slice_id < slice_max):
            raise ParsingException(
                f"slice id [{slice_id}] must be in [0, {slice_max})")
        self.slice_id = slice_id
        self.slice_max = slice_max
        self.inner = inner

    def do_execute(self, ctx):
        from elasticsearch_tpu.index.service import murmur3_hash
        scores, mask = self.inner.execute(ctx)
        seg = ctx.segment
        cache = getattr(seg, "_slice_hash_cache", None)
        if cache is None or cache[0] != self.slice_max:
            h = np.fromiter(
                (abs(murmur3_hash(seg.stored.ids[d])) % self.slice_max
                 for d in range(seg.n_docs)),
                np.int32, seg.n_docs)
            seg._slice_hash_cache = (self.slice_max, h)
        h = seg._slice_hash_cache[1]
        m = np.zeros(ctx.n_docs_padded, bool)
        m[: seg.n_docs] = h == self.slice_id
        mask = mask & jnp.asarray(m)
        return jnp.where(mask, scores, 0.0), mask

    def rewrite(self, searcher):
        inner = self.inner.rewrite(searcher)
        if inner is self.inner:
            return self
        q = SliceQuery(self.slice_id, self.slice_max, inner)
        q.boost = self.boost
        return q


class TextExpansionQuery(QueryBuilder):
    """Learned-sparse retrieval over a rank_features field (net-new
    surface in the TPU brief — the reference has no text_expansion at
    this version). Docs score Σ_t w_query(t) · w_doc(t): each expansion
    token is a rank_features column, so scoring is a weighted sum of
    device columns — the vmapped custom-scoring path. Query weights come
    precomputed (`tokens`) — from the ML trained-model store or an
    external expansion model; there is no in-process text-to-expansion
    inference."""

    name = "text_expansion"

    def __init__(self, field: str, tokens: Dict[str, float]):
        super().__init__()
        self.field = field
        self.tokens = {str(t): float(w) for t, w in tokens.items()}

    def do_execute(self, ctx):
        # one batched reduction: stack the PRESENT token columns (host
        # dict lookups) and weighted-sum in a single device op — sparse
        # expansions carry 100+ tokens, so a per-token eager loop would
        # dispatch hundreds of tiny ops per segment
        cols, misses, weights = [], [], []
        for tok, w in self.tokens.items():
            if ctx.device.numerics.get(f"{self.field}.{tok}") is None:
                continue
            col, miss = ctx.numeric_column(f"{self.field}.{tok}")
            cols.append(col)
            misses.append(miss)
            weights.append(w)
        if not cols:
            z = jnp.zeros(ctx.n_docs_padded, jnp.float32)
            return z, z.astype(bool)
        plane = jnp.stack(cols)                       # [T, ND]
        present = ~jnp.stack(misses)                  # [T, ND]
        wv = jnp.asarray(np.asarray(weights, np.float32))
        scores = jnp.einsum("t,tn->n", wv,
                            jnp.where(present, plane, 0.0))
        mask = present.any(axis=0) & ctx.all_true()
        return jnp.where(mask, scores, 0.0), mask


def _parse_text_expansion(spec):
    fields = [(k, v) for k, v in spec.items() if k != "boost"]
    if len(fields) != 1:
        raise ParsingException(
            "[text_expansion] requires exactly one field")
    field, body = fields[0]
    if not isinstance(body, dict):
        raise ParsingException(
            f"[text_expansion] [{field}] must be an object")
    tokens = body.get("tokens") or body.get("weighted_tokens")
    if isinstance(tokens, list):             # weighted_tokens list form
        try:
            tokens = {t["token"]: t["weight"] for t in tokens}
        except (TypeError, KeyError):
            raise ParsingException(
                "[text_expansion] weighted_tokens entries need "
                "[token] and [weight]")
    if not tokens or not isinstance(tokens, dict):
        raise ParsingException(
            "[text_expansion] requires precomputed [tokens] — no "
            "in-process expansion model is available")
    try:
        q = TextExpansionQuery(field, tokens)
    except (TypeError, ValueError):
        raise ParsingException(
            "[text_expansion] token weights must be numbers")
    _with_boost(q, body)
    return _with_boost(q, spec)



def _parse_nested(spec):
    return _with_boost(NestedQuery(
        spec["path"], spec.get("query", {"match_all": {}}),
        score_mode=spec.get("score_mode", "avg"),
        ignore_unmapped=bool(spec.get("ignore_unmapped", False)),
        inner_hits=spec.get("inner_hits")), spec)


_PARSERS = {
    "nested": _parse_nested,
    "text_expansion": _parse_text_expansion,
    "weighted_tokens": _parse_text_expansion,
    "intervals": _parse_intervals,
    "span_term": _parse_span("span_term"),
    "span_or": _parse_span("span_or"),
    "span_near": _parse_span("span_near"),
    "span_multi": _parse_span("span_multi"),
    "span_first": _parse_span("span_first"),
    "span_not": _parse_span("span_not"),
    "span_containing": _parse_span("span_containing"),
    "span_within": _parse_span("span_within"),
    "field_masking_span": _parse_span("field_masking_span"),
    "span_field_masking": _parse_span("span_field_masking"),
    "terms_set": _parse_terms_set,
    "script": _parse_script_query,
    "wrapper": _parse_wrapper,
    "has_child": _parse_has_child,
    "has_parent": _parse_has_parent,
    "parent_id": _parse_parent_id,
    "percolate": _parse_percolate,
    "match_all": lambda spec: _with_boost(MatchAllQuery(), spec),
    "match_none": lambda spec: MatchNoneQuery(),
    "match": _parse_match,
    "multi_match": _parse_multi_match,
    "term": _parse_term,
    "terms": _parse_terms,
    "range": _parse_range,
    "exists": lambda spec: ExistsQuery(spec["field"]),
    "ids": lambda spec: IdsQuery(list(spec.get("values", []))),
    "bool": _parse_bool,
    "constant_score": lambda spec: _with_boost(
        ConstantScoreQuery(parse_query(spec["filter"])), spec),
    "dis_max": lambda spec: _parse_dis_max(spec),
    "boosting": lambda spec: BoostingQuery(
        parse_query(spec["positive"]), parse_query(spec["negative"]),
        float(spec.get("negative_boost", 0.5))),
    "script_score": _parse_script_score,
    "knn": _parse_knn,
    "function_score": _parse_function_score,
    "rank_feature": lambda spec: _with_boost(RankFeatureQuery(
        spec["field"], saturation=spec.get("saturation"),
        log=spec.get("log"), sigmoid=spec.get("sigmoid"),
        linear=spec.get("linear")), spec),
    "geo_distance": _parse_geo_distance,
    "geo_bounding_box": _parse_geo_bounding_box,
    "geo_polygon": _parse_geo_polygon,
    "geo_shape": _parse_geo_shape,
    "match_phrase": _parse_match_phrase,
    "match_phrase_prefix": _parse_match_phrase_prefix,
    "match_bool_prefix": _parse_match_bool_prefix,
    "prefix": _parse_prefix,
    "wildcard": _parse_wildcard,
    "regexp": _parse_regexp,
    "fuzzy": _parse_fuzzy,
    "more_like_this": _parse_more_like_this,
    "pinned": _parse_pinned,
    "distance_feature": lambda spec: _with_boost(
        DistanceFeatureQuery(spec["field"], spec["origin"], spec["pivot"]),
        spec),
    "query_string": lambda spec: _with_boost(QueryStringQuery(
        str(spec["query"]) if isinstance(spec, dict) else str(spec),
        default_field=spec.get("default_field") if isinstance(spec, dict) else None,
        fields=spec.get("fields") if isinstance(spec, dict) else None,
        default_operator=spec.get("default_operator", "or")
        if isinstance(spec, dict) else "or"), spec),
    "simple_query_string": lambda spec: _with_boost(SimpleQueryStringQuery(
        str(spec["query"]), fields=spec.get("fields"),
        default_operator=spec.get("default_operator", "or")), spec),
}
