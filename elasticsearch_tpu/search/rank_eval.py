"""Rank evaluation: relevance metrics over rated search requests.

Port of the reference's _rank_eval module (ref: modules/rank-eval/.../
RankEvalSpec.java, PrecisionAtK.java, RecallAtK.java,
MeanReciprocalRank.java, DiscountedCumulativeGain.java,
ExpectedReciprocalRank.java) — the in-framework harness used to verify
"matched recall" for the TPU scoring path vs a reference ranking
(SURVEY.md §6).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional

from elasticsearch_tpu.common.errors import IllegalArgumentException


def _rated_map(ratings: List[Dict[str, Any]]) -> Dict[str, int]:
    return {str(r["_id"]): int(r["rating"]) for r in ratings}


class Metric:
    name = "?"

    def evaluate(self, hits: List[str], ratings: Dict[str, int]) -> float:
        raise NotImplementedError

    def detail(self, hits, ratings) -> Dict[str, Any]:
        return {}


class PrecisionAtK(Metric):
    """ref: PrecisionAtK.java — relevant-in-top-k / retrieved-in-top-k."""

    name = "precision"

    def __init__(self, k: int = 10, relevant_rating_threshold: int = 1,
                 ignore_unlabeled: bool = False):
        self.k = k
        self.threshold = relevant_rating_threshold
        self.ignore_unlabeled = ignore_unlabeled

    def evaluate(self, hits, ratings):
        top = hits[: self.k]
        relevant = 0
        retrieved = 0
        for doc_id in top:
            rating = ratings.get(doc_id)
            if rating is None and self.ignore_unlabeled:
                continue
            retrieved += 1
            if rating is not None and rating >= self.threshold:
                relevant += 1
        return relevant / retrieved if retrieved else 0.0


class RecallAtK(Metric):
    """ref: RecallAtK.java — relevant-in-top-k / all-relevant."""

    name = "recall"

    def __init__(self, k: int = 10, relevant_rating_threshold: int = 1):
        self.k = k
        self.threshold = relevant_rating_threshold

    def evaluate(self, hits, ratings):
        relevant_total = sum(1 for r in ratings.values() if r >= self.threshold)
        if relevant_total == 0:
            return 0.0
        found = sum(1 for doc_id in hits[: self.k]
                    if ratings.get(doc_id, 0) >= self.threshold)
        return found / relevant_total


class MeanReciprocalRank(Metric):
    name = "mean_reciprocal_rank"

    def __init__(self, k: int = 10, relevant_rating_threshold: int = 1):
        self.k = k
        self.threshold = relevant_rating_threshold

    def evaluate(self, hits, ratings):
        for rank, doc_id in enumerate(hits[: self.k], start=1):
            if ratings.get(doc_id, 0) >= self.threshold:
                return 1.0 / rank
        return 0.0


class DiscountedCumulativeGain(Metric):
    """ref: DiscountedCumulativeGain.java — gain 2^rating - 1, log2 discount;
    optionally normalized (NDCG)."""

    name = "dcg"

    def __init__(self, k: int = 10, normalize: bool = False):
        self.k = k
        self.normalize = normalize

    @staticmethod
    def _dcg(rs: List[int]) -> float:
        return sum((2 ** r - 1) / math.log2(rank + 2)
                   for rank, r in enumerate(rs))

    def evaluate(self, hits, ratings):
        rs = [ratings.get(doc_id, 0) for doc_id in hits[: self.k]]
        dcg = self._dcg(rs)
        if not self.normalize:
            return dcg
        ideal = sorted(ratings.values(), reverse=True)[: self.k]
        idcg = self._dcg(ideal)
        return dcg / idcg if idcg > 0 else 0.0


class ExpectedReciprocalRank(Metric):
    """ref: ExpectedReciprocalRank.java — cascade model with stop
    probability (2^r - 1) / 2^max_rating."""

    name = "expected_reciprocal_rank"

    def __init__(self, maximum_relevance: int, k: int = 10):
        self.max_rel = maximum_relevance
        self.k = k

    def evaluate(self, hits, ratings):
        err = 0.0
        p_continue = 1.0
        denom = 2 ** self.max_rel
        for rank, doc_id in enumerate(hits[: self.k], start=1):
            r = ratings.get(doc_id, 0)
            stop = (2 ** r - 1) / denom
            err += p_continue * stop / rank
            p_continue *= 1 - stop
        return err


def parse_metric(spec: Dict[str, Any]) -> Metric:
    if len(spec) != 1:
        raise IllegalArgumentException("[rank_eval] exactly one metric required")
    (name, params), = spec.items()
    params = params or {}
    if name == "precision":
        return PrecisionAtK(params.get("k", 10),
                            params.get("relevant_rating_threshold", 1),
                            params.get("ignore_unlabeled", False))
    if name == "recall":
        return RecallAtK(params.get("k", 10),
                         params.get("relevant_rating_threshold", 1))
    if name == "mean_reciprocal_rank":
        return MeanReciprocalRank(params.get("k", 10),
                                  params.get("relevant_rating_threshold", 1))
    if name == "dcg":
        return DiscountedCumulativeGain(params.get("k", 10),
                                        params.get("normalize", False))
    if name == "expected_reciprocal_rank":
        return ExpectedReciprocalRank(params["maximum_relevance"],
                                      params.get("k", 10))
    raise IllegalArgumentException(f"unknown rank-eval metric [{name}]")


def rank_eval(search_fn: Callable[[Dict[str, Any]], List[str]],
              requests: List[Dict[str, Any]],
              metric_spec: Dict[str, Any]) -> Dict[str, Any]:
    """Evaluate rated requests. search_fn(body) -> ordered doc-id list.
    Returns the reference's response shape: overall metric_score +
    per-request details with unrated docs."""
    metric = parse_metric(metric_spec)
    details = {}
    scores = []
    for req in requests:
        rid = req.get("id", f"request_{len(details)}")
        ratings = _rated_map(req.get("ratings", []))
        hits = search_fn(req["request"])
        score = metric.evaluate(hits, ratings)
        scores.append(score)
        details[rid] = {
            "metric_score": score,
            "unrated_docs": [{"_id": h} for h in hits if h not in ratings],
            "hits": [{"hit": {"_id": h}, "rating": ratings.get(h)} for h in hits],
        }
    return {
        "metric_score": sum(scores) / len(scores) if scores else 0.0,
        "details": details,
    }
