"""Mergeable quantile sketches for the percentile agg family.

The reference reduces percentile-family aggs by merging per-shard
TDigest states on the coordinator (ref: InternalTDigestPercentiles /
org.elasticsearch.search.aggregations.metrics.TDigestState — the
AVL/merging digest of Dunning & Ertl). This engine previously carried
the RAW SAMPLE across the agg tree (the ``_values`` ndarray on a
percentiles result) — unbounded memory per shard, and nothing that
could legally cross the wire to a coordinator. This module is the
bounded-memory replacement:

- ``TDigest`` holds at most ``compression`` weighted centroids sorted
  by mean (f64), plus exact min/max/count. Memory is
  ``O(compression)`` regardless of input size.
- **Exact mode**: while every centroid is a singleton (weight 1) and
  the count fits the budget, ``quantile`` is numpy's default linear
  interpolation and ``cdf``/``mad`` are exact — so small corpora (and
  every pre-existing test) produce bit-for-bit the results the raw
  sample produced. Merging exact digests whose combined size fits the
  budget stays exact, which makes shard-split invariance EXACT below
  the budget and bounded-error above it.
- **Compressed mode** (count > budget): centroids merge under the k1
  scale function ``k(q) = c/(2π)·asin(2q−1)`` — more resolution at the
  tails, the classic TDigest trade. Quantile error is bounded by the
  widest centroid's q-span: O(1/compression) in the middle, tighter at
  the tails (documented in COMPONENTS.md "Distributed aggregations").

The compression pass is fully vectorized (sort + cumsum + bincount —
no per-point Python), so building a digest over millions of values is
one numpy pass, and merging two digests touches only
O(compression) centroids.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

# default centroid budget (the reference's TDigest compression default
# is 100; this engine defaults higher — centroids are 16 bytes, so 256
# costs 4 KiB per sketch and halves mid-quantile error)
DEFAULT_COMPRESSION = 256


class TDigest:
    """A merging t-digest: ≤ ``compression`` centroids, exact min/max."""

    __slots__ = ("means", "weights", "min", "max", "compression")

    def __init__(self, means: np.ndarray, weights: np.ndarray,
                 mn: Optional[float], mx: Optional[float],
                 compression: int = DEFAULT_COMPRESSION):
        self.means = np.asarray(means, np.float64)
        self.weights = np.asarray(weights, np.float64)
        self.min = mn
        self.max = mx
        self.compression = int(compression)

    # ------------------------------------------------------------ build

    @classmethod
    def empty(cls, compression: int = DEFAULT_COMPRESSION) -> "TDigest":
        return cls(np.zeros(0), np.zeros(0), None, None, compression)

    @classmethod
    def from_values(cls, values,
                    compression: int = DEFAULT_COMPRESSION) -> "TDigest":
        vals = np.asarray(values, np.float64).ravel()
        if vals.size == 0:
            return cls.empty(compression)
        vals = np.sort(vals)
        mn, mx = float(vals[0]), float(vals[-1])
        if vals.size <= compression:
            # exact mode: one singleton centroid per sample
            return cls(vals.copy(), np.ones(vals.size), mn, mx,
                       compression)
        means, weights = _compress(vals, np.ones(vals.size), compression)
        return cls(means, weights, mn, mx, compression)

    # ------------------------------------------------------------ state

    @property
    def count(self) -> float:
        return float(self.weights.sum())

    def is_empty(self) -> bool:
        return self.means.size == 0

    def is_exact(self) -> bool:
        """True while the digest is a losslessly-held sample."""
        return bool(self.means.size <= self.compression
                    and (self.weights == 1.0).all())

    def nbytes(self) -> int:
        """Accounting size (breaker charges): centroid arrays + header."""
        return int(self.means.nbytes + self.weights.nbytes + 64)

    # ------------------------------------------------------------ merge

    def merge(self, other: "TDigest") -> "TDigest":
        return TDigest.merge_all([self, other], self.compression)

    @staticmethod
    def merge_all(digests: Iterable["TDigest"],
                  compression: Optional[int] = None) -> "TDigest":
        """Associative-by-value merge: concatenate centroids, re-sort,
        compress only past the budget (so exact stays exact)."""
        ds = [d for d in digests if d is not None and not d.is_empty()]
        if compression is None:
            compression = (max(d.compression for d in ds)
                           if ds else DEFAULT_COMPRESSION)
        if not ds:
            return TDigest.empty(compression)
        means = np.concatenate([d.means for d in ds])
        weights = np.concatenate([d.weights for d in ds])
        order = np.argsort(means, kind="stable")
        means, weights = means[order], weights[order]
        mn = min(d.min for d in ds)
        mx = max(d.max for d in ds)
        if means.size > compression:
            means, weights = _compress(means, weights, compression)
        return TDigest(means, weights, mn, mx, compression)

    # -------------------------------------------------------- estimates

    def quantile(self, q: float) -> Optional[float]:
        """Value at quantile ``q`` in [0, 100] — PERCENT, matching the
        agg bodies. Exact mode reproduces ``np.percentile(sample, q)``
        (linear interpolation); compressed mode interpolates between
        centroid midpoints, clamped to the exact min/max."""
        if self.is_empty():
            return None
        p = float(q) / 100.0
        p = min(max(p, 0.0), 1.0)
        if self.is_exact():
            return float(np.percentile(self.means, p * 100.0))
        w = self.weights
        total = w.sum()
        target = p * total
        # centroid "centers" in cumulative-weight space
        cum = np.cumsum(w)
        centers = cum - w / 2.0
        if target <= centers[0]:
            # below the first center: interpolate min → first mean
            if w[0] <= 1.0 or centers[0] <= 0:
                return float(self.min)
            f = target / centers[0]
            return float(self.min + f * (self.means[0] - self.min))
        if target >= centers[-1]:
            tail = total - centers[-1]
            if w[-1] <= 1.0 or tail <= 0:
                return float(self.max)
            f = (target - centers[-1]) / tail
            return float(self.means[-1] + f * (self.max - self.means[-1]))
        i = int(np.searchsorted(centers, target, side="right")) - 1
        span = centers[i + 1] - centers[i]
        f = 0.0 if span <= 0 else (target - centers[i]) / span
        return float(self.means[i] + f * (self.means[i + 1] - self.means[i]))

    def cdf(self, x: float) -> float:
        """Fraction of mass ≤ x (exact mode: exactly the sample CDF the
        raw-carrier implementation computed)."""
        if self.is_empty():
            return 0.0
        if self.is_exact():
            return float((self.means <= x).mean())
        if x < self.min:
            return 0.0
        if x >= self.max:
            return 1.0
        w = self.weights
        total = w.sum()
        cum = np.cumsum(w)
        centers = cum - w / 2.0
        if x < self.means[0]:
            span = self.means[0] - self.min
            f = 0.0 if span <= 0 else (x - self.min) / span
            return float(f * centers[0] / total)
        if x >= self.means[-1]:
            span = self.max - self.means[-1]
            f = 1.0 if span <= 0 else (x - self.means[-1]) / span
            return float((centers[-1] + f * (total - centers[-1])) / total)
        i = int(np.searchsorted(self.means, x, side="right")) - 1
        span = self.means[i + 1] - self.means[i]
        f = 0.0 if span <= 0 else (x - self.means[i]) / span
        return float((centers[i] + f * (centers[i + 1] - centers[i]))
                     / total)

    def mad(self) -> Optional[float]:
        """Median absolute deviation (ref: x-pack analytics
        MedianAbsoluteDeviationAggregator reduces a TDigest the same
        way): the weighted median of |centroid − median|. Exact on the
        exact path, centroid-resolution approximate when compressed."""
        if self.is_empty():
            return None
        med = self.quantile(50.0)
        dev = np.abs(self.means - med)
        order = np.argsort(dev, kind="stable")
        dev, w = dev[order], self.weights[order]
        if self.is_exact():
            return float(np.median(dev))
        cum = np.cumsum(w)
        i = int(np.searchsorted(cum, w.sum() / 2.0, side="left"))
        return float(dev[min(i, dev.size - 1)])

    def data_points(self) -> np.ndarray:
        """The digest's representative points (exact mode: the sample
        itself) — used by boxplot's whisker clamp."""
        return self.means

    # -------------------------------------------------------------- wire

    def to_wire(self) -> Dict[str, Any]:
        return {"c": self.compression,
                "mn": self.min, "mx": self.max,
                "m": [float(v) for v in self.means],
                "w": [float(v) for v in self.weights]}

    @classmethod
    def from_wire(cls, payload: Optional[Dict[str, Any]]) -> "TDigest":
        if not payload or not payload.get("m"):
            return cls.empty((payload or {}).get(
                "c", DEFAULT_COMPRESSION))
        return cls(np.asarray(payload["m"], np.float64),
                   np.asarray(payload["w"], np.float64),
                   payload.get("mn"), payload.get("mx"),
                   payload.get("c", DEFAULT_COMPRESSION))


def _compress(means: np.ndarray, weights: np.ndarray,
              compression: int):
    """One vectorized merging pass under the k1 scale function: assign
    each (sorted) centroid to the k-bucket of its cumulative-weight
    midpoint, then aggregate buckets with weighted bincounts."""
    total = weights.sum()
    cum = np.cumsum(weights)
    q_mid = (cum - weights / 2.0) / total
    # k1 scale: k(q) = (c/2π)·(asin(2q−1) + π/2) ∈ [0, c/2]·(2/π)… the
    # constant factor only sets the bucket count ≈ compression
    k = (compression / (2.0 * math.pi)) * (
        np.arcsin(np.clip(2.0 * q_mid - 1.0, -1.0, 1.0)) + math.pi / 2.0)
    ids = np.floor(k).astype(np.int64)
    # monotone ids (floor of a monotone function is monotone) → dense
    ids = np.cumsum(np.r_[0, (np.diff(ids) > 0).astype(np.int64)])
    nb = int(ids[-1]) + 1
    w_out = np.bincount(ids, weights=weights, minlength=nb)
    m_out = np.bincount(ids, weights=weights * means,
                        minlength=nb) / np.maximum(w_out, 1e-300)
    return m_out, w_out


def merge_wire_digests(payloads: List[Optional[Dict[str, Any]]],
                       compression: Optional[int] = None
                       ) -> Dict[str, Any]:
    """Merge wire-form digests (coordinator partial reduce) without the
    caller touching TDigest instances."""
    merged = TDigest.merge_all(
        [TDigest.from_wire(p) for p in payloads if p], compression)
    return merged.to_wire()
