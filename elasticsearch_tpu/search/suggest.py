"""Suggesters: term, phrase, completion.

ref: search/suggest/ — TermSuggester (per-token edit-distance candidates
over the term dictionary, Lucene DirectSpellChecker), PhraseSuggester
(whole-phrase correction built from per-token candidates), and
CompletionSuggester (prefix matching; the reference uses FSTs, here the
sorted term dictionary gives prefix ranges directly).

All candidate generation runs host-side against the shard term
dictionaries — suggesters are dictionary problems, not scoring problems,
so nothing here needs the device.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.search.queries import (
    _edit_distance_within,
    _expand_prefix,
)


def _field_analyzer(mapper, field: str):
    ft = mapper.field_type(field)
    name = getattr(ft, "search_analyzer_name",
                   getattr(ft, "analyzer_name", "standard"))
    return (mapper.analysis.get(name) if mapper.analysis.has(name)
            else mapper.analysis.default)


class _TermDict:
    """Union of shard term dictionaries: sorted term list (for bisected
    prefix ranges) + summed doc freqs."""

    def __init__(self, searchers, field: str):
        freqs: Dict[str, int] = {}
        for _, searcher in searchers:
            for seg in searcher.segments:
                pf = seg.postings.get(field)
                if pf is None:
                    continue
                for tid, term in enumerate(pf.terms):
                    freqs[term] = freqs.get(term, 0) + int(pf.doc_freq[tid])
        self.freqs = freqs
        self.sorted_terms = sorted(freqs)

    def candidates_for(self, prefix: str) -> List[str]:
        if not prefix:
            return self.sorted_terms
        return _expand_prefix(self.sorted_terms, prefix,
                              len(self.sorted_terms))


def _term_candidates(token: str, tdict: _TermDict, max_edits: int,
                     prefix_length: int, min_word_length: int,
                     size: int) -> List[Dict[str, Any]]:
    if len(token) < min_word_length:
        return []
    out: List[Tuple[float, int, str]] = []
    for term in tdict.candidates_for(token[:prefix_length]):
        if term == token or abs(len(term) - len(token)) > max_edits:
            continue
        d = _edit_distance_within(token, term, max_edits)
        if d <= max_edits:
            score = 1.0 - d / max(len(token), len(term))
            out.append((score, tdict.freqs[term], term))
    out.sort(key=lambda e: (-e[0], -e[1], e[2]))
    return [{"text": t, "score": round(s, 6), "freq": df}
            for s, df, t in out[:size]]


def compute_suggest(spec: Dict[str, Any], searchers) -> Dict[str, Any]:
    """spec: {"text": global_text?, <name>: {"text"?, "term"|"phrase"|
    "completion": {...}}} → ES-shaped suggest response section."""
    global_text = spec.get("text")
    out: Dict[str, Any] = {}
    mapper = searchers[0][1].mapper if searchers else None
    for name, entry in spec.items():
        if name == "text" or not isinstance(entry, dict):
            continue
        text = entry.get("text", global_text) or ""
        if "term" in entry:
            out[name] = _term_suggest(text, entry["term"], searchers, mapper)
        elif "phrase" in entry:
            out[name] = _phrase_suggest(text, entry["phrase"], searchers, mapper)
        elif "completion" in entry:
            out[name] = _completion_suggest(
                entry.get("prefix", text), entry["completion"], searchers)
    return out


def _term_suggest(text: str, cfg: Dict[str, Any], searchers, mapper):
    field = cfg["field"]
    size = int(cfg.get("size", 5))
    max_edits = int(cfg.get("max_edits", 2))
    prefix_length = int(cfg.get("prefix_length", 1))
    min_word_length = int(cfg.get("min_word_length", 4))
    suggest_mode = cfg.get("suggest_mode", "missing")
    tdict = _TermDict(searchers, field)
    analyzer = _field_analyzer(mapper, field)
    entries = []
    for tok in analyzer.analyze(text):
        existing_df = tdict.freqs.get(tok.term, 0)
        if suggest_mode == "missing" and existing_df > 0:
            options: List[Dict[str, Any]] = []
        else:
            options = _term_candidates(tok.term, tdict, max_edits,
                                       prefix_length, min_word_length, size)
            if suggest_mode == "popular":
                options = [o for o in options if o["freq"] > existing_df]
        entries.append({
            "text": tok.term, "offset": tok.start_offset,
            "length": tok.end_offset - tok.start_offset,
            "options": options,
        })
    return entries


def _phrase_suggest(text: str, cfg: Dict[str, Any], searchers, mapper):
    field = cfg["field"]
    size = int(cfg.get("size", 5))
    max_errors = float(cfg.get("max_errors", 1.0))
    tdict = _TermDict(searchers, field)
    analyzer = _field_analyzer(mapper, field)
    toks = analyzer.analyze(text)
    if not toks:
        return [{"text": text, "offset": 0, "length": len(text), "options": []}]
    # per-token best corrections (existing tokens "correct" to themselves)
    per_token: List[List[Tuple[str, float]]] = []
    any_correction = False
    for tok in toks:
        if tdict.freqs.get(tok.term, 0) > 0:
            per_token.append([(tok.term, 1.0)])
        else:
            cands = _term_candidates(tok.term, tdict, 2, 1, 1, 3)
            if cands:
                any_correction = True
                per_token.append([(c["text"], c["score"]) for c in cands])
            else:
                per_token.append([(tok.term, 0.1)])
    options: List[Dict[str, Any]] = []
    if any_correction:
        budget = max(1, int(max_errors) if max_errors >= 1
                     else int(len(toks) * max_errors) or 1)
        # beam over per-token candidates, bounded by the error budget
        beams: List[Tuple[List[str], float, int]] = [([], 1.0, 0)]
        for ti, cands in enumerate(per_token):
            new_beams = []
            orig = toks[ti].term
            for words, score, errs in beams:
                for w, s in cands[: size]:
                    e = errs + (1 if w != orig else 0)
                    if e > budget:
                        continue
                    new_beams.append((words + [w], score * s, e))
            new_beams.sort(key=lambda b: -b[1])
            beams = new_beams[: max(size * 2, 10)]
        seen = set()
        for words, score, errs in beams:
            phrase = " ".join(words)
            if phrase in seen or errs == 0:
                continue
            seen.add(phrase)
            options.append({"text": phrase, "score": round(score, 6)})
            if len(options) >= size:
                break
    return [{"text": text, "offset": 0, "length": len(text),
             "options": options}]


def _completion_suggest(prefix: str, cfg: Dict[str, Any], searchers):
    field = cfg["field"]
    size = int(cfg.get("size", 5))
    # context filter: {"contexts": {"genre": ["rock"]}} — entries must
    # carry EVERY requested context value (category contexts, ref:
    # completion/context/CategoryContextMapping)
    ctx_filter = frozenset(
        f"{name}={v}"
        for name, vals in (cfg.get("contexts") or {}).items()
        for v in ([vals] if isinstance(vals, str) else vals))

    # completion-FIELD segments serve from the weighted prefix index
    # (sublinear top-k; ref CompletionSuggester.java:41); fields without
    # one keep the term-dictionary fallback below
    best: Dict[str, Tuple[float, str]] = {}
    used_index = False
    for _, searcher in searchers:
        for seg in searcher.segments:
            cv = seg.completions.get(field)
            if cv is None:
                continue
            used_index = True
            for i in cv.top_k(prefix, size,
                              context_filter=ctx_filter or None,
                              live=seg.live):
                text = cv.inputs[i]
                w = float(cv.weights[i])
                doc = seg.stored.ids[int(cv.doc_of[i])]
                if text not in best or w > best[text][0]:
                    best[text] = (w, doc)
    if used_index:
        options = [
            {"text": t, "_id": doc, "score": w}
            for t, (w, doc) in sorted(best.items(),
                                      key=lambda e: (-e[1][0], e[0]))
        ][:size]
        return [{"text": prefix, "offset": 0, "length": len(prefix),
                 "options": options}]

    scored: Dict[str, int] = {}
    for _, searcher in searchers:
        for seg in searcher.segments:
            pf = seg.postings.get(field)
            kv = seg.keywords.get(field)
            terms = (pf.terms if pf is not None
                     else kv.terms if kv is not None else [])
            for t in _expand_prefix(terms, prefix, size * 8):
                if pf is not None:
                    scored[t] = scored.get(t, 0) + int(
                        pf.doc_freq[pf.term_id(t)])
                else:
                    scored[t] = scored.get(t, 0) + 1
    options = [{"text": t, "score": float(df)} for t, df in
               sorted(scored.items(), key=lambda e: (-e[1], e[0]))[:size]]
    return [{"text": prefix, "offset": 0, "length": len(prefix),
             "options": options}]
