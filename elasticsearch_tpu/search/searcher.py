"""Shard searcher: the query and fetch phases over one shard.

Mirrors the data-node side of the reference (ref: search/query/
QueryPhase.java:170-328 — collector chain of post_filter → min_score →
top-k; search/fetch/FetchPhase.java:75,90 — load _source for winners).
Execution model: per segment, the compiled query produces dense
(scores, mask) device arrays; the collector chain is mask algebra; top-k
runs on device (ops/topk.py); per-segment results merge host-side the way
SearchPhaseController.mergeTopDocs merges per-shard results — by
(-score, segment_idx, docid), Lucene's exact tie order.

Sorting: sort keys are columnar doc values, so a sort is top-k over a
transformed key column. Multi-key sorts use the primary key on device and
re-sort the k winners by the full key host-side (exact unless >k docs tie
on the primary key — noted limitation).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.index.mapper import MapperService
from elasticsearch_tpu.index.segment import Segment
from elasticsearch_tpu.ops import device as device_ops
from elasticsearch_tpu.ops import topk as topk_ops
from elasticsearch_tpu.search.context import (
    DeviceSegmentCache,
    SegmentContext,
    ShardStats,
)
from elasticsearch_tpu.search.queries import QueryBuilder, parse_query

MAX_TOPK = 10000
_MISS = object()   # plan-cache sentinel (None is a valid cached value)


@dataclass(slots=True)
class DocAddress:
    segment_idx: int
    docid: int
    score: float
    sort_values: Tuple = ()
    sort_key: float = 0.0  # the device key used for ordering (score or field)


@dataclass
class QueryResult:
    """Per-shard query-phase result (ref: QuerySearchResult): doc addresses
    + scores only — sources are fetched in the fetch phase for winners.
    When requested, also carries per-segment match masks (pre-post_filter,
    as the reference computes aggs before post_filter applies) for the
    aggregation phase."""

    docs: List[DocAddress]
    total_hits: int
    max_score: Optional[float]
    agg_masks: Optional[List[Tuple[Segment, np.ndarray]]] = None
    # True when block-max pruning ran: total_hits is then a LOWER bound
    # (the service reports hits.total.relation = "gte")
    total_lower_bound: bool = False


class ShardSearcher:
    def __init__(self, segments: List[Segment], mapper: MapperService,
                 cache: Optional[DeviceSegmentCache] = None,
                 k1: float = 1.2, b: float = 0.75):
        self.segments = segments
        self.mapper = mapper
        self.cache = cache or DeviceSegmentCache()
        self.stats = ShardStats(segments)
        self.k1 = k1
        self.b = b
        # set by SearchService: continuous batching of plan launches
        self.batcher = None
        # breaker-accounted host allocation (utils/bigarrays.py): when
        # wired, the dense path's [ND] host readback buffers charge the
        # `request` breaker — the analogue of BigArrays guarding
        # QueryPhase's collector allocations. Inherited from the shared
        # device cache (the node wires it once); None = unaccounted.
        self.bigarrays = getattr(self.cache, "bigarrays", None)
        # snapshot epoch, set by IndexService.shard_searchers — feeds
        # plan-cache keys (tests constructing searchers directly leave
        # it None, which only means their caches key on segment names)
        self.epoch = None

    def _contexts(self) -> List[SegmentContext]:
        return [SegmentContext(seg, self.cache.get(seg), self.mapper,
                               self.stats, self.k1, self.b)
                for seg in self.segments]

    # ------------------------------------------------------------ query
    def query_phase(self, query: QueryBuilder, size: int,
                    post_filter: Optional[QueryBuilder] = None,
                    min_score: Optional[float] = None,
                    sort: Optional[List[Dict[str, Any]]] = None,
                    search_after: Optional[List[Any]] = None,
                    # bool OR int threshold (ES track_total_hits): any
                    # non-True value licenses block-max pruning, since
                    # totals may then be lower bounds ("gte")
                    track_total_hits=True,
                    after_key: Optional[Tuple[float, int, int]] = None,
                    collect_masks: bool = False,
                    allow_plan: bool = True,
                    cache_key: Optional[str] = None) -> QueryResult:
        k = min(max(size, 1), MAX_TOPK)
        sort_spec = _parse_sort(sort)

        # ---- fused plan fast path (ref: the BulkScorer replacement —
        # ops/plan.py): score-sorted top-k queries with no agg masks
        # compile straight to the sorted segmented-reduction kernel; the
        # dense executor below stays for everything that semantically
        # needs full [ND] score/mask vectors
        plan_after: Optional[float] = None
        if search_after is not None and sort_spec is None \
                and len(search_after) == 1:
            # _score cursor: the kernel applies it natively, keeping ALL
            # pages of a score-paged walk on one executor (float32 sums
            # differ between executors in the last bits)
            plan_after = float(search_after[0])
        plannable = (allow_plan and sort_spec is None and min_score is None
                     and (search_after is None or plan_after is not None)
                     and after_key is None and not collect_masks)
        lp_key = None
        if plannable and cache_key is not None:
            # compiled-plan memo (DeviceSegmentCache.plan_cache): repeat
            # queries skip parse-side rewrite + compile entirely; the
            # epoch in the key pins shard-level stats (idf, avg length)
            lp_key = (tuple(s.name for s in self.segments),
                      self.epoch, self.k1, self.b, cache_key)
            cached = self.cache.plan_cache.get(lp_key, _MISS)
            if cached is not _MISS:
                self.cache.plan_cache_hits += 1
                if cached is not None:
                    return self._plan_query_phase(
                        query, cached, k, track_total_hits, plan_after,
                        cache_key=lp_key)
                plannable = False   # known not plannable: dense path
            else:
                self.cache.plan_cache_misses += 1

        from elasticsearch_tpu.search import profile as _prof
        with _prof.span("rewrite"):
            query = query.rewrite(self)
            if post_filter is not None:
                post_filter = post_filter.rewrite(self)
        if plannable:
            from elasticsearch_tpu.search.plan import compile_plan
            with _prof.span("compile"):
                plan = compile_plan(query, self, post_filter)
            if lp_key is not None:
                pc = self.cache.plan_cache
                pc[lp_key] = plan
                while len(pc) > self.cache.plan_cache_max:
                    pc.popitem(last=False)
                    self.cache.plan_cache_evictions += 1
            if plan is not None:
                return self._plan_query_phase(query, plan, k,
                                              track_total_hits, plan_after,
                                              cache_key=lp_key)
        per_segment: List[Tuple[int, np.ndarray, np.ndarray, np.ndarray]] = []
        total = 0
        max_score = None
        agg_masks: List[Tuple[Segment, np.ndarray]] = [] if collect_masks else None

        for seg_idx, ctx in enumerate(self._contexts()):
            if ctx.segment.n_docs == 0 or not query.can_match(ctx):
                if collect_masks:
                    agg_masks.append((ctx.segment,
                                      np.zeros(ctx.segment.n_docs, bool)))
                continue
            _prof.note("collector", "DenseColumnTopDocsCollector")
            with _prof.span("score"):
                scores, mask = query.execute(ctx)
            mask = mask & ctx.live
            if min_score is not None:
                # min_score wraps ALL collectors incl. aggs (ref:
                # MinimumScoreCollector in the QueryPhase chain)
                mask = mask & (scores >= min_score)
            if collect_masks:
                # aggs see the query mask BEFORE post_filter (that's the
                # point of post_filter, ref: QueryPhase collector order)
                agg_masks.append((ctx.segment,
                                  np.asarray(mask)[: ctx.segment.n_docs]))
            if post_filter is not None:
                _, pf_mask = post_filter.execute(ctx)
                mask = mask & pf_mask
            if track_total_hits:
                total += int(jnp.sum(mask))
            if _needs_max_score(sort_spec):
                seg_max = float(jnp.max(jnp.where(mask, scores, -jnp.inf)))
                if np.isfinite(seg_max):
                    max_score = seg_max if max_score is None else max(max_score, seg_max)

            key = _primary_sort_key(ctx, scores, sort_spec)
            if search_after is not None:
                mask = mask & _search_after_mask(
                    ctx, scores, sort_spec, search_after)
            if after_key is not None:
                # exact scroll continuation: strictly after the last emitted
                # doc in (key desc, segment asc, docid asc) order (ref:
                # scroll lastEmittedDoc, QueryPhase.java:182-213)
                ck, cseg, cdoc = after_key[0], after_key[1], after_key[2]
                if _primary_is_keyword(self, sort_spec):
                    # keyword sort keys are segment-LOCAL ordinals — compare
                    # the cursor TERM against this segment's term dict
                    cval = after_key[3] if len(after_key) > 3 else None
                    strictly, tied = _keyword_after_masks(
                        ctx, sort_spec[0].field, cval, sort_spec[0].order)
                    if seg_idx < cseg:
                        allowed = strictly
                    elif seg_idx == cseg:
                        docids = jnp.arange(ctx.n_docs_padded)
                        allowed = strictly | (tied & (docids > cdoc))
                    else:
                        allowed = strictly | tied
                elif seg_idx < cseg:
                    allowed = key < ck
                elif seg_idx == cseg:
                    docids = jnp.arange(ctx.n_docs_padded)
                    allowed = (key < ck) | ((key == ck) & (docids > cdoc))
                else:
                    allowed = key <= ck
                mask = mask & allowed
            with _prof.span("topk"):
                vals, ids = topk_ops.masked_topk(key, mask,
                                                 min(k, ctx.n_docs_padded))
            with _prof.span("readback"):
                # the tracked funnel (ops/device.py): flight-recorder
                # provenance + `profile: true` readback counters
                vals, ids = device_ops.readback(
                    "search.searcher.dense_topk", vals, ids)
            keep = np.isfinite(vals)
            ids = ids[keep]
            if self.bigarrays is not None:
                # the full [ND] score column materializes on the host
                # here — account it against the request breaker for the
                # duration of the gather (a trip aborts THIS shard with
                # a typed circuit_breaking_exception; siblings and other
                # copies still answer)
                with self.bigarrays.adopt(np.asarray(scores),
                                          "dense_scores_readback") as acc:
                    scores_np = acc.array[ids]
            else:
                scores_np = np.asarray(scores)[ids]
            per_segment.append((seg_idx, vals[keep], ids, scores_np))

        # ---- merge per-segment top-k (ref: SearchPhaseController.sortDocs)
        if not per_segment:
            return QueryResult([], total, None, agg_masks)
        all_keys = np.concatenate([v for _, v, _, _ in per_segment])
        all_segs = np.concatenate(
            [np.full(len(i), s, np.int32) for s, _, i, _ in per_segment])
        all_ids = np.concatenate([i for _, _, i, _ in per_segment])
        all_scores = np.concatenate([sc for _, _, _, sc in per_segment])
        # keyword primary sorts use segment-LOCAL ordinals as device keys,
        # so cross-segment truncation must compare the terms themselves:
        # keep every per-segment winner, re-sort host-side, then cut to k
        string_primary = _primary_is_keyword(self, sort_spec)
        order = (np.arange(len(all_keys)) if string_primary
                 else np.lexsort((all_ids, all_segs, -all_keys))[:k])

        docs = []
        for idx in order:
            seg_idx, docid = int(all_segs[idx]), int(all_ids[idx])
            ctx_seg = self.segments[seg_idx]
            sv = _sort_values(self, ctx_seg, docid, float(all_scores[idx]), sort_spec)
            docs.append(DocAddress(seg_idx, docid, float(all_scores[idx]), sv,
                                   sort_key=float(all_keys[idx])))
        # multi-key or string-keyed: re-sort winners by the full key
        # host-side (ref: SearchPhaseController merge compares real values)
        if sort_spec is not None and (len(sort_spec) > 1 or string_primary):
            import functools
            docs.sort(key=functools.cmp_to_key(
                lambda a, b: _host_sort_cmp(a, b, sort_spec)))
            docs = docs[:k]
        return QueryResult(docs, total, max_score, agg_masks)

    def _plan_query_phase(self, query: QueryBuilder, plan, k: int,
                          track_total_hits,
                          after_score: Optional[float] = None,
                          cache_key=None) -> QueryResult:
        """Execute a compiled LogicalPlan per segment via the fused
        sorted-top-k kernel (search/plan.py) and merge exactly as the
        dense path merges (by (-score, segment, docid))."""
        from elasticsearch_tpu.search import profile as _prof
        from elasticsearch_tpu.search.plan import bind_plan, execute_bound
        _prof.note("collector", "FusedPlanTopDocsCollector")

        # exact totals (track_total_hits: true) forbid dropping blocks;
        # thresholded/disabled totals license block-max pruning, exactly
        # as Lucene only enters TOP_SCORES mode under a total-hits
        # threshold (ref: TopDocsCollectorContext.java:210-217)
        allow_prune = track_total_hits is not True and after_score is None
        per_segment: List[Tuple[int, np.ndarray, np.ndarray]] = []
        total = 0
        lower_bound = False
        for seg_idx, ctx in enumerate(self._contexts()):
            if ctx.segment.n_docs == 0:
                continue
            # bound-plan cache: repeats reuse the device-resident
            # selection arrays (skips bind + per-launch h2d uploads)
            bkey = None
            bp = None
            if cache_key is not None:
                # live_version: deletes change which docs validate the
                # block-max pruning threshold, so bound (possibly
                # pruned) plans must not outlive the live mask
                bkey = (cache_key, k, allow_prune,
                        ctx.segment.live_version)
                bp = ctx.device._bound_plans.get(bkey)
                if bp is not None:
                    ctx.device.bound_plan_hits += 1
            if bp is None:
                if bkey is not None:
                    ctx.device.bound_plan_misses += 1
                if not query.can_match(ctx):
                    continue
                with _prof.span("bind"):
                    bp = bind_plan(plan, ctx, k=k,
                                   allow_prune=allow_prune)
                if bkey is not None:
                    bpc = ctx.device._bound_plans
                    bpc[bkey] = bp
                    while len(bpc) > 128:
                        bpc.popitem(last=False)
                        ctx.device.bound_plan_evictions += 1
            lower_bound = lower_bound or bp.pruned
            with _prof.span("launch"):
                if self.batcher is not None:
                    vals, ids, seg_total = self.batcher.execute(
                        bp, ctx, k, self.k1, self.b, after_score)
                else:
                    rec_on = _prof.recording()
                    t_l = _prof.now_ns() if rec_on else 0
                    vals, ids, seg_total = execute_bound(
                        bp, ctx, k, self.k1, self.b, after_score)
                    if rec_on:
                        # unbatched launch (the distributed data-node
                        # path): a cohort-of-one attribution record so
                        # the shard profile still names the kernel and
                        # its selection width
                        _prof.record_device({
                            "kernel": "plan_topk_packed",
                            "cohort": 1, "q_bucket": 1,
                            "nb_bucket": max(
                                (int(st.sel_blocks.shape[0])
                                 for st in bp.streams), default=0),
                            "padding_waste_pct": 0.0,
                            "batch_wait_ms": 0.0,
                            "launch_ms": round(
                                (_prof.now_ns() - t_l) / 1e6, 3),
                        })
            with _prof.span("readback"):
                vals, ids = device_ops.readback(
                    "search.searcher.plan_topk", vals, ids)
            if track_total_hits:
                total += int(seg_total)
            keep = vals > -np.inf
            if not keep.any():
                continue
            per_segment.append((seg_idx, vals[keep], ids[keep]))
        if not per_segment:
            return QueryResult([], total, None, None,
                               total_lower_bound=lower_bound)
        if len(per_segment) == 1:
            # kernel top_k rows are already (-score, docid)-ordered
            seg_idx, vals, ids = per_segment[0]
            docs = [DocAddress(seg_idx, int(i), float(v), (), sort_key=float(v))
                    for v, i in zip(vals.tolist(), ids.tolist())]
            return QueryResult(docs, total, docs[0].score if docs else None,
                               None, total_lower_bound=lower_bound)
        all_keys = np.concatenate([v for _, v, _ in per_segment])
        all_segs = np.concatenate(
            [np.full(len(i), s, np.int32) for s, _, i in per_segment])
        all_ids = np.concatenate([i for _, _, i in per_segment])
        order = np.lexsort((all_ids, all_segs, -all_keys))[:k]
        docs = [DocAddress(int(all_segs[i]), int(all_ids[i]),
                           float(all_keys[i]), (),
                           sort_key=float(all_keys[i]))
                for i in order]
        max_score = float(all_keys[order[0]]) if len(order) else None
        return QueryResult(docs, total, max_score, None,
                           total_lower_bound=lower_bound)

    # ---------------------------------------------------------- rescore
    def rescore(self, docs: List[DocAddress],
                rescore_specs: List[Dict[str, Any]]) -> List[DocAddress]:
        """Query rescorer (ref: rescore/QueryRescorer.java, run from
        QueryPhase.execute:152-153): re-scores the top ``window_size``
        docs of this shard with a (usually costlier) second query. The
        rescore query executes dense per segment ONCE; per-doc scores are
        gathered from the result column."""
        for spec in rescore_specs:
            window = int(spec.get("window_size", 10))
            qspec = spec.get("query", {})
            rq = parse_query(qspec["rescore_query"]).rewrite(self)
            qw = float(qspec.get("query_weight", 1.0))
            rqw = float(qspec.get("rescore_query_weight", 1.0))
            mode = qspec.get("score_mode", "total")
            seg_cache: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
            contexts = self._contexts()
            head, tail = docs[:window], docs[window:]
            for d in head:
                if d.segment_idx not in seg_cache:
                    s, m = rq.execute(contexts[d.segment_idx])
                    seg_cache[d.segment_idx] = (np.asarray(s), np.asarray(m))
                scores, mask = seg_cache[d.segment_idx]
                base = qw * d.score
                if bool(mask[d.docid]):
                    rs = rqw * float(scores[d.docid])
                    if mode == "total":
                        new = base + rs
                    elif mode == "multiply":
                        new = base * rs
                    elif mode == "avg":
                        new = (base + rs) / 2.0
                    elif mode == "max":
                        new = max(base, rs)
                    elif mode == "min":
                        new = min(base, rs)
                    else:
                        raise IllegalArgumentException(
                            f"illegal score_mode [{mode}]")
                else:
                    new = base  # non-matching docs keep query_weight·score
                d.score = new
                d.sort_key = new
            head.sort(key=lambda d: (-d.score, d.segment_idx, d.docid))
            docs = head + tail
        return docs

    # ------------------------------------------------------------ fetch
    def fetch_phase(self, docs: List[DocAddress],
                    source_filter: Any = True,
                    docvalue_fields: Optional[List[str]] = None,
                    highlight: Optional[Dict[str, Any]] = None,
                    highlight_query: Optional[QueryBuilder] = None,
                    script_fields: Optional[Dict[str, Any]] = None,
                    fields: Optional[List[Any]] = None,
                    version: bool = False,
                    seq_no_primary_term: bool = False) -> List[Dict[str, Any]]:
        if (source_filter is False and not docvalue_fields
                and not highlight and not script_fields and not fields
                and not version and not seq_no_primary_term
                and not any(d.sort_values for d in docs)):
            # serving fast path: id+score rows only (size=k, _source
            # false — the benchmark/scroll-id class); one tight
            # comprehension instead of the subphase loop
            segs = self.segments
            return [{"_id": segs[d.segment_idx].stored.ids[d.docid],
                     "_score": d.score if d.score == d.score else None}
                    for d in docs]
        script_cols = (self._script_field_columns(script_fields)
                       if script_fields else None)
        hits = []
        for d in docs:
            seg = self.segments[d.segment_idx]
            hit: Dict[str, Any] = {
                "_id": seg.stored.ids[d.docid],
                "_score": d.score if d.score == d.score else None,
            }
            if d.sort_values:
                hit["sort"] = list(d.sort_values)
            # metadata doc values (ref: fetch subphases VersionPhase /
            # SeqNoPrimaryTermPhase — `"version": true` /
            # `"seq_no_primary_term": true` in the search body)
            if version:
                nv = seg.numerics.get("_version")
                vs = nv.get(d.docid) if nv is not None else None
                if vs:
                    hit["_version"] = int(vs[0])
            if seq_no_primary_term:
                for meta in ("_seq_no", "_primary_term"):
                    nv = seg.numerics.get(meta)
                    vs = nv.get(d.docid) if nv is not None else None
                    if vs:
                        hit[meta] = int(vs[0])
            parsed_source: Optional[Dict[str, Any]] = None

            def get_source(seg=seg, d=d):
                nonlocal parsed_source
                if parsed_source is None:
                    parsed_source = json.loads(seg.stored.source(d.docid))
                return parsed_source

            if source_filter is not False:
                hit["_source"] = _filter_source(get_source(), source_filter)
            if docvalue_fields:
                out = {}
                for f in docvalue_fields:
                    nv = seg.numerics.get(f)
                    if nv is not None:
                        vs = nv.get(d.docid)
                        if vs:
                            out[f] = vs
                    kv = seg.keywords.get(f)
                    if kv is not None:
                        vs = kv.get(d.docid)
                        if vs:
                            out[f] = vs
                hit["fields"] = out
            if fields:
                # the "fields" retrieval API (ref: FetchFieldsPhase) —
                # values come from doc values, falling back to _source
                out = hit.setdefault("fields", {})
                for f in fields:
                    fname = f if isinstance(f, str) else f.get("field")
                    vs = []
                    nv = seg.numerics.get(fname)
                    kv = seg.keywords.get(fname)
                    if nv is not None:
                        vs = nv.get(d.docid)
                    if not vs and kv is not None:
                        vs = kv.get(d.docid)
                    if not vs:
                        v = _get_path(get_source(), fname)
                        if v is not None:
                            vs = v if isinstance(v, list) else [v]
                    if vs:
                        out[fname] = vs
            if script_cols:
                out = hit.setdefault("fields", {})
                for fname, col in script_cols.items():
                    out[fname] = [float(col[d.segment_idx][d.docid])]
            if highlight:
                hit["highlight"] = self._highlight(seg, d.docid, highlight,
                                                   highlight_query)
            hits.append(hit)
        return hits

    def _script_field_columns(self, script_fields: Dict[str, Any]):
        """Evaluate each script field ONCE per segment as a dense column
        (ref: search/fetch/subphase/ScriptFieldsPhase — but columnar, not
        per-doc)."""
        from elasticsearch_tpu.search.script import (
            ScriptContext,
            _DocColumn,
            compile_script,
        )
        cols: Dict[str, List[np.ndarray]] = {}
        contexts = self._contexts()
        for fname, spec in script_fields.items():
            script = spec.get("script", spec) if isinstance(spec, dict) else spec
            source = (script.get("source") if isinstance(script, dict)
                      else str(script))
            params = script.get("params", {}) if isinstance(script, dict) else {}
            compiled = compile_script(source)
            per_seg = []
            for ctx in contexts:
                def doc_columns(field, ctx=ctx):
                    col, miss = ctx.numeric_column(field)
                    return _DocColumn(col, miss)
                sctx = ScriptContext(doc_columns, params)
                val = np.broadcast_to(
                    np.asarray(compiled(sctx), np.float32),
                    (ctx.n_docs_padded,))
                per_seg.append(val)
            cols[fname] = per_seg
        return cols

    def _highlight(self, seg: Segment, docid: int, spec: Dict[str, Any],
                   query: Optional[QueryBuilder]) -> Dict[str, List[str]]:
        """Unified-highlighter analogue (ref: search/fetch/subphase/
        highlight/UnifiedHighlighter.java — passage-based fragmenting
        with score-ordered snippets; ``type: plain`` keeps the whole-
        field PlainHighlighter behavior). Per-field options follow the
        reference: ``fragment_size`` (default 100), ``number_of_
        fragments`` (default 5; 0 = no fragmenting, highlight the whole
        value), ``no_match_size``, ``order`` ("score" default /
        "none"), ``pre_tags``/``post_tags``. Passages snap to sentence
        boundaries and are scored by (distinct matched terms, total
        matches, earliest) — a disclosed simplification of Lucene's
        BM25 PassageScorer that preserves its ordering behavior on
        multi-term queries."""
        query_terms = _collect_terms(query, self.mapper) if query else {}
        source = json.loads(seg.stored.source(docid))
        out: Dict[str, List[str]] = {}
        for fname, fspec in (spec.get("fields", {}) or {}).items():
            fspec = fspec or {}

            def opt(name, default):
                return fspec.get(name, spec.get(name, default))
            pre = opt("pre_tags", ["<em>"])[0]
            post = opt("post_tags", ["</em>"])[0]
            frag_size = int(opt("fragment_size", 100))
            n_frags = int(opt("number_of_fragments", 5))
            no_match = int(opt("no_match_size", 0))
            order = str(opt("order", "score"))
            value = _get_path(source, fname)
            if not isinstance(value, str):
                continue
            htype = str(opt("type", "unified"))
            if htype == "fvh":
                # FVH analogue (ref: search/fetch/subphase/highlight/
                # FastVectorHighlighter.java): matched_fields merges
                # matches from sibling (multi-)fields into this field's
                # highlighting — each matched field's spans derive
                # through ITS OWN analyzer over the same source text
                # (a stemmed or case-preserving subfield's hits mark
                # the original). The reference reads term vectors; this
                # engine's positional streams keep term ids but not
                # offsets, so offsets re-derive through the analyzers
                # (disclosed), preserving FVH's observable behaviors:
                # matched_fields, match-centered fragments,
                # boundary_chars/boundary_max_scan trimming.
                matched = opt("matched_fields", None) or [fname]
                if isinstance(matched, str):
                    matched = [matched]
                if fname not in matched:
                    matched = [fname] + list(matched)
                spans = []
                for m in matched:
                    mterms = query_terms.get(m, set())
                    if not mterms:
                        continue
                    mft = self.mapper.field_type(m)
                    aname = getattr(
                        mft, "search_analyzer_name",
                        getattr(mft, "analyzer_name", "standard"))
                    man = (self.mapper.analysis.get(aname)
                           if self.mapper.analysis.has(aname)
                           else self.mapper.analysis.default)
                    spans.extend(
                        (t.start_offset, t.end_offset, t.term)
                        for t in man.analyze(value)
                        if t.term in mterms)
                spans.sort()
                spans = spans[:int(opt("phrase_limit", 256))]
            else:
                terms = query_terms.get(fname, set())
                ft = self.mapper.field_type(fname)
                analyzer_name = getattr(ft, "analyzer_name", "standard")
                analyzer = (self.mapper.analysis.get(analyzer_name)
                            if self.mapper.analysis.has(analyzer_name)
                            else self.mapper.analysis.default)
                spans = [(t.start_offset, t.end_offset, t.term)
                         for t in analyzer.analyze(value)
                         if t.term in terms] if terms else []
            if not spans:
                if no_match > 0 and value:
                    out[fname] = [value[:_snap_end(value, no_match)]]
                continue
            if n_frags == 0 or htype == "plain":
                out[fname] = [_wrap_spans(
                    value, [(s, e) for s, e, _t in spans], pre, post)]
                continue
            if htype == "fvh":
                passages = _fvh_fragments(
                    value, spans, frag_size,
                    str(opt("boundary_chars", ".,!? \t\n")),
                    int(opt("boundary_max_scan", 20)))
            else:
                passages = _build_passages(value, frag_size)
            scored = []
            for pi, (ps, pe) in enumerate(passages):
                inside = [sp for sp in spans
                          if sp[0] >= ps and sp[1] <= pe]
                if not inside:
                    continue
                distinct = len({t for _s, _e, t in inside})
                scored.append(((distinct, len(inside), -ps), pi,
                               inside))
            scored.sort(key=lambda r: r[0], reverse=True)
            chosen = scored[:n_frags]
            if order != "score":
                chosen.sort(key=lambda r: r[1])
            frags = []
            for _score, pi, inside in chosen:
                ps, pe = passages[pi]
                frags.append(_wrap_spans(
                    value[ps:pe],
                    [(s - ps, e - ps) for s, e, _t in inside],
                    pre, post).strip())
            if frags:
                out[fname] = frags
        return out


def _fvh_fragments(text: str, spans, frag_size: int,
                   boundary_chars: str, boundary_max_scan: int):
    """FVH fragmenting: fragments CENTER on match runs (the reference's
    SimpleFragmentsBuilder discipline) and trim to the nearest boundary
    char within ``boundary_max_scan`` (BoundaryScanner semantics) —
    unlike the unified path's precomputed sentence passages."""
    bset = set(boundary_chars)
    n = len(text)

    def snap(pos: int, forward: bool) -> int:
        pos = max(0, min(n, pos))
        rng = (range(pos, min(n, pos + boundary_max_scan)) if forward
               else range(pos, max(0, pos - boundary_max_scan), -1))
        for i in rng:
            if 0 <= i < n and text[i] in bset:
                return i + 1    # cut just past the boundary char
        return pos

    frags = []
    covered_to = -1
    for s, _e, _t in sorted(spans):
        if s <= covered_to:
            continue
        lo = snap(s - frag_size // 2, forward=False) \
            if s > frag_size // 2 else 0
        hi = snap(lo + frag_size, forward=True)
        frags.append((lo, min(hi, n)))
        covered_to = hi
    return frags


def _wrap_spans(text: str, spans, pre: str, post: str) -> str:
    """Wrap (start, end) character spans of ``text`` in pre/post tags."""
    parts = []
    last = 0
    for s, e in sorted(spans):
        if s < last:           # overlapping analyzer spans: keep first
            continue
        parts.append(text[last:s])
        parts.append(pre + text[s:e] + post)
        last = e
    parts.append(text[last:])
    return "".join(parts)


_SENTENCE_ENDS = ".!?\n"


def _snap_end(text: str, at: int) -> int:
    """End offset near ``at`` snapped FORWARD to a sentence/word break
    (the BreakIterator discipline: fragments end on natural boundaries,
    ref UnifiedHighlighter's SENTENCE BreakIterator)."""
    n = len(text)
    if at >= n:
        return n
    for i in range(at, min(n, at + 40)):
        if text[i] in _SENTENCE_ENDS:
            return i + 1
    for i in range(at, min(n, at + 20)):
        if text[i].isspace():
            return i
    return at


def _build_passages(text: str, frag_size: int):
    """Sentence-snapped passages of ~frag_size chars covering the text."""
    passages = []
    start = 0
    n = len(text)
    while start < n:
        end = _snap_end(text, start + max(frag_size, 1))
        if end <= start:
            end = min(n, start + max(frag_size, 1))
        passages.append((start, end))
        start = end
        while start < n and text[start].isspace():
            start += 1
    return passages


# ---------------------------------------------------------------------------
# sort machinery
# ---------------------------------------------------------------------------

@dataclass
class SortKey:
    field: str           # "_score" | "_doc" | "_geo_distance" | field name
    order: str           # "asc" | "desc"
    missing: float = 0.0
    # _geo_distance extras (ref: search/sort/GeoDistanceSortBuilder)
    geo_field: str = ""
    geo_lat: float = 0.0
    geo_lon: float = 0.0
    geo_unit: str = "m"


def _parse_sort(sort) -> Optional[List[SortKey]]:
    if not sort:
        return None
    if isinstance(sort, (str, dict)):
        sort = [sort]
    keys = []
    for entry in sort:
        if isinstance(entry, str):
            field_name, order = entry, ("asc" if entry not in ("_score",) else "desc")
        else:
            (field_name, spec), = entry.items()
            if isinstance(spec, str):
                order = spec
                spec = {}
            else:
                order = spec.get("order", "desc" if field_name == "_score" else "asc")
            if field_name == "_geo_distance":
                from elasticsearch_tpu.common.geo import parse_geo_point
                field_entries = [
                    (k, v) for k, v in spec.items()
                    if k not in ("order", "unit", "mode", "distance_type",
                                 "ignore_unmapped")]
                if len(field_entries) != 1:
                    from elasticsearch_tpu.common.errors import ParsingException
                    raise ParsingException(
                        "[_geo_distance] sort requires exactly one point "
                        "field with an origin")
                geo_field, origin = field_entries[0]
                lat, lon = parse_geo_point(origin)
                keys.append(SortKey("_geo_distance",
                                    spec.get("order", "asc"),
                                    geo_field=geo_field, geo_lat=lat,
                                    geo_lon=lon,
                                    geo_unit=spec.get("unit", "m")))
                continue
        keys.append(SortKey(field_name, order))
    return keys


def _needs_max_score(sort_spec) -> bool:
    return sort_spec is None


def _primary_sort_key(ctx: SegmentContext, scores, sort_spec) -> jnp.ndarray:
    """Device key column for top-k (max-selected): negate for ascending."""
    if sort_spec is None or sort_spec[0].field == "_score":
        key = scores
        if sort_spec and sort_spec[0].order == "asc":
            key = -key
        return key
    sk = sort_spec[0]
    if sk.field == "_doc":
        key = -jnp.arange(ctx.n_docs_padded, dtype=jnp.float32)
        return key if sk.order == "asc" else -key
    if sk.field == "_geo_distance":
        from elasticsearch_tpu.common.geo import haversine_meters
        lat, miss = ctx.numeric_column(f"{sk.geo_field}.lat")
        lon, _ = ctx.numeric_column(f"{sk.geo_field}.lon")
        dist = haversine_meters(lat, lon, sk.geo_lat, sk.geo_lon, xp=jnp)
        missing_val = jnp.float32(np.finfo(np.float32).max if sk.order == "asc"
                                  else np.finfo(np.float32).min)
        key = jnp.where(miss, missing_val, dist)
        return -key if sk.order == "asc" else key
    if (ctx.segment.numerics.get(sk.field) is None
            and ctx.segment.keywords.get(sk.field) is not None):
        # keyword sort: segment-local ordinals (lexicographic within the
        # segment; merge re-sorts winners by term host-side)
        col, miss = ctx.keyword_ord_column(sk.field)
    else:
        col, miss = ctx.numeric_column(sk.field)
    missing_val = jnp.float32(np.finfo(np.float32).max if sk.order == "asc"
                              else np.finfo(np.float32).min)
    key = jnp.where(miss, missing_val, col)
    return -key if sk.order == "asc" else key


def _sort_values(searcher, seg: Segment, docid: int, score: float,
                 sort_spec) -> Tuple:
    if sort_spec is None:
        return ()
    out = []
    for sk in sort_spec:
        if sk.field == "_score":
            out.append(score)
        elif sk.field == "_doc":
            out.append(docid)
        elif sk.field == "_geo_distance":
            from elasticsearch_tpu.common.geo import (haversine_meters,
                                                      meters_to_unit)
            nlat = seg.numerics.get(f"{sk.geo_field}.lat")
            nlon = seg.numerics.get(f"{sk.geo_field}.lon")
            v = None
            if nlat is not None and not nlat.missing[docid]:
                meters = float(haversine_meters(
                    float(nlat.values[docid]), float(nlon.values[docid]),
                    sk.geo_lat, sk.geo_lon))
                v = meters_to_unit(meters, sk.geo_unit)
            out.append(v)
        else:
            nv = seg.numerics.get(sk.field)
            v = None
            if nv is not None and not nv.missing[docid]:
                v = float(nv.values[docid])
            elif nv is None:
                kv = seg.keywords.get(sk.field)
                if kv is not None:
                    lo, hi = kv.offsets[docid], kv.offsets[docid + 1]
                    if hi > lo:
                        v = kv.terms[kv.all_ords[lo]]
            out.append(v)
    return tuple(out)


def _keyword_after_masks(ctx, field: str, term, order: str):
    """(strictly_after, tied) masks for a string cursor value in THIS
    segment's ordinal space (keyword sorts; terms are segment-local so the
    cursor term is re-ranked per segment via binary search). A None cursor
    term means the cursor doc had no value — missing sorts last, so only
    later missing docs remain."""
    import bisect

    real = ctx.all_true()
    kv = ctx.segment.keywords.get(field)
    if kv is None:
        # segment lacks the field entirely: every doc is "missing"
        if term is None:
            return jnp.zeros(ctx.n_docs_padded, bool), real
        return real, jnp.zeros(ctx.n_docs_padded, bool)
    col, miss = ctx.keyword_ord_column(field)
    if term is None:
        return jnp.zeros(ctx.n_docs_padded, bool), real & miss
    r_left = bisect.bisect_left(kv.terms, term)
    r_right = bisect.bisect_right(kv.terms, term)
    if order == "asc":
        strictly = (real & ~miss & (col >= r_right)) | (real & miss)
    else:
        strictly = (real & ~miss & (col < r_left)) | (real & miss)
    tied = (real & ~miss & (col == r_left)) if r_left < r_right else (
        jnp.zeros(ctx.n_docs_padded, bool))
    return strictly, tied


def _primary_is_keyword(searcher, sort_spec) -> bool:
    if sort_spec is None:
        return False
    f = sort_spec[0].field
    if f in ("_score", "_doc", "_geo_distance"):
        return False
    return any(seg.numerics.get(f) is None
               and seg.keywords.get(f) is not None
               for seg in searcher.segments)


def _host_sort_cmp(a: DocAddress, b: DocAddress, sort_spec) -> int:
    """Full-precision winner comparison (numbers AND strings); missing
    values sort last regardless of direction, matching the device keys."""
    for sk, x, y in zip(sort_spec, a.sort_values, b.sort_values):
        if x == y:
            continue
        if x is None:
            return 1
        if y is None:
            return -1
        c = -1 if x < y else 1
        return c if sk.order == "asc" else -c
    if a.segment_idx != b.segment_idx:
        return -1 if a.segment_idx < b.segment_idx else 1
    return -1 if a.docid < b.docid else (1 if a.docid > b.docid else 0)


def _search_after_mask(ctx: SegmentContext, scores, sort_spec,
                       after: List[Any]) -> jnp.ndarray:
    """Docs strictly after the cursor in sort order (ref: searchafter/
    SearchAfterBuilder). With a single non-unique sort key, docs tied with
    the cursor are excluded — as in ES, reliable pagination requires a
    trailing ``_doc`` (or unique field) tiebreaker, which IS applied here
    when the sort spec's last key is ``_doc`` and ``after`` carries its
    value."""
    # strictly-after on the primary key
    if sort_spec is None or sort_spec[0].field == "_score":
        after_val = float(after[0])
        primary = scores
        strictly = primary < after_val
        tied = primary == after_val
    else:
        sk = sort_spec[0]
        if sk.field == "_geo_distance":
            from elasticsearch_tpu.common.geo import (haversine_meters,
                                                      meters_to_unit)
            lat, miss = ctx.numeric_column(f"{sk.geo_field}.lat")
            lon, _ = ctx.numeric_column(f"{sk.geo_field}.lon")
            # sort values travel in the requested unit; compare in meters
            col = haversine_meters(lat, lon, sk.geo_lat, sk.geo_lon, xp=jnp)
            after_val = float(after[0]) / meters_to_unit(1.0, sk.geo_unit)
        elif (ctx.segment.numerics.get(sk.field) is None
                and ctx.segment.keywords.get(sk.field) is not None):
            # keyword search_after: compare the string cursor value
            strictly, tied = _keyword_after_masks(
                ctx, sk.field, after[0], sk.order)
            col = None
        else:
            col, miss = ctx.numeric_column(sk.field)
            after_val = float(after[0])
        if col is not None:
            if sk.order == "asc":
                strictly = (~miss) & (col > after_val)
                tied = (~miss) & (col == after_val)
            else:
                strictly = (~miss) & (col < after_val)
                tied = (~miss) & (col == after_val)
    if (sort_spec is not None and len(sort_spec) >= 2
            and sort_spec[-1].field == "_doc" and len(after) >= 2):
        docids = jnp.arange(ctx.n_docs_padded)
        return strictly | (tied & (docids > int(after[-1])))
    return strictly


# ---------------------------------------------------------------------------
# fetch helpers
# ---------------------------------------------------------------------------

def _filter_source(source: Dict[str, Any], source_filter) -> Optional[Dict[str, Any]]:
    """_source: true | false | "field" | [fields] | {includes, excludes}
    (ref: search/fetch/subphase/FetchSourcePhase)."""
    if source_filter is True:
        return source
    if source_filter is False:
        return None
    includes: List[str] = []
    excludes: List[str] = []
    if isinstance(source_filter, str):
        includes = [source_filter]
    elif isinstance(source_filter, list):
        includes = source_filter
    elif isinstance(source_filter, dict):
        includes = source_filter.get("includes", source_filter.get("include", []))
        excludes = source_filter.get("excludes", source_filter.get("exclude", []))
        if isinstance(includes, str):
            includes = [includes]
        if isinstance(excludes, str):
            excludes = [excludes]

    def match(path: str, patterns: List[str]) -> bool:
        import fnmatch
        return any(fnmatch.fnmatch(path, p) or path.startswith(p + ".")
                   for p in patterns)

    def walk(obj, prefix=""):
        if not isinstance(obj, dict):
            return obj
        out = {}
        for k, v in obj.items():
            path = f"{prefix}{k}"
            if excludes and match(path, excludes):
                continue
            if isinstance(v, dict):
                sub = walk(v, f"{path}.")
                if sub:
                    out[k] = sub
            else:
                if includes and not match(path, includes):
                    continue
                out[k] = v
        return out

    if includes:
        # keep parents of included leaves
        def walk_inc(obj, prefix=""):
            if not isinstance(obj, dict):
                return obj
            out = {}
            for k, v in obj.items():
                path = f"{prefix}{k}"
                if excludes and match(path, excludes):
                    continue
                if isinstance(v, dict):
                    sub = walk_inc(v, f"{path}.")
                    if sub:
                        out[k] = sub
                elif match(path, includes):
                    out[k] = v
            return out
        return walk_inc(source)
    return walk(source)


def _get_path(source: Dict[str, Any], path: str):
    node = source
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node


def _collect_terms(query: Optional[QueryBuilder],
                   mapper: MapperService) -> Dict[str, set]:
    """Query terms per field, for highlighting."""
    from elasticsearch_tpu.search import queries as q

    out: Dict[str, set] = {}

    def visit(node):
        if node is None:
            return
        if isinstance(node, q.MatchQuery):
            ft = mapper.field_type(node.field)
            name = getattr(ft, "search_analyzer_name", "standard")
            analyzer = (mapper.analysis.get(name) if mapper.analysis.has(name)
                        else mapper.analysis.default)
            out.setdefault(node.field, set()).update(analyzer.terms(node.query))
        elif isinstance(node, q.MultiMatchQuery):
            for f in node.fields:
                visit(q.MatchQuery(f, node.query))
        elif isinstance(node, q.TermQuery):
            out.setdefault(node.field, set()).add(str(node.value))
        elif isinstance(node, q.TermsQuery):
            out.setdefault(node.field, set()).update(str(v) for v in node.values)
        elif isinstance(node, q.BoolQuery):
            for clause in node.must + node.should + node.filter:
                visit(clause)
        elif isinstance(node, (q.ConstantScoreQuery,)):
            visit(node.filter_query)
        elif isinstance(node, q.DisMaxQuery):
            for sub in node.queries:
                visit(sub)
        elif isinstance(node, q.ScriptScoreQuery):
            visit(node.query)
        elif isinstance(node, q.BoostingQuery):
            visit(node.positive)
        elif isinstance(node, q.FunctionScoreQuery):
            visit(node.query)

    visit(query)
    return out
