"""Search service: request lifecycle over one or more indices.

Mirrors both sides of the reference's search stack collapsed into one
process (ref: action/search/TransportSearchAction.java:216-240 — resolve
indices, fan out; search/SearchService.java:136,230,293 — context
lifecycle with keepalive reaper, scroll contexts): parses the request
body, fans out to every shard searcher, merges per-shard top-k
(SearchPhaseController-style), runs the fetch phase on winners, and
manages scroll contexts with expiry.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    SearchContextMissingException,
    SearchPhaseExecutionException,
    TaskCancelledException,
    error_type_of,
)
import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.common.settings import parse_time_value
from elasticsearch_tpu.index.service import IndexService, IndicesService
from elasticsearch_tpu.search.queries import MatchAllQuery, parse_query
from elasticsearch_tpu.search.searcher import (
    DocAddress,
    QueryResult,
    ShardSearcher,
)

DEFAULT_SIZE = 10


def _knn_clauses(knn) -> List[Dict[str, Any]]:
    """Top-level knn spec(s) → knn query clauses; the top-level `k`
    becomes the clause's candidate cut (KnnQuery keeps the k nearest
    per shard, the gather half of ES's gather-then-merge kNN)."""
    specs = knn if isinstance(knn, list) else [knn]
    out = []
    for spec in specs:
        clause = {k: v for k, v in spec.items() if k != "k"}
        if spec.get("k") is not None:
            clause["k"] = int(spec["k"])
        out.append({"knn": clause})
    return out


def _merge_knn_into_query(body: Dict[str, Any]) -> Dict[str, Any]:
    """Top-level `knn` sections without rrf combine with the query by
    score-sum (the modern ES hybrid default): bool should of all parts."""
    body = dict(body)
    clauses = _knn_clauses(body.pop("knn"))
    q = body.get("query")
    if q is None and len(clauses) == 1:
        body["query"] = clauses[0]
    else:
        body["query"] = {"bool": {
            "should": ([q] if q is not None else []) + clauses}}
    return body


class _CoordinatorRewriteContext:
    """A searcher-shaped view over every shard, for coordinator rewrites
    (ref: Rewriteable's coordinator-rewrite stage): ``segments`` spans all
    shards so doc lookups resolve wherever the doc lives, and stats are
    index-wide."""

    def __init__(self, shard_searchers: List[ShardSearcher]):
        from elasticsearch_tpu.search.context import ShardStats
        self.segments = [seg for s in shard_searchers for seg in s.segments]
        self.mapper = shard_searchers[0].mapper
        self.stats = ShardStats(self.segments)


def _collect_decorators(query, out=None, seen=None):
    """Walk a parsed query tree for queries exposing add_hit_fields."""
    from elasticsearch_tpu.search.queries import QueryBuilder
    if out is None:
        out, seen = [], set()
    if id(query) in seen:
        return out
    seen.add(id(query))
    if hasattr(query, "add_hit_fields"):
        out.append(query)
    for v in vars(query).values():
        if isinstance(v, QueryBuilder):
            _collect_decorators(v, out, seen)
        elif isinstance(v, (list, tuple)):
            for item in v:
                if isinstance(item, QueryBuilder):
                    _collect_decorators(item, out, seen)
    return out


def _doc_field_value(searcher: ShardSearcher, d: DocAddress, field: str):
    """First doc-value for a doc (collapse keys, missing → None)."""
    seg = searcher.segments[d.segment_idx]
    kv = seg.keywords.get(field)
    if kv is None:
        # dynamic text fields carry their doc values on .keyword
        kv = seg.keywords.get(f"{field}.keyword")
    if kv is not None:
        vs = kv.get(d.docid)
        if vs:
            return vs[0]
    nv = seg.numerics.get(field)
    if nv is not None and not nv.missing[d.docid]:
        return float(nv.values[d.docid])
    return None


@dataclass
class ScrollContext:
    """A pinned point-in-time over shard snapshots + continuation cursor
    (ref: the reference's scroll contexts pin a reader + lastEmittedDoc,
    SURVEY.md §5.7)."""

    scroll_id: str
    index_names: List[str]
    searchers: List[Tuple[str, ShardSearcher]]
    body: Dict[str, Any]
    keep_alive: float
    expires_at: float
    # per (index, shard) continuation: (last_key, last_seg, last_docid)
    cursors: Dict[int, Tuple[float, int, int]] = field(default_factory=dict)
    # total hits from the initial page, reported on every scroll page
    # (ref: scroll responses carry the full total throughout)
    total_hits: int = 0


@dataclass
class PitContext:
    """An open point-in-time: pinned shard snapshots, no cursor."""

    id: str
    index_names: List[str]
    searchers: List[Tuple[str, ShardSearcher]]
    keep_alive: float
    expires_at: float


class SearchService:
    REQUEST_CACHE_MAX_ENTRIES = 256

    def __init__(self, indices_service: IndicesService):
        self.indices_service = indices_service
        # node telemetry bundle (metrics + tracer), wired by Node; None
        # keeps every instrumented site a single branch
        self.telemetry = None
        # cluster-settings provider (Node wires this to its persistent
        # settings overlay): seeds the allow_partial_search_results
        # default like the distributed coordinator does (ref:
        # SearchService.DEFAULT_ALLOW_PARTIAL_SEARCH_RESULTS)
        self.cluster_settings = lambda: {}
        self._scrolls: Dict[str, ScrollContext] = {}
        self._pits: Dict[str, PitContext] = {}
        self._lock = threading.Lock()
        self.slowlog_recent: List[Dict[str, Any]] = []
        # shard request cache (ref: indices/IndicesRequestCache.java:69 —
        # keyed by reader + request bytes; here: per-shard engine epochs
        # + the canonical request body, so any refresh naturally misses).
        # Caches size=0 (agg/count-style) responses only, like the
        # reference's default policy. LRU-bounded.
        from collections import OrderedDict
        self._request_cache: "OrderedDict[tuple, Dict[str, Any]]" = (
            OrderedDict())
        self.request_cache_stats = {"hit_count": 0, "miss_count": 0}
        # continuous batching of plan-path launches: concurrent requests
        # with the same kernel shape share one vmapped device launch
        # (SURVEY.md §7 hard part 5; search/batching.py)
        from elasticsearch_tpu.search.batching import (KnnBatcher,
                                                       PlanBatcher)
        self.plan_batcher = PlanBatcher()
        self.knn_batcher = KnnBatcher()
        # mesh-sharded execution: multi-shard indices with enough devices
        # run one SPMD fan-out/merge program instead of the per-shard loop
        # (ref: TransportSearchAction scatter-gather → shard_map +
        # all_gather; parallel/mesh_executor.py). Ineligible shapes fall
        # back to the loop with a typed counter — never an error.
        from elasticsearch_tpu.parallel.mesh_executor import (
            MeshSearchBackend,
        )
        self.mesh_executor = MeshSearchBackend()
        import os as _os
        if _os.environ.get("ESTPU_REPLICA_BATCH") == "1":
            # replica-axis cohort fan-out: continuous-batching launches
            # split their query axis across the device mesh (opt-in —
            # single-accelerator deployments gain nothing from it)
            self.plan_batcher.mesh = self.mesh_executor

    # --------------------------------------------------------------- PIT
    def open_pit(self, index_expression: str, keep_alive: str) -> str:
        names = self.indices_service.resolve(index_expression)
        searchers: List[Tuple[str, ShardSearcher]] = []
        for name in names:
            idx = self.indices_service.get(name)
            for s in idx.shard_searchers():
                searchers.append((name, s))
        ka = parse_time_value(keep_alive, "keep_alive")
        pit = PitContext(id=uuid.uuid4().hex, index_names=names,
                         searchers=searchers, keep_alive=ka,
                         expires_at=time.time() + ka)
        with self._lock:
            self._pits[pit.id] = pit
        return pit.id

    def close_pit(self, pit_id: str) -> bool:
        with self._lock:
            return self._pits.pop(pit_id, None) is not None

    def open_pit_count(self) -> int:
        with self._lock:
            return len(self._pits)

    # ------------------------------------------------------------ public
    def _default_tenant(self, index_expression: str) -> Optional[str]:
        """The `index.tenant.default` setting of a concretely named
        index (None for patterns/unknown — only an exact name can carry
        a default)."""
        if self.indices_service.has(index_expression):
            return self.indices_service.get(index_expression).settings.get(
                "index.tenant.default")
        return None

    def search(self, index_expression: str, body: Dict[str, Any],
               scroll: Optional[str] = None, task=None,
               search_type: Optional[str] = None) -> Dict[str, Any]:
        from elasticsearch_tpu.telemetry import context as _telectx
        from elasticsearch_tpu.telemetry.workload import (
            classify_search_request)
        tenant = _telectx.current_tenant()
        if tenant is None:
            # precedence: header (already ambient) > body > index
            # default; a late resolution re-enters under the tenant so
            # batcher entries / flight events / profile trees see it
            resolved = (body or {}).get("tenant") \
                or self._default_tenant(index_expression)
            if resolved is not None:
                with _telectx.activate_tenant(str(resolved)):
                    return self.search(index_expression, body, scroll,
                                       task, search_type)
        wclass = _telectx.current_workload_class()
        if wclass is None:
            # precedence: header (already ambient) > request shape; the
            # re-entry makes the class ambient for the same reasons as
            # tenant above
            with _telectx.activate_workload_class(
                    classify_search_request(body, scroll)):
                return self.search(index_expression, body, scroll,
                                   task, search_type)
        tele = self.telemetry
        if tele is None:
            return self._search(index_expression, body, scroll, task,
                                search_type)
        # node search metrics cover EVERY outcome: cache hits (which
        # skip _after_search), failures, and the success paths
        tele.metrics.inc("search.requests")
        t0 = tele.metrics.clock()
        try:
            response = self._search(index_expression, body, scroll,
                                    task, search_type)
        except Exception:
            took = (tele.metrics.clock() - t0) * 1000.0
            tele.metrics.inc("search.failed")
            tele.metrics.observe("search.latency", took)
            tele.tenants.record_search(tenant, took, failed=True)
            tele.workload.record_search(wclass, took, failed=True)
            raise
        took = (tele.metrics.clock() - t0) * 1000.0
        tele.metrics.observe("search.latency", took)
        tele.tenants.record_search(
            tenant, took,
            shards=response.get("_shards", {}).get("total", 0))
        tele.workload.record_search(wclass, took)
        if response.get("timed_out") or \
                response.get("_shards", {}).get("failed"):
            tele.metrics.inc("search.partial_results")
        return response

    def _search(self, index_expression: str, body: Dict[str, Any],
                scroll: Optional[str] = None, task=None,
                search_type: Optional[str] = None) -> Dict[str, Any]:
        start = time.monotonic()
        pit_spec = (body or {}).get("pit")
        if pit_spec is not None:
            if index_expression not in ("_all", "*", ""):
                raise IllegalArgumentException(
                    "[indices] cannot be used with point in time")
            # search against a pinned point-in-time reader set (ref:
            # x-pack point-in-time / ReaderContext keepalive)
            self._reap()
            with self._lock:
                pit = self._pits.get(pit_spec.get("id"))
            if pit is None:
                raise SearchContextMissingException(pit_spec.get("id", "?"))
            if pit_spec.get("keep_alive"):
                pit.keep_alive = parse_time_value(pit_spec["keep_alive"],
                                                  "keep_alive")
            pit.expires_at = time.time() + pit.keep_alive
            names, searchers = pit.index_names, pit.searchers
            cache_body_key = None
        else:
            names = self.indices_service.resolve(index_expression)

            # ---- shard request cache probe (ref: IndicesRequestCache):
            # the cache directive leaves the body (it is not part of the
            # query); the probe runs BEFORE searcher acquisition so hits
            # skip snapshot/DFS setup entirely. Cache state stays LOCAL
            # to this request — the service is shared across threads.
            cache_body_key = None
            if body and "request_cache" in body:
                body = dict(body)
                use_flag = body.pop("request_cache")
            else:
                use_flag = None
            if (scroll is None
                    and int((body or {}).get("size",
                                             DEFAULT_SIZE)) == 0
                    and use_flag is not False):
                cache_body_key = json.dumps(
                    body, sort_keys=True, default=str)
                live_epochs = []
                for name in names:
                    live_epochs.extend(
                        sh.epoch for sh in
                        self.indices_service.get(name).shards)
                probe_key = (tuple(names), tuple(live_epochs),
                             self._cache_identity(names), search_type,
                             cache_body_key)
                with self._lock:
                    cached = self._request_cache.get(probe_key)
                    if cached is not None:
                        self._request_cache.move_to_end(probe_key)
                        self.request_cache_stats["hit_count"] += 1
                        import copy as _copy
                        response = _copy.deepcopy(cached)
                        response["took"] = int(
                            (time.monotonic() - start) * 1000)
                        return response
                    self.request_cache_stats["miss_count"] += 1

            searchers = []
            for name in names:
                idx = self.indices_service.get(name)
                for s in idx.shard_searchers():
                    searchers.append((name, s))

        if search_type == "dfs_query_then_fetch" and len(searchers) > 1:
            # DFS phase: aggregate term statistics over EVERY shard so all
            # shards score with identical IDF (ref: search/dfs/DfsPhase +
            # AggregatedDfs). PIT/scroll searchers are LONG-lived, so the
            # swap happens on per-request shallow copies, never in place.
            import copy
            from elasticsearch_tpu.search.context import ShardStats
            global_stats = ShardStats(
                [seg for _, s in searchers for seg in s.segments])
            swapped = []
            for name, s in searchers:
                s2 = copy.copy(s)
                s2.stats = global_stats
                # the mesh backend implements the DEFAULT per-shard-IDF
                # semantics (bind_mesh reads each shard's own stats) —
                # dfs-swapped searchers must take the per-shard loop,
                # which scores with these global stats everywhere
                s2.dfs_global_stats = True
                swapped.append((name, s2))
            searchers = swapped

        # ---- hybrid retrieval (net-new surface, BASELINE.md config 5):
        # top-level `knn` sections + optional `rank.rrf` fusion
        rank_spec = (body or {}).get("rank")
        if rank_spec is not None and not isinstance(rank_spec, dict):
            raise IllegalArgumentException("[rank] must be an object")
        if rank_spec and rank_spec.get("rrf") is not None:
            if scroll is not None:
                raise IllegalArgumentException(
                    "[rank] cannot be used with [scroll]")
            response = self._rrf_search(searchers, body, task)
            response["took"] = int((time.monotonic() - start) * 1000)
            self._after_search(names, response["took"], body,
                               response)
            return response
        if body and body.get("knn") is not None:
            # pure top-level kNN with an ids+scores-only response rides
            # the batched cohort kernel (BASELINE config 4's serving
            # shape: {"knn": ..., "_source": false}); anything richer
            # merges into the query and takes the dense path
            pure = (self._pure_knn_search(searchers, body)
                    if scroll is None else None)
            if pure is not None:
                pure["took"] = int((time.monotonic() - start) * 1000)
                self._after_search(names, pure["took"], body, pure)
                return pure
            body = _merge_knn_into_query(body)

        scroll_ctx = None
        if scroll is not None:
            keep_alive = parse_time_value(scroll, "scroll")
            scroll_ctx = ScrollContext(
                scroll_id=uuid.uuid4().hex, index_names=names,
                searchers=searchers, body=body, keep_alive=keep_alive,
                expires_at=time.time() + keep_alive)
            with self._lock:
                self._scrolls[scroll_ctx.scroll_id] = scroll_ctx

        response = self._execute(searchers, body, scroll_ctx=scroll_ctx,
                                 task=task)
        response["took"] = int((time.monotonic() - start) * 1000)
        if scroll_ctx is not None:
            response["_scroll_id"] = scroll_ctx.scroll_id
        if pit_spec is not None:
            # ES echoes the (possibly re-keyed) pit id on every PIT
            # search; the cluster coordinator path stamps it too
            response["pit_id"] = pit.id
        if cache_body_key is not None:
            # store under the SNAPSHOT epochs the data was read at (a
            # concurrent refresh between probe and acquire must not file
            # stale data under the fresh key)
            snap_epochs = tuple(getattr(s, "epoch", -1)
                                for _, s in searchers)
            store_key = (tuple(names), snap_epochs,
                         self._cache_identity(names), search_type,
                         cache_body_key)
            import copy as _copy
            with self._lock:
                self._request_cache[store_key] = _copy.deepcopy(response)
                while len(self._request_cache) > \
                        self.REQUEST_CACHE_MAX_ENTRIES:
                    self._request_cache.popitem(last=False)
        self._after_search(names, response["took"], body,
                               response)
        return response

    def _cache_identity(self, names: List[str]) -> tuple:
        """Index identity (creation dates): epochs restart per Engine, so
        a deleted+recreated index must never hit old entries."""
        out = []
        for name in names:
            if self.indices_service.has(name):
                out.append(self.indices_service.get(name).settings.get(
                    "index.creation_date"))
            else:
                out.append(None)
        return tuple(out)

    def _pure_knn_search(self, searchers, body: Dict[str, Any]):
        """Body-level gate + execution for a batched pure-kNN search
        (single top-level knn section, no query, response carries only
        ids+scores). Returns a full response dict, or None → caller
        takes the dense merged-query path (which supports everything)."""
        if body.get("query") is not None \
                or body.get("_source", True) is not False:
            return None
        if any(body.get(x) for x in (
                "aggs", "aggregations", "sort", "post_filter",
                "highlight", "min_score", "search_after", "fields",
                "suggest", "collapse", "rescore", "slice",
                "track_total_hits", "docvalue_fields",
                "stored_fields", "script_fields", "pit",
                "version", "seq_no_primary_term", "profile",
                "terminate_after", "explain")):
            return None
        if int(body.get("from", 0) or 0) != 0:
            return None
        clauses = _knn_clauses(body["knn"])
        if len(clauses) != 1:
            return None
        spec = clauses[0]["knn"]
        size = int(body.get("size", DEFAULT_SIZE))
        # the candidate cut mirrors KnnQuery: k or num_candidates
        cut = spec.get("k") or spec.get("num_candidates")
        window = min(int(cut), size) if cut else size
        hits = self._knn_branch_hits(searchers, spec, window)
        if hits is None:
            return None
        name, searcher = searchers[0]
        seg = searcher.segments[0]
        field = spec.get("field")
        vv = seg.vectors.get(field)
        n_match = 0
        if vv is not None:
            live_ver = getattr(seg, "live_version", None)
            cached = getattr(vv, "_n_live_value", None)
            if cached is not None and cached[0] == live_ver:
                n_match = cached[1]
            else:
                hv = vv.has_value
                n_match = int(np.count_nonzero(
                    hv & seg.live[: len(hv)]))
                try:
                    vv._n_live_value = (live_ver, n_match)
                except Exception:
                    pass
        total = min(int(cut), n_match) if cut else n_match
        return {
            "timed_out": False,
            "_shards": {"total": 1, "successful": 1, "skipped": 0,
                        "failed": 0},
            "hits": {"total": {"value": total, "relation": "eq"},
                     "max_score": (hits[0]["_score"] if hits else None),
                     "hits": hits},
        }

    def _knn_branch_hits(self, searchers, spec: Dict[str, Any],
                         window: int):
        """Serve a pure top-level kNN branch through the batched cohort
        kernel (batching.KnnBatcher → ops.vector.knn_nominate_batch):
        concurrent hybrid requests share one matmul+top-k launch
        instead of one dense matvec chain each. Returns the branch's
        hit dicts, or None when the shape isn't batchable (filters,
        multi-shard, multi-segment, missing field) — the caller falls
        back to the dense per-request path, which handles everything."""
        if spec.get("filter") is not None or len(searchers) != 1:
            return None
        name, searcher = searchers[0]
        if (not hasattr(searcher, "_contexts")
                or len(getattr(searcher, "segments", ())) != 1):
            return None
        try:
            ctx = searcher._contexts()[0]
        except Exception:
            return None
        field = spec.get("field")
        dv = ctx.device.vectors.get(field) if field else None
        if dv is None or dv.similarity not in ("cosine", "dot_product",
                                               "l2_norm"):
            return None
        qvec = np.asarray(spec.get("query_vector", ()), np.float32)
        if qvec.ndim != 1 or not qvec.size:
            return None
        from elasticsearch_tpu.search.batching import _CUT_BUCKETS
        k = spec.get("k")
        nc = spec.get("num_candidates")
        cut = min(int(k or nc or window), window)
        if dv.vectors.dtype != jnp.float32:
            # quantized slab: nominate the full num_candidates before
            # the exact re-rank, then trim to the window
            cut = max(cut, min(int(nc or 3 * (k or 1000)),
                               ctx.n_docs_padded))
        if cut > _CUT_BUCKETS[-1]:
            # beyond the batched kernel's bucket table the launch would
            # silently truncate — the dense path handles any cut
            return None
        seg = ctx.segment
        host_vv = seg.vectors.get(field) if hasattr(seg, "vectors") \
            else None
        scores, ids = self.knn_batcher.topk(
            dv, ctx.device.live, qvec, cut,
            host_vectors=host_vv.vectors if host_vv is not None
            else None)
        n_docs = seg.n_docs
        hits = []
        for s, i in zip(scores[:window], ids[:window]):
            if i < 0 or i >= n_docs or not np.isfinite(s):
                continue
            hits.append({"_index": name, "_id": seg.stored.ids[int(i)],
                         "_score": float(s)})
        return hits

    def _rrf_search(self, searchers, body: Dict[str, Any],
                    task) -> Dict[str, Any]:
        """Reciprocal rank fusion over the query and knn branches
        (net-new surface per BASELINE.md — the reference has no RRF at
        this version; semantics follow the modern `rank.rrf` API:
        score(d) = Σ_branches 1 / (rank_constant + rank_d))."""
        rrf = (body.get("rank") or {}).get("rrf") or {}
        k_const = int(rrf.get("rank_constant", 60))
        size = int(body.get("size", DEFAULT_SIZE))
        from_ = int(body.get("from", 0))
        window = int(rrf.get("window_size",
                             rrf.get("rank_window_size",
                                     max(100, size + from_))))
        branches: List[Dict[str, Any]] = []
        if body.get("query") is not None:
            branches.append({"query": body["query"]})
        knn = body.get("knn")
        if knn is not None:
            branches.extend({"query": c} for c in _knn_clauses(knn))
        if not branches:
            raise IllegalArgumentException(
                "rrf requires at least one of [query, knn]")
        passthrough = {k: v for k, v in body.items()
                       if k in ("_source", "fields", "post_filter",
                                "min_score", "track_total_hits",
                                "highlight")}
        scores: Dict[Tuple[str, str], float] = {}
        best_hit: Dict[Tuple[str, str], Dict[str, Any]] = {}
        truncated = False
        aggregations = None
        wants_source = passthrough.get("_source", True) is not False
        for bi, br in enumerate(branches):
            # pure-knn branches ride the batched cohort kernel when the
            # response needs only ids+scores from them (the RRF fusion
            # itself) — everything else falls through to _execute
            hits = None
            if (isinstance(br.get("query"), dict)
                    and set(br["query"].keys()) == {"knn"}
                    and not wants_source
                    and not any(passthrough.get(x) for x in
                                ("highlight", "post_filter", "min_score",
                                 "fields"))
                    and not (bi == 0 and ("aggs" in body
                                          or "aggregations" in body))):
                hits = self._knn_branch_hits(searchers,
                                             br["query"]["knn"], window)
            if hits is None:
                sub = {**passthrough, **br, "size": window}
                if bi == 0:
                    # aggs compute once, over the first (query) branch
                    for agg_key in ("aggs", "aggregations"):
                        if agg_key in body:
                            sub[agg_key] = body[agg_key]
                r = self._execute(searchers, sub, task=task)
                if bi == 0 and "aggregations" in r:
                    aggregations = r["aggregations"]
                hits = r["hits"]["hits"]
            if len(hits) >= window:
                truncated = True
            for rank_i, h in enumerate(hits):
                key = (h["_index"], h["_id"])
                scores[key] = scores.get(key, 0.0) + 1.0 / (
                    k_const + rank_i + 1)
                best_hit.setdefault(key, h)
        order = sorted(scores, key=lambda key: (-scores[key], key))
        hits = []
        for key in order[from_: from_ + size]:
            h = dict(best_hit[key])
            h["_score"] = scores[key]
            hits.append(h)
        out = {
            "timed_out": False,
            "_shards": {"total": len(searchers),
                        "successful": len(searchers),
                        "skipped": 0, "failed": 0},
            "hits": {"total": {"value": len(scores),
                               "relation": "gte" if truncated else "eq"},
                     "max_score": hits[0]["_score"] if hits else None,
                     "hits": hits},
        }
        if aggregations is not None:
            out["aggregations"] = aggregations
        return out

    def _after_search(self, names: List[str], took_ms: int,
                      body: Dict[str, Any],
                      response: Optional[Dict[str, Any]] = None):
        """Post-search hooks: frozen-index HBM eviction + slow log
        (search metrics live in the search() wrapper, which also sees
        cache hits and failures)."""
        for name in names:
            if not self.indices_service.has(name):
                continue
            idx = self.indices_service.get(name)
            if idx.is_frozen:
                # frozen: no device-resident state between searches (ref:
                # FrozenEngine per-search readers → per-search HBM)
                idx.device_cache.evict(idx._known_seg_names)
        from elasticsearch_tpu.search.slowlog import (
            record_search_slowlog,
            slowest_stage_summary,
        )
        from elasticsearch_tpu.telemetry import context as _telectx
        from elasticsearch_tpu.telemetry import flightrecorder as _fl
        ambient = _telectx.current()
        trace_id = ambient.trace_id if ambient is not None else None
        fr = _fl.current()
        record_search_slowlog(
            lambda n: (self.indices_service.get(n).settings
                       if self.indices_service.has(n) else None),
            names, took_ms, body, self.slowlog_recent,
            trace_id=trace_id,
            slowest_stage=slowest_stage_summary(response),
            opaque_id=_telectx.current_opaque_id(),
            tenant=_telectx.current_tenant(),
            workload_class=_telectx.current_workload_class(),
            flight=(fr.summary_for_trace(trace_id)
                    if fr is not None and trace_id else None))

    def scroll(self, scroll_id: str, scroll: Optional[str] = None) -> Dict[str, Any]:
        start = time.monotonic()
        self._reap()
        with self._lock:
            ctx = self._scrolls.get(scroll_id)
        if ctx is None:
            raise SearchContextMissingException(scroll_id)
        if scroll is not None:
            ctx.keep_alive = parse_time_value(scroll, "scroll")
        ctx.expires_at = time.time() + ctx.keep_alive
        response = self._execute(ctx.searchers, ctx.body, scroll_ctx=ctx,
                                 continuing=True)
        response["took"] = int((time.monotonic() - start) * 1000)
        response["_scroll_id"] = scroll_id
        self._after_search(ctx.index_names, response["took"], ctx.body,
                           response)
        return response

    def scan(self, index_expression: str, body: Dict[str, Any],
             page: int = 1000):
        """Yield EVERY matching hit via scroll paging (the scan pattern
        reindex/datafeeds/enrich use, ref: reindex's ClientScrollableHitSource
        — no silent size cap)."""
        body = dict(body or {})
        body["size"] = page
        r = self.search(index_expression, body, scroll="5m")
        sid = r["_scroll_id"]
        try:
            while True:
                hits = r["hits"]["hits"]
                if not hits:
                    return
                for h in hits:
                    yield h
                r = self.scroll(sid)
        finally:
            self.clear_scroll([sid])

    def clear_scroll(self, scroll_ids: List[str]) -> int:
        freed = 0
        with self._lock:
            if scroll_ids == ["_all"]:
                freed = len(self._scrolls)
                self._scrolls.clear()
            else:
                for sid in scroll_ids:
                    if self._scrolls.pop(sid, None) is not None:
                        freed += 1
        return freed

    def open_scroll_count(self) -> int:
        with self._lock:
            return len(self._scrolls)

    def _reap(self):
        now = time.time()
        with self._lock:
            for sid in [s for s, c in self._scrolls.items() if c.expires_at < now]:
                del self._scrolls[sid]
            for pid in [p for p, c in self._pits.items() if c.expires_at < now]:
                del self._pits[pid]

    # ---------------------------------------------------------- internal
    def _execute(self, searchers: List[Tuple[str, ShardSearcher]],
                 body: Dict[str, Any], scroll_ctx: Optional[ScrollContext] = None,
                 continuing: bool = False, task=None) -> Dict[str, Any]:
        tele = self.telemetry
        if tele is None:
            return self._execute_inner(searchers, body, scroll_ctx,
                                       continuing, task)
        # device/host stage timings (launch, readback, topk, merge, ...)
        # accumulate into node histograms on EVERY search — `profile:
        # true` only adds the per-request breakdown on top
        from elasticsearch_tpu.search import profile as _prof
        with _prof.stage_sink(tele.stage_sink()):
            return self._execute_inner(searchers, body, scroll_ctx,
                                       continuing, task)

    def _execute_inner(self, searchers: List[Tuple[str, ShardSearcher]],
                       body: Dict[str, Any],
                       scroll_ctx: Optional[ScrollContext] = None,
                       continuing: bool = False, task=None
                       ) -> Dict[str, Any]:
        body = body or {}
        from elasticsearch_tpu.search.percolate import resolve_percolate_refs
        query_spec = body.get("query")
        if query_spec:
            query_spec = resolve_percolate_refs(query_spec,
                                                self.indices_service)
        if body.get("post_filter"):
            body = dict(body)
            body["post_filter"] = resolve_percolate_refs(
                body["post_filter"], self.indices_service)
        query = parse_query(query_spec) if query_spec else MatchAllQuery()
        slice_spec = body.get("slice")
        if slice_spec is not None:
            # sliced scroll: disjoint id-hash partitions (ref: SliceBuilder)
            from elasticsearch_tpu.search.queries import SliceQuery
            query = SliceQuery(int(slice_spec.get("id", 0)),
                               int(slice_spec.get("max", 1)), query)
        if searchers:
            # coordinator-level rewrite: doc-resolving queries (e.g.
            # more_like_this) see ALL shards' segments, not just one
            # shard's (ref: the reference resolves like-docs with index
            # routing before the shard fan-out)
            query = query.rewrite(_CoordinatorRewriteContext(
                [s for _, s in searchers]))
        post_filter = (parse_query(body["post_filter"])
                       if body.get("post_filter") else None)
        size = int(body.get("size", DEFAULT_SIZE))
        from_ = int(body.get("from", 0))
        if from_ + size > 10000 and scroll_ctx is None:
            raise IllegalArgumentException(
                "Result window is too large, from + size must be less than "
                "or equal to: [10000]. Use the scroll API or search_after")
        sort = body.get("sort")
        min_score = body.get("min_score")
        search_after = body.get("search_after")
        # Default: exact totals (a stronger guarantee than the
        # reference's 10,000 threshold). An EXPLICIT int threshold or
        # false licenses block-max pruned collection, exactly as
        # Lucene's TOP_SCORES mode only engages under a total-hits
        # threshold — totals then become lower bounds ("gte"); keeping
        # the default exact preserves the reference's
        # exact-below-threshold contract in every default-path response.
        track_total = body.get("track_total_hits", True)
        highlight = body.get("highlight")
        aggs_spec = body.get("aggs", body.get("aggregations"))
        collect_masks = bool(aggs_spec) and not continuing
        rescore_spec = body.get("rescore")
        if rescore_spec is not None:
            if sort is not None:
                raise IllegalArgumentException(
                    "Cannot use [sort] option in conjunction with [rescore].")
            if isinstance(rescore_spec, dict):
                rescore_spec = [rescore_spec]
        collapse_field = (body.get("collapse") or {}).get("field")
        profile = bool(body.get("profile"))
        terminate_after = body.get("terminate_after")

        k = from_ + size if scroll_ctx is None else size
        # rescore windows may exceed the page size (ref: RescorePhase
        # collects max(window_size) docs per shard)
        query_k = k
        if rescore_spec:
            query_k = max(k, max(int(r.get("window_size", 10))
                                 for r in rescore_spec))
        if collapse_field:
            # over-collect so enough distinct groups survive the collapse
            query_k = max(query_k, k * 5)

        # ---- mesh fast path: a multi-shard single-index query with no
        # aggs/sort/rescore runs as ONE shard_map program over the device
        # mesh — fan-out and merge in a single launch (mesh_executor.py).
        # `profile: true` rides along: the launch records one pseudo-shard
        # entry with per-chip device attribution (mesh_shape + devices)
        mesh_docs = None
        mesh_total = 0
        mesh_profile_entry = None
        if (scroll_ctx is None and not continuing and post_filter is None
                and sort is None and min_score is None
                and search_after is None and not aggs_spec
                and not rescore_spec and not collapse_field
                and terminate_after is None and slice_spec is None
                and len(searchers) > 1
                and len({n for n, _ in searchers}) == 1):
            from elasticsearch_tpu.search import profile as _prof
            mesh_cm = None
            mesh_rec: Dict[str, Any] = {}
            t0_mesh = time.monotonic_ns()
            if profile:
                mesh_cm = _prof.profiling()
                mesh_rec = mesh_cm.__enter__()
            try:
                mr = self.mesh_executor.execute(
                    searchers[0][0], [s for _, s in searchers], query, k)
            except Exception:  # noqa: BLE001 — mesh is an optimization
                # the backend contract is "clean fallback, never an
                # error": any mesh failure (slab upload OOM, device
                # fault) logs, counts, and the per-shard loop — which
                # served this query before the mesh existed — answers
                import logging
                logging.getLogger(__name__).exception(
                    "mesh serving failed; using the per-shard loop")
                self.mesh_executor._fallback("error")
                mr = None
            finally:
                if mesh_cm is not None:
                    mesh_cm.__exit__(None, None, None)
            if mr is not None:
                mesh_docs, mesh_total = mr
                if profile:
                    mesh_profile_entry = _prof.shard_profile_tree(
                        f"[{searchers[0][0]}][_mesh]", body, mesh_rec,
                        time.monotonic_ns() - t0_mesh)

        # ---- query phase: fan out over shards (ref:
        # AbstractSearchAsyncAction.run / SearchPhaseController merge)
        shard_results: List[Tuple[str, ShardSearcher, QueryResult]] = []
        profile_shards: List[Dict[str, Any]] = []
        if mesh_profile_entry is not None:
            profile_shards.append(mesh_profile_entry)
        # per-shard failure capture (ref: the per-shard halves of
        # AbstractSearchAsyncAction.onShardFailure collapsed in-process):
        # a failing shard becomes a typed `_shards.failures` entry instead
        # of sinking the whole request — unless every shard failed, or the
        # request set allow_partial_search_results=false
        shard_failures: List[Dict[str, Any]] = []
        first_failure: Optional[BaseException] = None
        index_shard_ord: Dict[str, int] = {}   # per-INDEX shard numbering
        total = 0
        max_score = None
        for shard_idx, (index_name, searcher) in enumerate(
                [] if mesh_docs is not None else searchers):
            shard_ord = index_shard_ord.get(index_name, 0)
            index_shard_ord[index_name] = shard_ord + 1
            searcher.batcher = self.plan_batcher
            if task is not None:
                # cooperative cancellation between shard executions (ref:
                # CancellableTask checks in ContextIndexSearcher)
                task.ensure_not_cancelled()
            after_key = (scroll_ctx.cursors.get(shard_idx)
                         if (scroll_ctx is not None and continuing) else None)
            shard_span = None
            if self.telemetry is not None:
                from elasticsearch_tpu.telemetry import context as _telectx
                if _telectx.current() is not None:
                    # parented to the REST-boundary root span via the
                    # ambient context (telemetry/context.py)
                    shard_span = self.telemetry.tracer.start_span(
                        f"shard[{index_name}][{shard_ord}]",
                        tags={"phase": "query", "outcome": "ok"})
            t0 = time.monotonic_ns()
            prof_cm = None
            prof_rec = {}
            churn0 = (0, 0)
            if profile:
                from elasticsearch_tpu.search import profile as _prof
                prof_cm = _prof.profiling()
                prof_rec = prof_cm.__enter__()
                churn0 = searcher.cache.churn_counters()
            if scroll_ctx is None and slice_spec is None:
                # stable plan-cache key: the raw query/post_filter JSON —
                # repeat queries skip compile AND bind (searcher.py)
                try:
                    plan_cache_key = json.dumps(
                        [body.get("query"), body.get("post_filter")],
                        sort_keys=True, default=str)
                except (TypeError, ValueError):
                    plan_cache_key = None
            else:
                plan_cache_key = None
            cancel_cm = None
            stage_cm = None
            if task is not None:
                # the profile stage seam doubles as the device-launch
                # cancellation poll: a cancel mid-scan aborts between
                # launches of a multi-segment shard, not after it
                from elasticsearch_tpu.search import profile as _prof
                cancel_cm = _prof.cancellable(task.ensure_not_cancelled)
                cancel_cm.__enter__()
                # publish the task's CURRENT profile stage (ambient
                # `profile.record` context) so `_tasks?detailed=true`
                # shows WHERE a long-running search is
                stage_cm = _prof.stage_hook(
                    lambda st: setattr(task, "profile_stage", st))
                stage_cm.__enter__()
            try:
                result = searcher.query_phase(
                    query, query_k, post_filter=post_filter,
                    min_score=min_score,
                    sort=sort, search_after=search_after,
                    # raw value (bool OR int threshold): thresholded
                    # totals license block-max pruning down in the plan
                    # executor
                    track_total_hits=(track_total if not continuing
                                      else False),
                    after_key=after_key, collect_masks=collect_masks,
                    # scroll pages must stay on ONE executor: plan-path
                    # and dense-path float32 sums differ in the last
                    # bits, so a cursor taken from one would re-emit/
                    # skip boundary docs when continued on the other
                    allow_plan=scroll_ctx is None,
                    cache_key=plan_cache_key)
                if terminate_after:
                    # the shard "stops collecting" after terminate_after
                    result.docs[:] = result.docs[: int(terminate_after)]
                if rescore_spec:
                    result.docs[:] = searcher.rescore(result.docs,
                                                      rescore_spec)
            except TaskCancelledException:
                raise
            except Exception as e:  # noqa: BLE001 — per-shard fault barrier
                if first_failure is None:
                    first_failure = e
                if shard_span is not None:
                    shard_span.tag("outcome", "failed")
                    shard_span.tag("error_type", error_type_of(e))
                shard_failures.append({
                    "shard": shard_ord, "index": index_name, "node": None,
                    "reason": {"type": error_type_of(e),
                               "reason": str(e), "phase": "query"}})
                # an empty stand-in keeps shard_results aligned with the
                # searcher list (scroll cursors key on this index)
                result = QueryResult([], 0, None)
            finally:
                if stage_cm is not None:
                    stage_cm.__exit__(None, None, None)
                if cancel_cm is not None:
                    cancel_cm.__exit__(None, None, None)
                if prof_cm is not None:
                    prof_cm.__exit__(None, None, None)
                if shard_span is not None:
                    shard_span.finish()
            if profile:
                from elasticsearch_tpu.search import profile as _prof
                total_ns = time.monotonic_ns() - t0
                adm, ev = searcher.cache.churn_counters()
                if adm - churn0[0] or ev - churn0[1]:
                    # HBM churn observed during this shard's query
                    # window: segment uploads admitted (cold shard /
                    # evicted resident) and the LRU evictions the
                    # admission forced. Node-wide counter delta — a
                    # concurrent query's uploads can land in it.
                    counters = prof_rec.setdefault("_counters", {})
                    counters["hbm_admissions"] = adm - churn0[0]
                    counters["hbm_evictions"] = ev - churn0[1]
                profile_shards.append(_prof.shard_profile_tree(
                    f"[{index_name}][{shard_idx}]", body, prof_rec,
                    total_ns))
            shard_results.append((index_name, searcher, result))
            total += result.total_hits
            if result.max_score is not None:
                max_score = (result.max_score if max_score is None
                             else max(max_score, result.max_score))

        if shard_failures:
            if len(shard_failures) == len(shard_results) \
                    and first_failure is not None:
                # all shards failed: surface the root cause unchanged
                # (ref: SearchPhaseExecutionException wraps, but the REST
                # status comes from the cause)
                raise first_failure
            from elasticsearch_tpu.common.settings import parse_boolean
            allow_partial = parse_boolean(
                body.get("allow_partial_search_results"),
                parse_boolean(self.cluster_settings().get(
                    "search.default_allow_partial_results"), True,
                    key="search.default_allow_partial_results"),
                key="allow_partial_search_results")
            if not allow_partial:
                raise SearchPhaseExecutionException(
                    "query",
                    f"{len(shard_failures)} of {len(shard_results)} "
                    "shards failed and [allow_partial_search_results] "
                    "is false", shard_failures)

        # the between-phases cancellation poll: a search cancelled after
        # the query phase must not run the merge/fetch work
        if task is not None:
            task.ensure_not_cancelled()

        # ---- merge (score desc / sort key, then shard order, then docid)
        merged: List[Tuple[float, int, DocAddress, str, ShardSearcher]] = []
        for shard_idx, (index_name, searcher, result) in enumerate(shard_results):
            for d in result.docs:
                merged.append((d.sort_key, shard_idx, d, index_name, searcher))
        from elasticsearch_tpu.search.searcher import (_host_sort_cmp,
                                                       _parse_sort)
        sort_spec = _parse_sort(sort)
        if sort_spec is not None and any(d.sort_values
                                         for _, _, d, _, _ in merged):
            # compare real per-doc sort values (strings included) — the
            # numeric device sort_key is shard-LOCAL for keyword ordinals
            # (ref: SearchPhaseController.mergeTopDocs compares FieldDoc
            # values, not shard-internal keys)
            import functools

            def entry_cmp(a, b):
                c = _host_sort_cmp(a[2], b[2], sort_spec)
                if c:
                    return c
                return -1 if a[1] < b[1] else (1 if a[1] > b[1] else 0)

            merged.sort(key=functools.cmp_to_key(entry_cmp))
        elif len(shard_results) > 1:
            merged.sort(key=lambda e: (-e[0], e[1], e[2].segment_idx,
                                       e[2].docid))
        # single shard: per-shard results are already in final
        # (-score, segment, docid) order — no re-sort needed

        if mesh_docs is not None:
            # already merged on-device (all_gather + re-top-k); shards
            # hold exactly one segment on this path
            mesh_index = searchers[0][0]
            merged = [
                (score, shard_idx,
                 DocAddress(seg_idx, docid, score, (), sort_key=score),
                 mesh_index, searchers[shard_idx][1])
                for shard_idx, seg_idx, docid, score in mesh_docs]
            total = mesh_total if track_total else 0
            max_score = merged[0][0] if merged else None

        # ---- field collapsing (ref: collapse/CollapseBuilder + coordinator
        # keeping the best hit per group): first hit per key wins; docs
        # missing the key form a single null group
        if collapse_field:
            seen_keys = set()
            collapsed = []
            for entry in merged:
                _, _, d, _, searcher = entry
                key = _doc_field_value(searcher, d, collapse_field)
                hashable = key if not isinstance(key, list) else tuple(key)
                if hashable in seen_keys:
                    continue
                seen_keys.add(hashable)
                collapsed.append(entry)
            merged = collapsed
        page = merged[from_:from_ + size] if scroll_ctx is None else merged[:size]

        # update scroll cursors with the last emitted doc per shard
        if scroll_ctx is not None:
            for key, shard_idx, d, _, _ in page:
                # carry the real primary sort value too: keyword sort keys
                # are segment-local ordinals, so continuation re-ranks the
                # cursor TERM per segment (searcher._keyword_after_masks)
                scroll_ctx.cursors[shard_idx] = (
                    key, d.segment_idx, d.docid,
                    d.sort_values[0] if d.sort_values else None)

        # ---- fetch phase on winners only (ref: FetchSearchPhase.java:104)
        if task is not None:
            task.profile_stage = "fetch"
        hits = []
        source_filter = body.get("_source", True)
        docvalue_fields = [f if isinstance(f, str) else f.get("field")
                           for f in body.get("docvalue_fields", [])]
        script_fields = body.get("script_fields")
        fields_spec = body.get("fields")
        # group page docs by shard so per-request work (script-field
        # columns, highlighters) runs once per shard, not once per hit
        by_shard: Dict[int, List[Tuple[int, DocAddress]]] = {}
        shard_info: Dict[int, Tuple[str, ShardSearcher]] = {}
        for pos, (_, shard_idx, d, index_name, searcher) in enumerate(page):
            by_shard.setdefault(shard_idx, []).append((pos, d))
            shard_info[shard_idx] = (index_name, searcher)
        hits_by_pos: Dict[int, Dict[str, Any]] = {}
        fetch_ns: Dict[int, int] = {}
        fetch_span = None
        if self.telemetry is not None and by_shard:
            from elasticsearch_tpu.telemetry import context as _telectx
            if _telectx.current() is not None:
                fetch_span = self.telemetry.tracer.start_span(
                    "fetch", tags={"shards": len(by_shard)})
        try:
            for shard_idx, entries in by_shard.items():
                index_name, searcher = shard_info[shard_idx]
                docs = [d for _, d in entries]
                fetch_t0 = time.monotonic_ns()
                fetched_list = searcher.fetch_phase(
                    docs, source_filter=source_filter,
                    docvalue_fields=docvalue_fields or None,
                    highlight=highlight, highlight_query=query,
                    script_fields=script_fields, fields=fields_spec,
                    version=bool(body.get("version")),
                    seq_no_primary_term=bool(
                        body.get("seq_no_primary_term")))
                fetch_ns[shard_idx] = time.monotonic_ns() - fetch_t0
                for (pos, d), fetched in zip(entries, fetched_list):
                    fetched["_index"] = index_name
                    if collapse_field:
                        key = _doc_field_value(searcher, d, collapse_field)
                        fetched.setdefault("fields", {})[collapse_field] = (
                            key if isinstance(key, list) else [key])
                    hits_by_pos[pos] = fetched
        finally:
            if fetch_span is not None:
                fetch_span.finish()
        hits = [hits_by_pos[i] for i in sorted(hits_by_pos)]
        # query-computed hit decorations (percolator document slots) — the
        # percolate query may be nested inside compounds
        decorators = _collect_decorators(query)
        if post_filter is not None:
            decorators = decorators + _collect_decorators(post_filter)
        for q in decorators:
            for hit in hits:
                q.add_hit_fields(hit)

        # ---- aggregation phase (ref: AggregationPhase; reduce is trivial
        # here since all shards are in-process — masks concatenate)
        aggregations = None
        if collect_masks and searchers:
            from elasticsearch_tpu.search.aggregations import compute_aggs
            # each segment carries its own index's mapper (multi-index aggs)
            agg_ctx = []
            for _, searcher, result in shard_results:
                for seg, mask in (result.agg_masks or []):
                    agg_ctx.append((seg, mask, searcher.mapper))
            default_mapper = searchers[0][1].mapper
            cache = searchers[0][1].cache
            # empty index still yields empty/null agg results (never a
            # missing "aggregations" key)
            if task is not None:
                task.profile_stage = "aggs.reduce"
            t_agg = time.monotonic()
            aggregations = compute_aggs(aggs_spec, agg_ctx, default_mapper,
                                        cache)
            agg_ns = int((time.monotonic() - t_agg) * 1e9)
            if self.telemetry is not None:
                # the same search.agg_reduce.* surface the distributed
                # coordinator feeds (search/agg_partials.py consumer) —
                # in-process shards reduce as ONE batch, family "_all"
                # (the tree computes in one pass here; true per-family
                # latencies come from the coordinator's consumer)
                m = self.telemetry.metrics
                m.inc("search.agg_reduce.partials", len(shard_results))
                m.inc("search.agg_reduce.batches")
                m.observe("search.agg_reduce.latency",
                          (time.monotonic() - t_agg) * 1000.0,
                          family="_all")

        # ---- suggest phase (ref: SuggestPhase, search/suggest/)
        suggest = None
        if body.get("suggest"):
            from elasticsearch_tpu.search.suggest import compute_suggest
            suggest = compute_suggest(body["suggest"], searchers)

        relation = "eq"
        if any(r.total_lower_bound for _, _, r in shard_results):
            # block-max pruning ran: the counted total is a lower bound
            relation = "gte"
        if scroll_ctx is not None:
            if continuing:
                total = scroll_ctx.total_hits
            else:
                scroll_ctx.total_hits = total
        if isinstance(track_total, int) and not isinstance(track_total, bool):
            if total > track_total:
                total = track_total
                relation = "gte"
        terminated_early = None
        if terminate_after:
            # per-shard early termination semantics (ref: EarlyTerminating-
            # Collector): each shard reports at most terminate_after docs;
            # execution here is dense, so only the counts are clamped —
            # never below the number of hits actually returned
            ta = int(terminate_after)
            clamped = sum(min(r.total_hits, ta) for _, _, r in shard_results)
            terminated_early = any(r.total_hits > ta
                                   for _, _, r in shard_results)
            if terminated_early:
                total = clamped
                relation = "gte"
        n_failed = min(len(shard_failures), len(searchers))
        shards_section = {"total": len(searchers),
                          "successful": len(searchers) - n_failed,
                          "skipped": 0, "failed": n_failed}
        if shard_failures:
            shards_section["failures"] = shard_failures
        response = {
            "timed_out": False,
            "_shards": shards_section,
            "hits": {
                "total": {"value": total, "relation": relation},
                "max_score": max_score,
                "hits": hits,
            },
        }
        if track_total is False:
            # ES omits hits.total entirely when tracking is disabled
            del response["hits"]["total"]
        if terminated_early is not None:
            response["terminated_early"] = terminated_early
        if aggregations is not None:
            response["aggregations"] = aggregations
        if suggest is not None:
            response["suggest"] = suggest
        if profile:
            # per-shard fetch timing (ref: FetchProfiler — the fetch
            # phase reports its own breakdown since 7.16)
            for si, entry in enumerate(profile_shards):
                if si in fetch_ns:
                    entry["fetch"] = {
                        "type": "fetch",
                        "description": "",
                        "time_in_nanos": fetch_ns[si],
                        "breakdown": {"load_stored_fields": fetch_ns[si]},
                    }
            # the single-node service is a collapsed coordinator: the
            # profile section carries the SAME shape the distributed
            # path merges (shards + coordinator phases + trace.id), so
            # clients parse one format (ref: SearchProfileResults
            # shards map merged coordinator-side)
            coord: Dict[str, Any] = {"phases": {
                "query_ns": sum(
                    e["searches"][0]["query"][0]["time_in_nanos"]
                    for e in profile_shards),
                "fetch_ns": sum(fetch_ns.values()),
            }}
            if aggregations is not None:
                coord["phases"]["aggs_ns"] = agg_ns
                coord["reduce_batches"] = 1
            response["profile"] = {"shards": profile_shards,
                                   "coordinator": coord}
            from elasticsearch_tpu.telemetry import context as _telectx
            ambient = _telectx.current()
            if ambient is not None:
                # profile ↔ trace cross-link: the profiled request's
                # trace resolves via GET /_traces/{id}
                response["profile"]["trace.id"] = ambient.trace_id
        return response

    # ------------------------------------------------------------ explain
    def explain(self, index: str, doc_id: str,
                body: Dict[str, Any]) -> Dict[str, Any]:
        """_explain API (ref: action/explain/TransportExplainAction): run
        the query against the shard holding the doc and report its score."""
        names = self.indices_service.resolve(index)
        query = (parse_query(body["query"]) if body.get("query")
                 else MatchAllQuery())
        for name in names:
            idx = self.indices_service.get(name)
            for searcher in idx.shard_searchers():
                q = query.rewrite(searcher)
                for seg_idx, seg in enumerate(searcher.segments):
                    d = seg.docid_for(doc_id)
                    if d < 0:
                        continue
                    contexts = searcher._contexts()
                    import numpy as _np
                    scores, mask = q.execute(contexts[seg_idx])
                    matched = bool(_np.asarray(mask)[d])
                    score = float(_np.asarray(scores)[d]) if matched else 0.0
                    return {
                        "_index": name, "_id": doc_id, "matched": matched,
                        "explanation": {
                            "value": score,
                            "description": ("sum of BM25 term scores "
                                            "(TPU dense kernel)" if matched
                                            else "no matching term"),
                            "details": [],
                        },
                    }
        return {"_index": names[0] if names else index, "_id": doc_id,
                "matched": False,
                "explanation": {"value": 0.0,
                                "description": "document not found",
                                "details": []}}

    def count(self, index_expression: str, body: Dict[str, Any]) -> Dict[str, Any]:
        body = dict(body or {})
        body["size"] = 0
        body.pop("sort", None)
        body["track_total_hits"] = True   # _count is always exact
        response = self.search(index_expression, body)
        return {"count": response["hits"]["total"]["value"],
                "_shards": response["_shards"]}


def resumable_scroll_batches(search_service, index_expression: str,
                             body: Dict[str, Any], batch_size: int,
                             keep_alive: str = "5m", task=None,
                             on_resume=None):
    """Drain ``index_expression`` in batches via scroll, SURVIVING a lost
    scroll context (ref: ClientScrollableHitSource + the bulk-by-scroll
    retry contract): a ``search_context_missing_exception`` mid-drain
    re-opens the scroll and resumes from the last continuation point
    instead of restarting the caller's whole operation.

    Resume exactness: with an explicit ``sort`` in the body the stream
    re-opens at ``search_after = <last emitted hit's sort>`` — exact on
    any copy. Without one (score order is not portable across readers)
    the re-opened stream skips the already-emitted prefix by count —
    exact against a deterministic reader, best-effort otherwise.

    ``on_resume`` (optional) is called once per recovery, for metrics.
    Works against any service exposing the sync search/scroll/
    clear_scroll surface (the single-node SearchService shape).
    """
    base = dict(body or {})
    base["size"] = int(batch_size)
    has_sort = bool(base.get("sort"))
    emitted = 0
    last_sort = None
    skip = 0

    def reopen():
        b = dict(base)
        if has_sort and last_sort is not None:
            b["search_after"] = list(last_sort)
        return search_service.search(index_expression, b,
                                     scroll=keep_alive, task=task)

    r = search_service.search(index_expression, dict(base),
                              scroll=keep_alive, task=task)
    scroll_id = r.get("_scroll_id")
    try:
        while True:
            raw_hits = r["hits"]["hits"]
            hits = raw_hits
            if skip:
                drop = min(skip, len(hits))
                hits = hits[drop:]
                skip -= drop
            if hits:
                emitted += len(hits)
                if hits[-1].get("sort") is not None:
                    last_sort = hits[-1]["sort"]
                yield hits
            if not raw_hits:
                return
            try:
                r = search_service.scroll(scroll_id, keep_alive)
                scroll_id = r.get("_scroll_id", scroll_id)
            except SearchContextMissingException:
                if on_resume is not None:
                    on_resume()
                if has_sort and last_sort is not None:
                    skip = 0
                else:
                    # restart from the top, skipping what was already
                    # handed out
                    skip = emitted
                r = reopen()
                scroll_id = r.get("_scroll_id")
    finally:
        if scroll_id:
            try:
                search_service.clear_scroll([scroll_id])
            except Exception:  # noqa: BLE001 — release is best-effort:
                # an expired/unknown id means the context is gone anyway
                pass
