"""Query-plan compiler: QueryBuilder trees → fused top-k kernel plans.

The serving-path replacement for the dense (scores, mask) execution model
(ref: the reference compiles QueryBuilder → Lucene Weight/BulkScorer,
search/internal/ContextIndexSearcher.java:196-232; here the analogous
compilation target is ops/plan.py's sorted segmented-reduction kernel).

A query is *plannable* when it decomposes into:
- postings **groups** — clauses scored/filtered from a text/keyword field's
  postings (match, multi_match, term, terms, constant_score over those),
  each with its own presence requirement (operator=and /
  minimum_should_match inside the clause);
- **dense factors** — pure column predicates (range, exists, ids,
  numeric/date/bool term(s), match_all) whose masks are vectorized
  compares with no scatter anywhere;
composed by at most one level of bool occur semantics (must / filter /
should / must_not + minimum_should_match), or a top-level dis_max /
multi_match over plannable children.

Everything else (scripts, nested bools, positional queries, aggs paths)
falls back to the dense executor — kept for when a full [ND] score vector
is semantically required.

Compilation happens once per shard (terms analyzed, idf from shard-level
stats — exactly the stats the dense path uses); binding resolves term →
postings-block ids per segment.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.index.mapper import (
    ConstantKeywordFieldType,
    KeywordFieldType,
    TextFieldType,
)
from elasticsearch_tpu.ops import bm25 as bm25_ops
from elasticsearch_tpu.ops import plan as plan_ops
from elasticsearch_tpu.ops.device import block_bucket
from elasticsearch_tpu.search import queries as q

NAN = float("nan")
_NEVER = 1 << 30  # requirement no group can meet (pad groups)

# Floor for the selected-block bucket (powers of two above it). Serving
# deployments raise it to collapse the distinct compiled shapes — each
# (bucket, k) pair is one XLA compile (~20-40s on TPU first time).
MIN_PLAN_BUCKET = 0


@dataclass
class TermEntry:
    field: str
    term: str
    sub: int          # subgroup id within the group
    weight: float     # idf · boost (0 for pure-presence entries)
    const: bool       # constant-per-match contribution (keyword scoring)


@dataclass
class GroupPlan:
    kind: int                     # plan_ops.MUST / SHOULD / FILTER / MUST_NOT
    req: int                      # distinct subgroups required for presence
    const_score: float            # NaN = sum of contributions
    terms: List[TermEntry] = dc_field(default_factory=list)


@dataclass
class LogicalPlan:
    groups: List[GroupPlan]
    dense: List[Tuple[Any, bool]]         # (QueryBuilder, negate)
    n_must: int                           # postings MUST groups
    n_filter: int                         # postings FILTER groups
    msm: int
    bonus: float                          # constant score of dense must/
                                          # constant clauses every hit gets
    combine: str = "sum"
    tie: float = 0.0

    def postings_required(self) -> bool:
        """True iff every passing doc must match ≥1 postings group — the
        kernel can only see docs that appear in the gathered postings."""
        return self.n_must >= 1 or self.n_filter >= 1 or self.msm >= 1


# ---------------------------------------------------------------------------
# clause classification
# ---------------------------------------------------------------------------

def _is_postings_field(mapper, field: str) -> bool:
    ft = mapper.field_type(field)
    if isinstance(ft, ConstantKeywordFieldType):
        return False
    return (ft is None or isinstance(ft, (TextFieldType, KeywordFieldType))
            or getattr(ft, "docvalue_kind", None) == "flattened")


def _is_dense_clause(node, mapper) -> bool:
    """Clauses whose do_execute builds masks from dense columns only —
    no postings scatter anywhere (range/exists/ids/match_all and term(s)
    on numeric/date/bool/constant_keyword/range fields)."""
    if isinstance(node, (q.RangeQuery, q.ExistsQuery, q.IdsQuery,
                         q.MatchAllQuery)):
        return True
    if isinstance(node, (q.TermQuery, q.TermsQuery)):
        return not _is_postings_field(mapper, node.field)
    return False


def _analyze(searcher, field: str, text: str) -> List[str]:
    # the dense executor's analysis, verbatim — one tokenization for both
    # paths (queries._analyze_terms only reads .mapper, which ShardSearcher
    # exposes just like SegmentContext)
    return q._analyze_terms(searcher, field, text)


def _idf(searcher, field: str, term: str) -> float:
    doc_count, _ = searcher.stats.field_stats(field)
    df = searcher.stats.doc_freq(field, term)
    return bm25_ops.idf(df, doc_count) if df > 0 else 0.0


# ---------------------------------------------------------------------------
# per-clause group builders (return None when not plannable)
# ---------------------------------------------------------------------------

def _group_for_match(node: "q.MatchQuery", searcher, kind: int,
                     scale: float) -> Optional[GroupPlan]:
    if not _is_postings_field(searcher.mapper, node.field):
        return None
    terms = _analyze(searcher, node.field, node.query)
    if not terms:
        return None  # matches nothing; dense fallback returns empty fast
    uniq = {t: i for i, t in enumerate(sorted(set(terms)))}
    if node.operator == "and":
        req = len(uniq)
    elif node.minimum_should_match:
        # parsed over the token count (duplicates included), clamped to the
        # distinct-term count; ≤1 means "any term" — exactly the dense
        # path's required/need computation (queries.MatchQuery.do_execute)
        r = q.parse_minimum_should_match(
            node.minimum_should_match, len(terms))
        req = 1 if r <= 1 else min(r, len(uniq))
    else:
        req = 1
    g = GroupPlan(kind, req, NAN)
    for t in terms:  # duplicates kept: they double the contribution, as in
        # the dense path (select_blocks extends per occurrence)
        g.terms.append(TermEntry(node.field, t, uniq[t],
                                 _idf(searcher, node.field, t) * scale,
                                 False))
    return g


def _group_for_term(node: "q.TermQuery", searcher, kind: int,
                    scale: float) -> Optional[GroupPlan]:
    mapper = searcher.mapper
    if not _is_postings_field(mapper, node.field):
        return None
    ft = mapper.field_type(node.field)
    term = str(node.value)
    if isinstance(ft, TextFieldType):
        g = GroupPlan(kind, 1, NAN)
        g.terms.append(TermEntry(node.field, term,
                                 0, _idf(searcher, node.field, term) * scale,
                                 False))
        return g
    # keyword/unmapped/flattened: constant score idf·1/(1+k1), no norms
    # (ref: Lucene keyword fields omit norms; see queries.TermQuery)
    const = _idf(searcher, node.field, term) / (1.0 + searcher.k1) * scale
    g = GroupPlan(kind, 1, const)
    g.terms.append(TermEntry(node.field, term, 0, 0.0, False))
    return g


def _group_for_terms(node: "q.TermsQuery", searcher, kind: int,
                     scale: float) -> Optional[GroupPlan]:
    if not _is_postings_field(searcher.mapper, node.field):
        return None
    g = GroupPlan(kind, 1, 1.0 * scale)   # constant_score(1.0) any-of
    for v in node.values:
        g.terms.append(TermEntry(node.field, str(v), 0, 0.0, False))
    return g


def _group_for_clause(node, searcher, kind: int,
                      scale: float) -> Optional[GroupPlan]:
    scale = scale * getattr(node, "boost", 1.0)
    if isinstance(node, q.MatchQuery):
        return _group_for_match(node, searcher, kind, scale)
    if isinstance(node, q.TermQuery):
        return _group_for_term(node, searcher, kind, scale)
    if isinstance(node, q.TermsQuery):
        return _group_for_terms(node, searcher, kind, scale)
    if isinstance(node, q.ConstantScoreQuery):
        inner = _group_for_clause(node.filter_query, searcher, kind, 1.0)
        if inner is None:
            return None
        inner.kind = kind
        inner.const_score = 1.0 * scale   # score is the boost, not BM25
        for t in inner.terms:
            t.weight = 0.0
        return inner
    return None


# ---------------------------------------------------------------------------
# top-level compilation
# ---------------------------------------------------------------------------

def compile_plan(query, searcher,
                 post_filter=None) -> Optional[LogicalPlan]:
    """Compile a rewritten query (+ optional post_filter folded in as a
    filter — valid when no aggregations run) into a LogicalPlan, or None
    when the tree needs the dense executor."""
    plan = _compile_tree(query, searcher)
    if plan is None:
        return None
    if post_filter is not None:
        g = _group_for_clause(post_filter, searcher, plan_ops.FILTER, 1.0)
        if g is not None:
            g.const_score = NAN
            plan.groups.append(g)
            plan.n_filter += 1
        elif _is_dense_clause(post_filter, searcher.mapper):
            plan.dense.append((post_filter, False))
        else:
            return None
    if not plan.postings_required():
        return None
    # negative boosts would feed negative contributions into the kernel's
    # cumsum/cummax segmented sums (which require x >= 0) — dense fallback
    if plan.bonus < 0:
        return None
    for g in plan.groups:
        if any(t.weight < 0 for t in g.terms):
            return None
        if not math.isnan(g.const_score) and g.const_score < 0:
            return None
    return plan


def _compile_tree(query, searcher) -> Optional[LogicalPlan]:
    boost = getattr(query, "boost", 1.0)
    if isinstance(query, q.BoolQuery):
        return _compile_bool(query, searcher, boost)
    if isinstance(query, q.MultiMatchQuery):
        return _compile_multi_match(query, searcher, boost)
    if isinstance(query, q.DisMaxQuery):
        return _compile_dismax(query, searcher, boost)
    g = _group_for_clause(query, searcher, plan_ops.MUST, 1.0)
    if g is not None:
        # top-level boost is inside the group scale already via
        # _group_for_clause's getattr(node, "boost")
        return LogicalPlan([g], [], 1, 0, 0, 0.0)
    return None


def _compile_bool(node: "q.BoolQuery", searcher,
                  boost: float) -> Optional[LogicalPlan]:
    groups: List[GroupPlan] = []
    dense: List[Tuple[Any, bool]] = []
    bonus = 0.0
    n_must = n_filter = 0
    n_required_any = 0  # must+filter clauses of any kind (for msm default)

    for clause in node.must:
        g = _group_for_clause(clause, searcher, plan_ops.MUST, boost)
        if g is not None:
            groups.append(g)
            n_must += 1
        elif _is_dense_clause(clause, searcher.mapper):
            dense.append((clause, False))
            # a required constant-score clause adds its score to every hit
            # (dense masks score 1.0·boost in the dense path)
            bonus += getattr(clause, "boost", 1.0) * boost
        else:
            return None
        n_required_any += 1
    for clause in node.filter:
        g = _group_for_clause(clause, searcher, plan_ops.FILTER, 1.0)
        if g is not None:
            g.const_score = NAN   # filters never score
            groups.append(g)
            n_filter += 1
        elif _is_dense_clause(clause, searcher.mapper):
            dense.append((clause, False))
        else:
            return None
        n_required_any += 1
    for clause in node.must_not:
        g = _group_for_clause(clause, searcher, plan_ops.MUST_NOT, 1.0)
        if g is not None:
            g.const_score = NAN
            groups.append(g)
        elif _is_dense_clause(clause, searcher.mapper):
            dense.append((clause, True))
        else:
            return None
    for clause in node.should:
        g = _group_for_clause(clause, searcher, plan_ops.SHOULD, boost)
        if g is None:
            return None   # dense should-clauses: conditional +1 scoring —
            # rare; dense fallback keeps exact semantics
        groups.append(g)

    if node.minimum_should_match is None:
        msm = 1 if (node.should and n_required_any == 0) else 0
    else:
        msm = q.parse_minimum_should_match(
            node.minimum_should_match, len(node.should))
    if node.should and msm > len(node.should):
        msm = len(node.should)
    return LogicalPlan(groups, dense, n_must, n_filter, msm, bonus)


def _compile_multi_match(node: "q.MultiMatchQuery", searcher,
                         boost: float) -> Optional[LogicalPlan]:
    fields = node.fields
    if not fields or fields == ["*"]:
        fields = [name for name, ft in searcher.mapper.mapper.fields.items()
                  if isinstance(ft, TextFieldType)]
    if not fields:
        return None
    groups = []
    for f in fields:
        g = _group_for_match(q.MatchQuery(f, node.query), searcher,
                             plan_ops.SHOULD, boost)
        if g is None:
            return None
        groups.append(g)
    if node.type == "most_fields":
        return LogicalPlan(groups, [], 0, 0, 1, 0.0, combine="sum")
    if node.type == "best_fields":
        return LogicalPlan(groups, [], 0, 0, 1, 0.0, combine="dismax",
                           tie=node.tie_breaker)
    return None   # cross_fields/phrase types: dense fallback


def _compile_dismax(node: "q.DisMaxQuery", searcher,
                    boost: float) -> Optional[LogicalPlan]:
    groups = []
    for sub in node.queries:
        g = _group_for_clause(sub, searcher, plan_ops.SHOULD, boost)
        if g is None:
            return None
        groups.append(g)
    if not groups:
        return None
    return LogicalPlan(groups, [], 0, 0, 1, 0.0, combine="dismax",
                       tie=node.tie_breaker)


# ---------------------------------------------------------------------------
# per-segment binding + execution
# ---------------------------------------------------------------------------

@dataclass
class BoundPlan:
    """A LogicalPlan bound to one segment's device arrays: ready-to-launch
    kernel arguments (the per-query bytes shipped to device are just the
    selection arrays — a few hundred bytes)."""
    streams: List[plan_ops.FieldStream]
    group_kind: np.ndarray
    group_req: np.ndarray
    group_const: np.ndarray
    dense_mask: Optional[jnp.ndarray]
    n_must: int
    n_filter: int
    msm: int
    bonus: float
    tie: float
    combine: str
    empty: bool = False   # no query term exists in this segment


def bind_plan(plan: LogicalPlan, ctx) -> BoundPlan:
    """Resolve terms → block ids against one segment (ctx: SegmentContext).
    Selection arrays bucket to powers of two so NB takes O(log) distinct
    values across queries (XLA compile-cache discipline, ops/device.py)."""
    ngroups = len(plan.groups)
    by_field: Dict[str, List[Tuple[int, int, float, bool, str]]] = {}
    for gi, g in enumerate(plan.groups):
        for t in g.terms:
            by_field.setdefault(t.field, []).append(
                (gi, t.sub, t.weight, t.const, t.term))

    streams: List[plan_ops.FieldStream] = []
    any_entries = False
    for fname, entries in by_field.items():
        dp = ctx.device.postings.get(fname)
        if dp is None:
            continue
        starts: List[int] = []
        counts: List[int] = []
        egrp: List[int] = []
        esub: List[int] = []
        ew: List[float] = []
        econst: List[bool] = []
        for gi, sub, w, const, term in entries:
            tid = dp.host.term_id(term)
            if tid < 0:
                continue
            starts.append(int(dp.term_block_start[tid]))
            counts.append(int(dp.term_block_count[tid]))
            egrp.append(gi)
            esub.append(sub)
            ew.append(w)
            econst.append(const)
        if not starts:
            continue
        # vectorized range expansion (per-request host path: no Python
        # per-block loops)
        counts_np = np.asarray(counts, np.int64)
        tot = int(counts_np.sum())
        if tot == 0:
            continue
        any_entries = True
        rep = np.repeat(np.arange(len(starts)), counts_np)
        offs = (np.arange(tot, dtype=np.int64)
                - np.repeat(np.cumsum(counts_np) - counts_np, counts_np))
        n = max(block_bucket(tot), MIN_PLAN_BUCKET)
        sel = np.full(n, dp.zero_block, np.int32)
        sel[:tot] = np.asarray(starts, np.int64)[rep] + offs
        grp = np.full(n, ngroups, np.int32)   # pads: clipped; tf=0 ⇒ inert
        grp[:tot] = np.asarray(egrp, np.int32)[rep]
        sub_a = np.zeros(n, np.int32)
        sub_a[:tot] = np.asarray(esub, np.int32)[rep]
        w_a = np.zeros(n, np.float32)
        w_a[:tot] = np.asarray(ew, np.float32)[rep]
        c_a = np.zeros(n, bool)
        c_a[:tot] = np.asarray(econst, bool)[rep]
        streams.append(plan_ops.FieldStream(
            dp.block_docids, dp.block_tfs, dp.doc_lens,
            jnp.float32(ctx.stats.field_stats(fname)[1]),
            jnp.asarray(sel), jnp.asarray(grp), jnp.asarray(sub_a),
            jnp.asarray(w_a), jnp.asarray(c_a)))

    gpad = max(4, block_bucket(max(1, ngroups)) if ngroups else 4)
    kind = np.full(gpad, plan_ops.FILTER, np.int32)
    req = np.full(gpad, _NEVER, np.int32)
    const = np.full(gpad, NAN, np.float32)
    for gi, g in enumerate(plan.groups):
        kind[gi] = g.kind
        req[gi] = g.req
        const[gi] = g.const_score
    # pad groups: FILTER with unreachable req — never present, and absent
    # FILTER groups don't block (n_filter counts only real groups)

    dense_mask = None
    for clause, negate in plan.dense:
        _, m = clause.do_execute(ctx)
        m = (~m) if negate else m
        dense_mask = m if dense_mask is None else (dense_mask & m)

    return BoundPlan(streams, kind, req, const, dense_mask,
                     plan.n_must, plan.n_filter, plan.msm, plan.bonus,
                     plan.tie, plan.combine, empty=not any_entries)


def execute_bound(bp: BoundPlan, ctx, k: int, k1: float, b: float,
                  after_score: Optional[float] = None):
    """Launch the fused kernel for one segment → host (vals[k], ids[k],
    total). The device result is PACKED into one buffer so the whole
    query costs exactly one device→host readback (ops/plan.pack_result —
    a 3× latency lever under the axon tunnel's degraded-readback mode)."""
    if bp.empty:
        return (np.full(k, -np.inf, np.float32),
                np.full(k, plan_ops._SENTINEL, np.int32), 0)
    packed = plan_ops.plan_topk(
        bp.streams, bp.group_kind, bp.group_req, bp.group_const,
        ctx.live, bp.dense_mask, bp.n_must, bp.n_filter, bp.msm,
        bonus=bp.bonus, tie=bp.tie, k1=k1, b=b, k=k, combine=bp.combine,
        after_score=after_score, packed=True)
    return plan_ops.unpack_result(np.asarray(packed), k)
